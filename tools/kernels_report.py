#!/usr/bin/env python
"""Kernel-library report: registered kernels, active impls, autotune
decisions, measured-vs-roofline flags.

    python tools/kernels_report.py perf_dump.json          # from a dump
    python tools/kernels_report.py --autotune-cache ~/.cache/deeplearning4j_tpu/autotune.json
    python tools/kernels_report.py perf_dump.json --json

Reads the ``kernels`` block that ``telemetry.perf.perf_snapshot()``
embeds in every perf dump / flight-recorder black box (written by
``ops/kernels/registry.kernels_snapshot()``), the live
``perf.kernels.<name>.*`` gauges riding the dump's metrics snapshot, and
the autotune decision cache JSON (``DL4J_TPU_AUTOTUNE_CACHE``). Renders:

  - **Kernel table** — impl active on the dumping rig (fused /
    interpret / fallback), kill switch + legacy aliases, parity-pin
    presence, hand-tuned default block choice;
  - **Autotune decisions** — per (kernel, shape-sig, backend): the
    chosen blocks, whether measurement CHANGED the default (or the
    recorded reason defaults stand), replay count (proof the cache
    short-circuits re-measurement), best measured candidate times;
  - **Roofline check** — measured vs roofline ms per kernel from the
    gauges, flagging anything > 2x over its bound (the BASELINE.md
    flagging threshold).

Like the other tools/ CLIs this must stay importable WITHOUT the
package (no jax import): stdlib only.
"""
from __future__ import annotations

import argparse
import gzip
import json
import os
import sys
from typing import Dict, List, Optional

ROOFLINE_FLAG_RATIO = 2.0


def _read_text(path: str) -> str:
    with open(path, "rb") as f:
        magic = f.read(2)
    if path.endswith(".gz") or magic == b"\x1f\x8b":
        with gzip.open(path, "rt") as f:
            return f.read()
    with open(path) as f:
        return f.read()


def default_cache_path() -> str:
    p = os.environ.get("DL4J_TPU_AUTOTUNE_CACHE")
    if p:
        return p
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "deeplearning4j_tpu", "autotune.json")


def load_dump(path: str) -> dict:
    """{kernels, gauges} from a perf dump / flight-recorder dump."""
    data = json.loads(_read_text(path))
    if not isinstance(data, dict):
        raise ValueError(f"{path}: not a JSON object")
    perf = data.get("perf", data) or {}
    metrics = data.get("metrics", {}) or {}
    gauges = metrics.get("gauges", {}) or {}
    return {"kernels": perf.get("kernels", {}) or {}, "gauges": gauges}


def load_autotune(path: str) -> Dict[str, dict]:
    """decisions dict from the autotune cache file ({} when absent)."""
    try:
        data = json.loads(_read_text(path))
    except (OSError, ValueError):
        return {}
    if isinstance(data, dict) and data.get("autotune_cache") == 1:
        dec = data.get("decisions")
        if isinstance(dec, dict):
            return dec
    return {}


def _gauge(gauges: dict, name: str) -> Optional[float]:
    v = gauges.get(name)
    if isinstance(v, dict):
        v = v.get("value")
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def roofline_rows(kernels: dict, gauges: dict) -> List[dict]:
    rows = []
    names = set(kernels)
    for g in gauges:
        if g.startswith("perf.kernels.") and g.endswith(".measured_ms"):
            names.add(g[len("perf.kernels."):-len(".measured_ms")])
    for name in sorted(names):
        base = f"perf.kernels.{name}"
        measured = _gauge(gauges, f"{base}.measured_ms")
        if measured is None:
            continue
        rows.append({
            "kernel": name,
            "measured_ms": measured,
            "roofline_ms": _gauge(gauges, f"{base}.roofline_ms"),
            "vs_roofline": _gauge(gauges, f"{base}.vs_roofline"),
            "below_roofline": bool(
                _gauge(gauges, f"{base}.below_roofline") or 0.0),
        })
    return rows


def _fmt_choice(c) -> str:
    if not c:
        return "-"
    return "x".join(str(v) for v in c)


def _best_measured(rec: dict) -> str:
    ms = rec.get("measured_ms") or {}
    vals = [(v, k) for k, v in ms.items()
            if isinstance(v, (int, float)) and v == v]   # drop NaN
    if not vals:
        return "-"
    v, k = min(vals)
    return f"{v:.3f} ms @ {k}"


def render(kernels: dict, decisions: Dict[str, dict],
           gauges: dict) -> str:
    out = []
    w = out.append
    w("KERNEL LIBRARY")
    w("=" * 78)
    if kernels:
        w(f"{'kernel':<20} {'impl':<10} {'on':<3} {'pin':<4} "
          f"{'default':<10} kill switch")
        w("-" * 78)
        for name in sorted(kernels):
            row = kernels[name]
            kill = row.get("kill_env", "-")
            aliases = row.get("kill_aliases") or []
            if aliases:
                kill += " (legacy: " + ", ".join(aliases) + ")"
            w(f"{name:<20} {row.get('impl', '?'):<10} "
              f"{'y' if row.get('enabled', True) else 'N':<3} "
              f"{'yes' if row.get('has_parity_pin') else 'NO':<4} "
              f"{_fmt_choice(row.get('default_choice')):<10} {kill}")
    else:
        w("  (no kernels block in the dump — pass a perf dump written "
          "by telemetry.write_perf_dump)")
    w("")
    w("AUTOTUNE DECISIONS")
    w("=" * 78)
    if decisions:
        for key in sorted(decisions):
            rec = decisions[key]
            parts = key.split("|")
            kern, sig, backend = (parts + ["?", "?", "?"])[:3]
            chose = _fmt_choice(rec.get("choice"))
            dflt = _fmt_choice(rec.get("default"))
            tag = ("CHANGED default " + dflt
                   if rec.get("changed_default") else f"default {dflt}")
            w(f"  {kern} [{sig} @ {backend}] -> {chose}  ({tag}, "
              f"replays={rec.get('replays', 0)})")
            why = rec.get("why")
            if why:
                w(f"      why: {why}")
            best = _best_measured(rec)
            if best != "-":
                w(f"      best measured: {best}")
    else:
        w("  (no cached decisions)")
    w("")
    w("MEASURED VS ROOFLINE")
    w("=" * 78)
    rows = roofline_rows(kernels, gauges)
    if rows:
        for r in rows:
            flag = "  << BELOW ROOFLINE (>2x over bound)" \
                if r["below_roofline"] else ""
            roof = (f"{r['roofline_ms']:.4f}"
                    if r["roofline_ms"] is not None else "?")
            ratio = (f"{r['vs_roofline']:.2f}x"
                     if r["vs_roofline"] is not None else "?")
            w(f"  {r['kernel']:<20} measured {r['measured_ms']:.4f} ms  "
              f"roofline {roof} ms  ({ratio}){flag}")
    else:
        w("  (no perf.kernels.* timing gauges in the dump)")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dump", nargs="?", default=None,
                    help="perf dump / flight-recorder JSON (optional)")
    ap.add_argument("--autotune-cache", default=None,
                    help="autotune cache JSON (default: "
                         "$DL4J_TPU_AUTOTUNE_CACHE or "
                         "~/.cache/deeplearning4j_tpu/autotune.json)")
    ap.add_argument("--json", action="store_true",
                    help="emit the merged report as JSON")
    args = ap.parse_args(argv)

    kernels, gauges = {}, {}
    if args.dump:
        try:
            d = load_dump(args.dump)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        kernels, gauges = d["kernels"], d["gauges"]
    decisions = load_autotune(args.autotune_cache or default_cache_path())

    if args.json:
        print(json.dumps({"kernels": kernels, "autotune": decisions,
                          "roofline": roofline_rows(kernels, gauges)},
                         indent=1, sort_keys=True))
    else:
        print(render(kernels, decisions, gauges))
    return 0


if __name__ == "__main__":
    sys.exit(main())
