"""Generate reference-format DL4J model zips for the interop tests.

No Java runtime exists on this rig, so these fixtures are hand-built to
the Java writer's byte layout (util/ModelSerializer.java:79-96 for the
zip, nd4j Nd4j.write for the binary buffers, the pre-0.7.2 legacy string
dialect for activation/loss — the dialect the 0.8 reader itself accepts,
MultiLayerConfiguration.java:145-255). The MLP fixture mirrors
regressiontest/RegressionTest080.java's MLP_1 case: dense(3->4, relu) +
output(4->5, softmax, MCXENT), Nesterovs(0.15, 0.9), params =
linspace(1..N), updater state = linspace(1..N) — so the import test can
assert the same facts the Java regression test asserts.

Run from the repo root:  python tools/build_dl4j_fixtures.py
"""
import json
import os
import sys
import zipfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.interop.dl4j_zip import write_nd4j_array

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "fixtures", "dl4j")


def _conf(layer_wrapper, seed=12345, extra=None):
    c = {"seed": seed, "pretrain": False, **(extra or {}),
         "layer": layer_wrapper}
    return c


def _base_layer(name, act, nin, nout, **kw):
    d = {"layerName": name, "activationFunction": act, "nin": nin,
         "nout": nout, "weightInit": "XAVIER", "biasInit": 0.0,
         "learningRate": 0.15, "momentum": 0.9, "updater": "NESTEROVS",
         "l1": 0.0, "l2": 0.0, "dropOut": 0.0}
    d.update(kw)
    return d


def mlp_fixture(path):
    """RegressionTest080.regressionTestMLP1's architecture, linspace
    params/updater — restore must reproduce these exactly."""
    conf = {
        "backprop": True, "pretrain": False, "backpropType": "Standard",
        "confs": [
            _conf({"dense": _base_layer("layer0", "relu", 3, 4)}),
            _conf({"output": _base_layer("layer1", "softmax", 4, 5,
                                         lossFunction="MCXENT")}),
        ],
        "inputPreProcessors": {},
    }
    n = 3 * 4 + 4 + 4 * 5 + 5
    params = np.linspace(1, n, n).astype(np.float32).reshape(1, n)
    upd = np.linspace(1, n, n).astype(np.float32).reshape(1, n)
    _write_zip(path, conf, params, upd)


def lenet_fixture(path):
    """A LeNet-style CNN on flattened 1x8x8 images (the Java net's
    feedForwardToCnn/cnnToFeedForward preprocessor sandwich): conv 3x3
    1->4 relu, maxpool 2x2, dense 16 relu, output 3 softmax. Weights are
    seeded-random, written in the Java layouts ('c' [out,in,kh,kw] conv
    kernels, 'f' dense matrices)."""
    conv = _base_layer("conv0", "relu", 1, 4)
    conv.update({"kernelSize": [3, 3], "stride": [1, 1], "padding": [0, 0],
                 "convolutionMode": "Truncate"})
    sub = {"layerName": "pool0", "poolingType": "MAX", "kernelSize": [2, 2],
           "stride": [2, 2], "padding": [0, 0],
           "convolutionMode": "Truncate"}
    # conv output 6x6x4 -> pool 3x3x4 -> flatten 36
    conf = {
        "backprop": True, "pretrain": False, "backpropType": "Standard",
        "confs": [
            _conf({"convolution": conv}),
            _conf({"subsampling": sub}),
            _conf({"dense": _base_layer("dense0", "relu", 36, 16)}),
            _conf({"output": _base_layer("out", "softmax", 16, 3,
                                         lossFunction="MCXENT")}),
        ],
        "inputPreProcessors": {
            "0": {"feedForwardToCnn": {"inputHeight": 8, "inputWidth": 8,
                                       "numChannels": 1}},
            "2": {"cnnToFeedForward": {"inputHeight": 3, "inputWidth": 3,
                                       "numChannels": 4}},
        },
    }
    r = np.random.default_rng(42)
    convW = r.normal(0, 0.3, (4, 1, 3, 3)).astype(np.float32)   # [out,in,kh,kw]
    convb = r.normal(0, 0.1, (4,)).astype(np.float32)
    dW = r.normal(0, 0.2, (36, 16)).astype(np.float32)          # [nin,nout]
    db = r.normal(0, 0.1, (16,)).astype(np.float32)
    oW = r.normal(0, 0.2, (16, 3)).astype(np.float32)
    ob = r.normal(0, 0.1, (3,)).astype(np.float32)
    flat = np.concatenate([convW.ravel(order="C"), convb,
                           dW.ravel(order="F"), db,
                           oW.ravel(order="F"), ob]).astype(np.float32)
    np.save(os.path.join(OUT, "lenet_raw_weights.npy"),
            {"convW": convW, "convb": convb, "dW": dW, "db": db,
             "oW": oW, "ob": ob}, allow_pickle=True)
    _write_zip(path, conf, flat.reshape(1, -1), None)


def graves_lstm_fixture(path):
    """GravesLSTM char-RNN (the reference's flagship recurrent demo,
    GravesLSTMCharModellingExample): gravesLSTM(5->8, tanh) +
    rnnoutput(8->5, softmax, MCXENT). Weights seeded-random, written in
    the Java layouts: input W [nIn,4H] 'f', recurrent [H,4H+3] 'f' (the
    +3 columns are the wFF/wOO/wGG peepholes), bias [4H]; gate column
    order (g, f, o, i) per LSTMHelpers.java."""
    nin, h, nout = 5, 8, 5
    lstm = _base_layer("lstm0", "tanh", nin, h)
    conf = {
        "backprop": True, "pretrain": False, "backpropType": "Standard",
        "confs": [
            _conf({"gravesLSTM": lstm}),
            _conf({"rnnoutput": _base_layer("out", "softmax", h, nout,
                                            lossFunction="MCXENT")}),
        ],
        "inputPreProcessors": {},
    }
    r = np.random.default_rng(7)
    W = r.normal(0, 0.3, (nin, 4 * h)).astype(np.float32)
    RW = r.normal(0, 0.3, (h, 4 * h + 3)).astype(np.float32)
    b = r.normal(0, 0.1, (4 * h,)).astype(np.float32)
    oW = r.normal(0, 0.3, (h, nout)).astype(np.float32)
    ob = r.normal(0, 0.1, (nout,)).astype(np.float32)
    flat = np.concatenate([W.ravel(order="F"), RW.ravel(order="F"), b,
                           oW.ravel(order="F"), ob]).astype(np.float32)
    np.save(os.path.join(OUT, "graves_raw_weights.npy"),
            {"W": W, "RW": RW, "b": b, "oW": oW, "ob": ob},
            allow_pickle=True)
    _write_zip(path, conf, flat.reshape(1, -1), None)


def _write_zip(path, conf, params, updater_state):
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("configuration.json", json.dumps(conf))
        z.writestr("coefficients.bin", write_nd4j_array(params, order="c"))
        if updater_state is not None:
            z.writestr("updaterState.bin",
                       write_nd4j_array(updater_state, order="c"))
    print(f"wrote {path}")


if __name__ == "__main__":
    os.makedirs(OUT, exist_ok=True)
    mlp_fixture(os.path.join(OUT, "080_mlp_3_4_5.zip"))
    lenet_fixture(os.path.join(OUT, "080_lenet_flat_8x8.zip"))
    graves_lstm_fixture(os.path.join(OUT, "080_graves_char_rnn.zip"))
