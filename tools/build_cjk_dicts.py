# -*- coding: utf-8 -*-
"""Build the bundled CJK dictionaries + held-out gold fixtures.

Run from the repo root:  python tools/build_cjk_dicts.py

Outputs (committed to the repo):
  deeplearning4j_tpu/nlp/data/zh_dict.tsv
      Simplified-Chinese lexicon derived from the jieba 0.42.1 package's
      dict.txt (MIT License) installed in this image: entries with
      freq >= ZH_MIN_FREQ, word length <= 8 — real corpus frequencies and
      POS tags at real scale (tens of thousands of entries).
  deeplearning4j_tpu/nlp/data/ja_dict.tsv
      Japanese lexicon COMPILED (dict_build.compile_dictionary) from the
      first 85%% of an ipadic-tokenized public-domain corpus (Natsume
      Soseki's novel "Botchan", tokenized by kuromoji+mecab-ipadic; the
      token stream ships as third-party test data in the reference repo).
      Only (surface, top-level-POS) pairs are used — the compile step and
      output format are ours.
  tests/fixtures/ja_heldout_gold.json
      Sentences reconstructed from the HELD-OUT last 15%% of the same token
      stream (never seen by the dictionary build) with their gold token
      sequences — the span-F1 eval set.
  tests/fixtures/zh_gold_jieba.json
      Chinese eval sentences with gold segmentation produced by jieba's
      full 349k-entry dictionary (precise mode) — an independent segmenter,
      so our dictionary/lattice is graded against an external standard, not
      against the vocabulary it embeds.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.nlp.dict_build import (compile_dictionary,
                                               write_dict_tsv)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(REPO, "deeplearning4j_tpu", "nlp", "data")
FIXTURES = os.path.join(REPO, "tests", "fixtures")
ZH_MIN_FREQ = 50
JA_TRAIN_FRACTION = 0.85

# Eval sentences for Chinese (drafted text; the GOLD segmentation comes
# from jieba's full dictionary, not from any vocabulary we bundle)
ZH_EVAL_SENTENCES = [
    "今天的天气非常好，我们决定去公园散步。",
    "人工智能技术正在改变世界经济的发展方向。",
    "他昨天在北京大学参加了一个国际学术会议。",
    "这家公司的产品质量得到了消费者的广泛认可。",
    "政府宣布将加大对基础设施建设的投资力度。",
    "科学家发现了一种新的治疗方法来对抗疾病。",
    "随着互联网的普及，越来越多的人开始网上购物。",
    "她每天早上六点起床，然后去附近的健身房锻炼身体。",
    "中国的高速铁路网络已经成为世界上最大的铁路系统。",
    "环境保护是当今社会面临的重要问题之一。",
    "学生们正在图书馆里认真准备期末考试。",
    "这部电影讲述了一个关于友谊和成长的感人故事。",
    "经济学家预测明年的市场形势将会有所好转。",
    "医生建议病人多喝水，注意休息，避免过度劳累。",
    "新能源汽车的销量在过去五年里增长了十倍。",
    "记者在现场采访了几位目击事故经过的群众。",
    "历史博物馆收藏了大量珍贵的古代文物。",
    "足球比赛在体育场举行，吸引了数万名观众。",
    "软件工程师需要不断学习新的编程语言和技术。",
    "春节期间，家家户户都会贴春联、吃饺子、放鞭炮。",
]


def build_zh():
    import jieba  # MIT-licensed package installed in the image
    src = os.path.join(os.path.dirname(jieba.__file__), "dict.txt")
    entries = {}
    with open(src, encoding="utf-8") as f:
        for line in f:
            parts = line.split()
            if len(parts) < 2:
                continue
            w, freq = parts[0], int(parts[1])
            pos = parts[2] if len(parts) > 2 else ""
            if freq >= ZH_MIN_FREQ and len(w) <= 8:
                entries[w] = (freq, pos)
    os.makedirs(DATA, exist_ok=True)
    write_dict_tsv(entries, os.path.join(DATA, "zh_dict.tsv"), header=(
        "Simplified-Chinese lexicon for the lattice segmenter.\n"
        f"Derived from jieba 0.42.1 dict.txt (MIT License), freq >= "
        f"{ZH_MIN_FREQ}.\nFormat: word<TAB>freq<TAB>pos"))
    print(f"zh_dict.tsv: {len(entries)} entries")

    # gold fixture from jieba's FULL dictionary (precise mode)
    gold = [{"sentence": s, "tokens": [t for t in jieba.cut(s) if t.strip()]}
            for s in ZH_EVAL_SENTENCES]
    os.makedirs(FIXTURES, exist_ok=True)
    with open(os.path.join(FIXTURES, "zh_gold_jieba.json"), "w",
              encoding="utf-8") as f:
        json.dump({"provenance": "gold = jieba 0.42.1 precise mode "
                                 "(full 349k dict), an independent segmenter",
                   "data": gold}, f, ensure_ascii=False, indent=1)
    print(f"zh_gold_jieba.json: {len(gold)} sentences")


def _read_ipadic_stream(path):
    """(surface, top-POS) pairs from a kuromoji 'surface<TAB>features' dump;
    sentence punctuation is kept (it segments the eval sentences)."""
    toks = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.rstrip("\n")
            if not line or "\t" not in line:
                continue
            surface, feats = line.split("\t", 1)
            toks.append((surface, feats.split(",")[0]))
    return toks


def build_ja():
    src = ("/root/reference/deeplearning4j-nlp-parent/deeplearning4j-nlp-"
           "japanese/src/test/resources/bocchan-ipadic-features.txt")
    if not os.path.exists(src):
        print(f"SKIP ja: corpus not available at {src}")
        return
    toks = _read_ipadic_stream(src)
    cut = int(len(toks) * JA_TRAIN_FRACTION)
    train, heldout = toks[:cut], toks[cut:]
    entries = compile_dictionary(train, min_freq=1, max_word_len=10)
    os.makedirs(DATA, exist_ok=True)
    write_dict_tsv(entries, os.path.join(DATA, "ja_dict.tsv"), header=(
        "Japanese lexicon for the lattice segmenter.\n"
        "Compiled (deeplearning4j_tpu.nlp.dict_build) from the first 85% of\n"
        "the public-domain novel 'Botchan' (Natsume Soseki) tokenized with\n"
        "kuromoji + mecab-ipadic (ipadic license: BSD-style).\n"
        "Format: word<TAB>freq<TAB>pos"))
    print(f"ja_dict.tsv: {len(entries)} entries from {len(train)} tokens")

    # held-out gold: reconstruct sentences from the UNSEEN tail
    sents, cur = [], []
    for surface, pos in heldout:
        cur.append(surface)
        if surface in ("。", "？", "！"):
            if 4 <= len(cur) <= 60:
                sents.append(cur)
            cur = []
    sents = sents[:80]
    gold = [{"sentence": "".join(t), "tokens": t} for t in sents]
    with open(os.path.join(FIXTURES, "ja_heldout_gold.json"), "w",
              encoding="utf-8") as f:
        json.dump({"provenance": "held-out last 15% of the Botchan ipadic "
                                 "token stream (never seen by the dictionary "
                                 "build); gold = kuromoji+mecab-ipadic",
                   "data": gold}, f, ensure_ascii=False, indent=1)
    print(f"ja_heldout_gold.json: {len(gold)} sentences "
          f"from {len(heldout)} held-out tokens")


if __name__ == "__main__":
    build_zh()
    build_ja()
