"""End-to-end request tracing, flight recorder, and SLO watchdogs.

The correlated-observability layer (ISSUE 13) end to end on CPU:
  1. serve a generation model over HTTP and send a request with an
     ``X-Trace-Id`` header — the id is echoed back and stamped on every
     span/event the request touches (ingress, admission, prefill, every
     decode step);
  2. reconstruct that request's timeline with tools/trace2timeline.py
     ("why was THIS request slow");
  3. arm an SLO watchdog (latency objective over the live histograms,
     multi-window error-budget burn rates) and read it off /metrics;
  4. trigger a flight-recorder dump over POST /debug/flightrec and read
     the black box back with the trace tools;
  5. arm a TrainingWatch and train through a NaN-poisoned batch: the
     in-program health vector (grad-norm / loss-spike / non-finite,
     computed inside the jitted step — zero extra host syncs) flags the
     step and dumps a black box naming it.

Run: python examples/request_tracing.py
"""
import json
import os
import sys
import tempfile
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.models.zoo_extra import transformer_lm
from deeplearning4j_tpu.serving import GenerationEngine, ServingHTTPServer
from deeplearning4j_tpu.telemetry import (LatencySLO, SLOWatchdog,
                                          TrainingWatch,
                                          configure_flight_recorder,
                                          set_slo_watchdog,
                                          set_training_watch)
from tools.trace2summary import load_events
from tools.trace2timeline import format_timeline, timeline

workdir = tempfile.mkdtemp(prefix="request_tracing_")
recorder = configure_flight_recorder(directory=os.path.join(workdir, "fr"))
reg = telemetry.get_registry()

print("== 1. traced generation request over HTTP ==")
net = transformer_lm(vocab_size=101, d_model=32, n_heads=2, n_blocks=1,
                     max_length=64, seed=7, token_input=True).init()
eng = GenerationEngine(net, model_name="lm", block_len=16, max_seq_len=64,
                       decode_slots=4, prefill_batches=(1, 2),
                       prompt_rungs=(64,))
wd = SLOWatchdog([LatencySLO("generate_ttft", "generation.lm.ttft_ms",
                             threshold_ms=250.0, target=0.95)])
set_slo_watchdog(wd)
srv = ServingHTTPServer(generation=eng)
base = f"http://127.0.0.1:{srv.start()}"

trace_id = "00aa11bb22cc33dd44ee55ff66778899"
req = urllib.request.Request(
    base + "/generate",
    json.dumps({"prompt": [3, 5, 7, 11], "max_tokens": 12,
                "stream": False}).encode(),
    {"Content-Type": "application/json", "X-Trace-Id": trace_id})
with urllib.request.urlopen(req, timeout=60) as r:
    echoed = r.headers.get("X-Trace-Id")
    body = json.loads(r.read())
print(f"tokens: {body['tokens']}")
print(f"X-Trace-Id echoed: {echoed} (matches: {echoed == trace_id})")

print("\n== 2. per-request timeline (tools/trace2timeline.py) ==")
jsonl = reg.write_trace_jsonl(os.path.join(workdir, "run.jsonl"))
rows = timeline(load_events(jsonl), trace_id)
print(format_timeline(rows))

print("\n== 3. SLO watchdog on /metrics ==")
with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
    metrics = json.loads(r.read())
print(json.dumps(metrics["slo"], indent=2))
with urllib.request.urlopen(base + "/metrics/prometheus", timeout=30) as r:
    prom = r.read().decode()
print("prometheus slo lines:")
print("\n".join(ln for ln in prom.splitlines() if ln.startswith(
    "dl4j_tpu_slo")))

print("\n== 4. flight recorder over POST /debug/flightrec ==")
req = urllib.request.Request(
    base + "/debug/flightrec",
    json.dumps({"operator": "demo", "question": "what just happened"})
    .encode(), {"Content-Type": "application/json"})
with urllib.request.urlopen(req, timeout=30) as r:
    dump_path = json.loads(r.read())["dumped"]
dump = json.load(open(dump_path))
print(f"dumped {len(dump['events'])} events to {dump_path}")
print(f"trigger={dump['trigger']} info={dump['info']}")
srv.stop()
set_slo_watchdog(None)

print("\n== 5. training watch: NaN batch leaves a black box ==")
from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Sgd

conf = (NeuralNetConfiguration(seed=42, updater=Sgd(0.05))
        .list(DenseLayer(n_in=8, n_out=16, activation="tanh"),
              OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
        .build())
mln = MultiLayerNetwork(conf).init()
rng = np.random.default_rng(0)
x = rng.normal(size=(64, 8)).astype(np.float32)
y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=64)]
x[40] = np.nan                                    # the poisoned batch
watch = TrainingWatch(window=8)
set_training_watch(watch)
mln.fit(iterator=ListDataSetIterator(features=x, labels=y, batch_size=8),
        epochs=1, async_prefetch=False)
watch.drain()
set_training_watch(None)
print(f"healthy: {watch.healthy}")
print(f"first unhealthy record: {watch.unhealthy[0]}")
print(f"black box: {recorder.last_dump_path}")
print("\ndone.")
