"""Autoregressive generation serving: paged KV-cache decode, continuous
batching, per-token HTTP streaming.

Walks the full subsystem end to end on CPU:
  1. build + (toy-)init a transformer LM and warm a GenerationEngine —
     every prefill rung and the decode-step program AOT-compiled up front;
  2. blocking and streaming generation, greedy vs temperature/top-k;
  3. concurrent clients sharing the in-flight decode batch (continuous
     batching) with ZERO steady-state XLA compiles, proven by the
     process-wide compile counter;
  4. per-token streaming over HTTP (POST /generate, chunked NDJSON);
  5. zero-downtime hot-swap mid-decode: the in-flight stream finishes on
     the old params, the next request runs the new ones;
  6. the generation metrics snapshot (TTFT, tokens/sec, slot occupancy).

Run: python examples/serving_generate.py
"""
import json
import os
import sys
import threading
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.models.zoo_extra import transformer_lm
from deeplearning4j_tpu.serving import (GenerationEngine, ServingHTTPServer,
                                        xla_compile_count)

VOCAB = 101

print("== 1. build + warm (all generation programs AOT-compiled) ==")
net = transformer_lm(vocab_size=VOCAB, d_model=64, n_heads=2, n_blocks=2,
                     max_length=128, seed=7, token_input=True).init()
eng = GenerationEngine(net, model_name="lm", block_len=16, max_seq_len=128,
                       decode_slots=8, prefill_batches=(1, 2, 4),
                       prompt_rungs=(32, 128))
print(f"warmed: {eng.models()['lm']}")

print("\n== 2. blocking + streaming, greedy vs sampled ==")
rng = np.random.default_rng(3)
prompt = rng.integers(1, VOCAB, size=12).tolist()
tokens, reason = eng.generate(prompt, max_tokens=24)
print(f"greedy ({reason}): {tokens}")
stream = eng.generate(prompt, max_tokens=24, temperature=0.8, top_k=40,
                      stream=True)
sampled = list(stream)          # arrives token by token
print(f"sampled ({stream.finish_reason}): {sampled}")

print("\n== 3. continuous batching: 12 clients, 8 slots, 0 compiles ==")
c0 = xla_compile_count()
done = []

def client(i):
    p = rng.integers(1, VOCAB, size=int(rng.integers(2, 30))).tolist()
    toks, why = eng.generate(p, max_tokens=int(rng.integers(4, 32)))
    done.append((i, len(toks), why))

threads = [threading.Thread(target=client, args=(i,)) for i in range(12)]
for t in threads:
    t.start()
for t in threads:
    t.join()
print(f"completed {len(done)} generations, "
      f"steady-state compiles: {xla_compile_count() - c0}")

print("\n== 4. per-token streaming over HTTP ==")
srv = ServingHTTPServer(generation=eng)
base = f"http://127.0.0.1:{srv.start()}"
req = urllib.request.Request(
    base + "/generate",
    json.dumps({"prompt": prompt, "max_tokens": 8}).encode(),
    {"Content-Type": "application/json"})
with urllib.request.urlopen(req, timeout=30) as r:
    for line in r:
        print("  chunk:", line.decode().strip())

print("\n== 5. hot-swap mid-decode: in-flight finishes on OLD params ==")
net2 = transformer_lm(vocab_size=VOCAB, d_model=64, n_heads=2, n_blocks=2,
                      max_length=128, seed=8, token_input=True).init()
long_stream = eng.generate(prompt, max_tokens=60, stream=True)
version = eng.hot_swap("lm", net2)          # same arch: executables reused
after = eng.generate(prompt, max_tokens=8)[0]
old_out = list(long_stream)
print(f"swap -> version {version}; in-flight emitted {len(old_out)} tokens "
      f"on old params; post-swap output (new params): {after}")

print("\n== 6. metrics ==")
snap = eng.metrics()["lm"]
for k in ("requests", "tokens_out", "prefills", "decode_steps", "ttft_ms",
          "decode_step_ms", "slot_occupancy", "tokens_per_sec_recent",
          "finished", "decode_recompiles"):
    print(f"  {k}: {snap[k]}")

srv.stop()
print("\ndone.")
