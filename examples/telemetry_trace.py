"""Unified telemetry end to end: trace a short fused-window training run,
write a Perfetto-loadable Chrome trace, print the per-phase fold and the
Prometheus dump, and demo the recompile detector on a shape-unstable loop.

Run: python examples/telemetry_trace.py [out_dir]
Open the written trace at https://ui.perfetto.dev (or chrome://tracing).
"""
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.listeners import PerformanceListener
from deeplearning4j_tpu.optimize.updaters import Adam
from tools.trace2summary import format_table, summarize


def main():
    out_dir = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp()
    os.makedirs(out_dir, exist_ok=True)
    telemetry.reset()

    rng = np.random.default_rng(0)
    x = rng.normal(size=(512, 16)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 512)]
    conf = (NeuralNetConfiguration(seed=7, updater=Adam(3e-3),
                                   dtype="float32")
            .list(DenseLayer(n_in=16, n_out=64, activation="tanh"),
                  OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(PerformanceListener(frequency=16))

    # fused-window training: 8 batches per host dispatch; every fit/epoch/
    # window/dispatch phase (and each XLA compile) lands in the trace
    net.fit(iterator=ListDataSetIterator(features=x, labels=y, batch_size=16),
            epochs=3, steps_per_dispatch=8)

    reg = telemetry.get_registry()
    trace_path = os.path.join(out_dir, "training.trace.json")
    reg.write_chrome_trace(trace_path)
    print(f"trace written: {trace_path}  (load it in ui.perfetto.dev)\n")

    print("-- per-phase fold (tools/trace2summary.py) " + "-" * 30)
    print(format_table(summarize(reg.trace_events())))

    print("\n-- prometheus dump (first lines) " + "-" * 40)
    print("\n".join(reg.to_prometheus_text().splitlines()[:16]))

    # the detectors: a shape-unstable loop retraces every iteration —
    # RecompileDetector names the span it happened under
    import jax
    import jax.numpy as jnp
    print("\n-- recompile detector on a shape-unstable loop " + "-" * 26)
    f = jax.jit(lambda a: (a * 2).sum())
    with telemetry.RecompileDetector(allowed=0, warn=False) as det:
        with telemetry.span("unstable_loop"):
            for n in (3, 5, 7, 9):          # new shape every call -> retrace
                f(jnp.ones((n,)))
    print(f"compiles flagged: {det.count}  "
          f"(spans: {sorted({e['span_path'] for e in det.events})})")

    # host-sync detector: flags an accidental float() in a hot loop
    with telemetry.HostSyncDetector(action="count") as sync:
        with telemetry.span("hot_loop"):
            val = f(jnp.ones((3,)))
            float(val)                       # the accidental sync
    print(f"host syncs flagged: {sync.count} "
          f"(at span: {sync.events[0]['span_path']})")


if __name__ == "__main__":
    main()
