"""Performance observability end to end (ISSUE 15): run an instrumented
fused-window fit, read the live MFU/roofline gauges the cost index
folded, snapshot the memory profiler, write a perf dump and render the
offline one-page report (roofline table, step-time decomposition,
memory top-K, baseline deltas vs the checked-in BENCH trajectory).

Run: python examples/perf_report.py [out_dir]
"""
import os
import sys
import tempfile

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.listeners import PerformanceListener
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.telemetry import memprof
from deeplearning4j_tpu.telemetry.perf import get_cost_index, write_perf_dump
from tools.perf_report import load_dump, render


def main():
    out_dir = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp()
    os.makedirs(out_dir, exist_ok=True)
    telemetry.reset()

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2048, 16)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 2048)]
    conf = (NeuralNetConfiguration(seed=7, updater=Adam(3e-3),
                                   dtype="float32")
            .list(DenseLayer(n_in=16, n_out=64, activation="tanh"),
                  OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    perf_l = PerformanceListener(frequency=16)
    net.set_listeners(perf_l)

    # 64 batches/epoch fused K=8 -> 64 steps/epoch: the cost capture
    # lands once the program crosses the 256-step warm-up threshold
    # (DL4J_TPU_PERF_CAPTURE_AFTER, epoch 4 here), and the final epoch's
    # fold reads a clean steady-state timing delta
    it = ListDataSetIterator(features=x, labels=y, batch_size=32)
    net.fit(iterator=it, epochs=5, steps_per_dispatch=8,
            async_prefetch=False)

    # --- live gauges the epoch-boundary fold published -------------------
    reg = telemetry.get_registry()
    print("== live perf gauges (cost index fold) ==")
    for name, g in sorted(reg.gauges_matching("perf.")):
        print(f"  {name} = {g.value:.6g}")
    cost = get_cost_index().get("fit/epoch/window")
    print(f"\ncaptured train-step program: {cost.flops_per_step:.0f} "
          f"flops/step, {cost.bytes_per_step:.0f} bytes/step "
          f"(source={cost.source}, K={cost.steps_per_call})")
    last = [r for r in perf_l.history if "mfu" in r]
    if last:
        print(f"PerformanceListener history mfu={last[-1]['mfu']:.3e} "
              f"achieved_tflops={last[-1]['achieved_tflops']:.3e}")

    # --- memory profiler -------------------------------------------------
    snap = memprof.snapshot(top_k=5)
    print(f"\n== memory: {snap['live_arrays']} live arrays, "
          f"{snap['total_live_bytes']} bytes ==")
    for row in snap["top"]:
        print(f"  {tuple(row['shape'])!s:>16} {row['dtype']:<9} "
              f"owner={row['owner']:<12} {row['total_bytes']}B")

    # --- offline report --------------------------------------------------
    dump_path = os.path.join(out_dir, "perf_dump.json")
    write_perf_dump(dump_path, baseline_root=_ROOT)
    print(f"\nwrote perf dump: {dump_path}\n")
    print(render(load_dump(dump_path)))


if __name__ == "__main__":
    main()
