"""Long-context causal attention: the fused Pallas flash kernels end-to-end.

Trains a small causal self-attention language block at T=2048 through the
framework's layer SPI. On TPU the SelfAttentionLayer routes through the
fused flash-attention kernels (ops/pallas_attention.py — O(T) HBM traffic,
no [T,T] score tensor in HBM); anywhere else it transparently falls back to
the XLA path with identical numerics (same helper-probe seam as the fused
LSTM).

Run:
    python examples/long_context_attention.py            # TPU: fused path
    JAX_PLATFORMS=cpu python examples/long_context_attention.py  # fallback

For sequences too long for ONE chip, shard the time axis instead:
parallel.ring_attention.ring_attention_sharded (sequence parallelism over
the mesh's ICI; see examples/pipeline_transformer.py for the mesh setup).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import (DenseLayer, RnnOutputLayer,
                                          SelfAttentionLayer)
from deeplearning4j_tpu.optimize.updaters import Adam

V, T, B = 32, 2048, 4          # T=2048: the [T,T] scores would be 16MB/head

rng = np.random.default_rng(0)
# synthetic copy-ish task: predict the previous token
ids = rng.integers(0, V, (B, T))
x = np.eye(V, dtype=np.float32)[ids]
y = np.eye(V, dtype=np.float32)[np.roll(ids, 1, axis=1)]

conf = (NeuralNetConfiguration(seed=1, updater=Adam(1e-3), dtype="float32")
        .list(DenseLayer(n_out=256, activation="identity"),
              SelfAttentionLayer(n_out=256, n_heads=2, causal=True),
              RnnOutputLayer(n_out=V, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.recurrent(V, T)).build())
net = MultiLayerNetwork(conf).init()

s0 = net.score(x, y)
net.fit(x, y, epochs=20)
s1 = net.score(x, y)
print(f"causal attention LM @ T={T}: score {s0:.4f} -> {s1:.4f}")
assert s1 < s0
