"""Data-parallel training over every available device: per-step all-reduce
(shared-gradients mode) and K-step parameter averaging, plus optional
threshold-compressed gradient exchange. On a single chip this degenerates to
normal training; on a pod slice the same code shards the batch over ICI."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets import ListDataSetIterator
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.parallel.accumulation import EncodedAccumulator
from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper


def main():
    conf = (NeuralNetConfiguration(seed=1, updater=Adam(5e-3))
            .list(DenseLayer(n_in=10, n_out=64, activation="relu"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(512, 10)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(-1) > 0).astype(int)]
    it = ListDataSetIterator(features=x, labels=y, batch_size=128)

    pw = ParallelWrapper(net)                      # per-step psum over 'data'
    pw.fit(it, epochs=3)
    print("sync DP accuracy:", net.evaluate(x, y).accuracy())

    it.reset()
    pw_avg = ParallelWrapper(net, training_mode="averaging",
                             averaging_frequency=4)
    pw_avg.fit(it, epochs=3)                       # K local steps then pmean
    print("averaged DP accuracy:", net.evaluate(x, y).accuracy())

    it.reset()
    pw_enc = ParallelWrapper(net, gradient_accumulator=EncodedAccumulator(
        threshold=0.01, capacity_fraction=0.5))    # DCN-style compression
    pw_enc.fit(it, epochs=3)
    print("threshold-compressed DP accuracy:", net.evaluate(x, y).accuracy())


if __name__ == "__main__":
    main()
