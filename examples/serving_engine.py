"""Production serving end to end: train briefly, checkpoint, serve through
the shape-bucketed engine, hot-swap a retrained model with zero downtime.

Run: python examples/serving_engine.py
"""
import json
import os
import sys
import tempfile
import threading
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.serving import InferenceEngine, ServingHTTPServer
from deeplearning4j_tpu.util.serialization import write_model


def make_net(seed):
    conf = (NeuralNetConfiguration(seed=seed, updater=Adam(5e-3),
                                   dtype="float32")
            .list(DenseLayer(n_in=8, n_out=32, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def main():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(512, 8)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 512)]

    net = make_net(1)
    net.fit(x, y, epochs=2, batch_size=64)

    # warm-up compiles one forward program per bucket; after this the
    # serving path never traces again (serving.xla_compile_count proves it)
    engine = InferenceEngine(net, feature_shape=(8,), buckets=(1, 8, 32),
                             batch_window_ms=1.0)
    server = ServingHTTPServer(engine)
    port = server.start()
    base = f"http://127.0.0.1:{port}"
    print(f"serving on {base}")

    # concurrent clients coalesce into padded bucket batches
    def client(n):
        req = urllib.request.Request(
            f"{base}/predict",
            json.dumps({"features": x[:n].tolist()}).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())["output"]

    threads = [threading.Thread(target=client, args=(n,)) for n in
               (1, 3, 8, 20)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # retrain -> checkpoint -> zero-downtime reload over the wire
    net2 = make_net(2)
    net2.fit(x, y, epochs=4, batch_size=64)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "v2.zip")
        write_model(net2, path)
        req = urllib.request.Request(
            f"{base}/reload",
            json.dumps({"model": "default", "path": path}).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            print("reload:", json.loads(r.read()))

    with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
        print("metrics:", json.dumps(json.loads(r.read())["default"],
                                     indent=2))
    server.stop()        # drain-then-stop: nothing left hanging


if __name__ == "__main__":
    main()
