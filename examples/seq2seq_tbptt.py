"""Seq2seq ComputationGraph (encoder LSTM -> LastTimeStep ->
DuplicateToTimeSeries -> decoder LSTM) trained with truncated BPTT, then
streamed step-by-step with rnn_time_step."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from deeplearning4j_tpu import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph.graph import ComputationGraph
from deeplearning4j_tpu.nn.graph.vertices import (DuplicateToTimeSeriesVertex,
                                                  LastTimeStepVertex)
from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam


def main():
    g = (NeuralNetConfiguration(seed=5, updater=Adam(5e-3)).graph_builder()
         .add_inputs("in")
         .add_layer("enc", LSTM(n_out=32, activation="tanh"), "in")
         .add_vertex("last", LastTimeStepVertex(mask_input="in"), "enc")
         .add_vertex("dup", DuplicateToTimeSeriesVertex(reference_input="in"), "last")
         .add_layer("dec", LSTM(n_out=32, activation="tanh"), "dup")
         .add_layer("out", RnnOutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "dec")
         .set_outputs("out")
         .set_input_types(InputType.recurrent(4, 20))
         .tbptt_length(5))
    net = ComputationGraph(g.build()).init()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 20, 4)).astype(np.float32)
    csum = np.cumsum(x.sum(-1), 1)
    y = np.eye(3, dtype=np.float32)[
        (csum > 0).astype(int) + (csum > 3).astype(int)]   # 3 real classes
    print("score before:", net.score(x, y))
    net.fit(x, y, epochs=10, batch_size=32)
    print("score after:", net.score(x, y))
    net.rnn_clear_previous_state()
    for t in range(3):
        step_out = np.asarray(net.rnn_time_step(x[:2, t]))
        print(f"streamed step {t}: {step_out.shape}")


if __name__ == "__main__":
    main()
