"""Word2Vec with hierarchical softmax over segmented Chinese text.

Demonstrates three round-3 capabilities together: the dictionary+Viterbi
CJK segmenter (nlp/segmentation.py — the ansj/kuromoji capability), the
hierarchical-softmax objective (reference useHierarchicSoftmax; batched
gather over padded Huffman paths), and similarity queries."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from deeplearning4j_tpu.nlp import CJKTokenizerFactory, Word2Vec


def main():
    rng = np.random.default_rng(0)
    # unsegmented Chinese sentences from two topics (study vs weather)
    study = ["我们在大学学习机器学习", "学生喜欢学习", "老师教学生机器学习",
             "我们研究深度学习", "学习机器学习很好"]
    weather = ["今天天气很好", "明天天气不好", "天气好我们高兴",
               "昨天天气不好", "今天天气好"]
    corpus = []
    for _ in range(60):
        corpus.append((study if rng.random() < 0.5 else weather)[rng.integers(5)])

    w2v = Word2Vec(layer_size=32, window=3, min_word_frequency=2, epochs=15,
                   learning_rate=0.05, sample=1e-3, seed=7,
                   use_hierarchical_softmax=True,
                   tokenizer_factory=CJKTokenizerFactory(language="zh"))
    w2v.fit(corpus)

    print("vocab:", len(w2v.vocab), "words (segmented, e.g. 机器学习 is ONE token)")
    print("sim(学习, 机器学习) =", round(w2v.similarity("学习", "机器学习"), 3))
    print("sim(学习, 天气)     =", round(w2v.similarity("学习", "天气"), 3))
    assert w2v.similarity("学习", "机器学习") > w2v.similarity("学习", "天气")
    print("nearest to 天气:", w2v.words_nearest("天气", 3))


if __name__ == "__main__":
    main()
