"""Migrating a Java DL4J model zip onto TPU.

A model saved by the Java reference (ModelSerializer.writeModel — the
standard ``configuration.json`` + ``coefficients.bin`` zip) restores
directly through the same ``restore_model`` entry point used for this
framework's own zips: the Java config dialect, the Nd4j binary buffers,
the 'f'-order dense / 'c'-order conv / (g,f,o,i)-gate LSTM layouts, and
BatchNormalization's running stats are all translated by
``interop/dl4j_zip.py``.

The restored net is a first-class MultiLayerNetwork: predict, evaluate,
fine-tune (the whole step jit-compiles onto the TPU), re-save in this
framework's format, or transfer-learn from it.

Run:  python examples/dl4j_zip_migration.py
(uses the committed test fixtures as stand-ins for your Java zips)
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.util.serialization import restore_model, write_model

FIXTURES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "fixtures", "dl4j")


def main():
    # 1. restore a Java-era MLP — ModelGuesser sniffs the format
    net = restore_model(os.path.join(FIXTURES, "080_mlp_3_4_5.zip"))
    print("restored Java MLP:",
          [type(l).__name__ for l in net.conf.layers],
          "| updater:", type(net.conf.updater).__name__)
    if net.import_notes:
        print("  import notes:", net.import_notes)

    x = np.random.default_rng(0).normal(size=(8, 3)).astype(np.float32)
    print("  predictions:", np.asarray(net.output(x)).argmax(1).tolist())

    # 2. a GravesLSTM char-RNN — the recurrent state APIs work immediately
    rnn = restore_model(os.path.join(FIXTURES, "080_graves_char_rnn.zip"))
    rnn.rnn_clear_previous_state()
    step = rnn.rnn_time_step(
        np.random.default_rng(1).normal(size=(2, 5)).astype(np.float32))
    print("restored Java GravesLSTM; rnn_time_step ->",
          np.asarray(step).shape)

    # 3. fine-tune the imported model on TPU and re-save natively
    y = np.eye(5, dtype=np.float32)[
        np.random.default_rng(2).integers(0, 5, 8)]
    s0 = net.score(x, y)
    net.fit(x, y, epochs=20)
    print(f"fine-tuned on TPU: score {s0:.4f} -> {net.score(x, y):.4f}")
    out = "/tmp/migrated_model.zip"
    write_model(net, out)
    again = restore_model(out)
    assert np.allclose(np.asarray(again.output(x)), np.asarray(net.output(x)))
    print(f"re-saved natively -> {out} (round-trip verified)")


if __name__ == "__main__":
    main()
