"""LeNet on MNIST — the canonical first example (reference
dl4j-examples LenetMnistExample). Runs on whatever device JAX finds
(the real TPU chip under this repo's environment)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from deeplearning4j_tpu import InputType, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets import MnistDataSetIterator
from deeplearning4j_tpu.models.lenet import lenet
from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener
from deeplearning4j_tpu.ui import StatsListener, render_dashboard
from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage


def main():
    net = lenet(n_classes=10).init()
    storage = InMemoryStatsStorage()
    net.set_listeners(ScoreIterationListener(50), StatsListener(storage))
    train_it = MnistDataSetIterator(batch_size=128, train=True)
    net.fit(iterator=train_it, epochs=1)
    ev = net.evaluate(MnistDataSetIterator(batch_size=512, train=False))
    print(ev.stats())
    render_dashboard(storage, path="lenet_training.html")
    print("dashboard written to lenet_training.html")


if __name__ == "__main__":
    main()
