"""Generation at user scale: prefix-cache sharing + speculative decoding.

Walks both ISSUE 14 engines end to end on CPU:
  1. build a target LM + a TRUNCATED-transformer draft sharing its
     weights, warm a GenerationEngine with both features on — prefill
     rungs, decode step, COW copy, draft prefill/propose and the batched
     verify window all AOT-compiled up front;
  2. prefix-cache sharing: a long block-aligned "system prompt" pays
     prefill ONCE — repeats match the rolling prefix hash, take refcounted
     references on the shared read-only blocks, COW the final block, and
     reach their first token in ~one decode step (watch the cached-vs-
     uncached TTFT);
  3. a divergent continuation after the shared prefix stays token-for-
     token identical to its own cache-free greedy decode;
  4. speculative decoding: the draft proposes k tokens, ONE batched
     verify pass accepts the longest agreeing prefix + the target's
     correction token — same tokens as plain greedy, fewer target
     dispatches (accepted_tokens_per_verify is the per-dispatch yield);
  5. both composed under concurrent clients with ZERO steady-state XLA
     compiles, proven by the process-wide compile counter;
  6. the /metrics block-pool economics: hit rate, shared blocks, COW
     copies, cached-LRU size, evictions.

Run: python examples/speculative_decode.py
"""
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.models.decode import (TransformerDecodeSpec,
                                              naive_generate,
                                              truncated_draft)
from deeplearning4j_tpu.models.zoo_extra import transformer_lm
from deeplearning4j_tpu.serving import GenerationEngine, xla_compile_count

VOCAB = 101

print("== 1. target + truncated draft, warm everything ==")
net = transformer_lm(vocab_size=VOCAB, d_model=64, n_heads=2, n_blocks=2,
                     max_length=128, seed=7, token_input=True).init()
# the draft = the target's first block + shared embed/head: where the
# second block's refinement is small, greedy agreement is high. A random
# init has NO such structure, so (like a distilled draft would) scale the
# second block's residual contribution down to put the toy model in the
# trained-draft agreement regime:
params = list(net.params)
for i, name in enumerate(net.vertex_names):
    if name == "b1_attn":
        p = dict(params[i])
        p["Wo"], p["b"] = p["Wo"] * 0.25, p["b"] * 0.25
        params[i] = p
    elif name == "b1_ff2":
        params[i] = {k: v * 0.25 for k, v in params[i].items()}
net.params = tuple(params)
draft = truncated_draft(net, n_blocks=1)
eng = GenerationEngine(net, model_name="lm", block_len=16, max_seq_len=128,
                       decode_slots=4, prefill_batches=(1, 2),
                       prompt_rungs=(128,), draft=draft, spec_k=4)
print(f"model: {json.dumps(eng.models()['lm'], indent=2)}")

print("\n== 2. prefix cache: pay prefill once for a shared system prompt ==")
rng = np.random.default_rng(3)
system = rng.integers(1, VOCAB, size=96).tolist()   # 6 full blocks, aligned

def ttft(prompt):
    """Client-side time to FIRST streamed token."""
    t0 = time.perf_counter()
    st = eng.generate(prompt, max_tokens=8, stream=True)
    it = iter(st)
    first = next(it)
    dt = (time.perf_counter() - t0) * 1e3
    return dt, [first] + list(it)

uncached_ms, first_tokens = ttft(system)
cached_ms, repeat_tokens = ttft(system)
assert repeat_tokens == first_tokens
print(f"TTFT uncached: {uncached_ms:.1f} ms -> cached repeat: "
      f"{cached_ms:.1f} ms (prefill skipped: COW + one decode step)")

print("\n== 3. divergent continuation stays bit-exact ==")
question = system + rng.integers(1, VOCAB, size=9).tolist()
spec = TransformerDecodeSpec(net)
want = naive_generate(net, question, 12, pad_to=128, spec=spec)
got, _ = eng.generate(question, max_tokens=12)
assert got == want, "cached-prefix decode diverged from naive greedy!"
print(f"shared 96-token prefix, private suffix -> {got[:6]}... == naive")

print("\n== 4. speculative decoding: tokens per target dispatch ==")
prompt = rng.integers(1, VOCAB, size=12).tolist()
want = naive_generate(net, prompt, 24, pad_to=128, spec=spec)
got, _ = eng.generate(prompt, max_tokens=24)
assert got == want, "speculative greedy diverged from plain greedy!"
sp = eng.metrics()["lm"]["speculative"]
print(f"exact output, {sp['verify_steps']} verify windows, "
      f"accepted_tokens_per_verify={sp['accepted_tokens_per_verify']} "
      f"(plain decode = 1.0 by definition)")

print("\n== 5. composed, concurrent, zero steady-state compiles ==")
compiles0 = xla_compile_count()
outs = {}

def client(i):
    p = system if i % 2 == 0 else prompt
    outs[i] = eng.generate(p, max_tokens=12)[0]

threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
for t in threads:
    t.start()
for t in threads:
    t.join()
assert len({tuple(outs[i]) for i in range(0, 8, 2)}) == 1
assert xla_compile_count() == compiles0
print(f"8 concurrent clients (hits + speculation interleaved), "
      f"compiles: {xla_compile_count() - compiles0}")

print("\n== 6. block-pool economics ==")
print(json.dumps(eng.metrics()["lm"]["prefix"], indent=2))
eng.stop()
print("\ndone.")
