"""End-to-end pipeline-parallel transformer LM over a device mesh.

The GPipe pipeline (deeplearning4j_tpu.parallel.pipeline) handles the
practical pipeline case: a deep stack of IDENTICAL blocks whose activations
share one shape. That restriction is by design — activations hop
stage-to-stage via ppermute, which needs a single static shape, and stacking
per-stage params on a leading axis is what shards 1/n of the parameters per
device. Heterogeneous ends (embedding, LM head) stay OUTSIDE the pipeline,
replicated — exactly how stacked-transformer training uses GPipe in
practice.

This example trains a tiny char-level decoder-only transformer end-to-end:
  embedding (replicated) -> n_devices pre-LN decoder blocks, one per pipeline
  stage (params stage-sharded) -> head (replicated), with jax.grad flowing
  through the pipelined forward (scan + ppermute transpose = the GPipe
  backward schedule). Run with JAX_PLATFORMS=cpu
  XLA_FLAGS=--xla_force_host_platform_device_count=8 for a virtual mesh, or
  as-is on a pod slice.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.pipeline import (pipeline_apply,
                                                  stack_stage_params,
                                                  stage_sharding)

D, HEADS, FF = 32, 4, 64
VOCAB, T = 32, 16


def init_block(key, scale=0.1):
    ks = jax.random.split(key, 6)
    n = lambda k, s: jax.random.normal(k, s, jnp.float32) * scale
    return {"qkv": n(ks[0], (D, 3 * D)), "proj": n(ks[1], (D, D)),
            "ff1": n(ks[2], (D, FF)), "ff2": n(ks[3], (FF, D)),
            "ln1": jnp.ones((D,)), "ln2": jnp.ones((D,))}


def block_fn(p, x):
    """One pre-LN decoder block: causal self-attention + MLP. [B, T, D]."""
    def ln(v, g):
        mu = jnp.mean(v, -1, keepdims=True)
        sd = jnp.sqrt(jnp.var(v, -1, keepdims=True) + 1e-5)
        return (v - mu) / sd * g

    B, T_, _ = x.shape
    h = ln(x, p["ln1"])
    qkv = h @ p["qkv"]
    q, k, v = jnp.split(qkv.reshape(B, T_, HEADS, 3 * D // HEADS), 3, axis=-1)
    att = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(D // HEADS)
    mask = jnp.tril(jnp.ones((T_, T_)))
    att = jax.nn.softmax(jnp.where(mask > 0, att, -1e9), axis=-1)
    o = jnp.einsum("bhts,bshd->bthd", att, v).reshape(B, T_, D)
    x = x + o @ p["proj"]
    h = ln(x, p["ln2"])
    return x + jax.nn.relu(h @ p["ff1"]) @ p["ff2"]


def main():
    n = len(jax.devices())
    mesh = make_mesh((n,), ("pipe",))
    key = jax.random.PRNGKey(0)
    keys = jax.random.split(key, n + 2)

    blocks = [init_block(keys[i]) for i in range(n)]
    stacked = jax.device_put(stack_stage_params(blocks),
                             stage_sharding(mesh, "pipe"))
    embed = jax.random.normal(keys[-2], (VOCAB, D), jnp.float32) * 0.1
    head = jax.random.normal(keys[-1], (D, VOCAB), jnp.float32) * 0.1
    pipe = pipeline_apply(block_fn, mesh, "pipe")

    # toy corpus: ascending mod-VOCAB sequences (next char = +1)
    rng = np.random.default_rng(0)
    starts = rng.integers(0, VOCAB, (64,))
    ids = (starts[:, None] + np.arange(T + 1)[None, :]) % VOCAB
    x_ids, y_ids = jnp.asarray(ids[:, :-1]), jnp.asarray(ids[:, 1:])
    n_micro, mb = 4, 16

    def loss_fn(params):
        stacked_p, embed_p, head_p = params
        h = embed_p[x_ids]                                   # [B, T, D]
        h = h.reshape(n_micro, mb, T, D)
        h = pipe(stacked_p, h)                               # pipelined stack
        logits = h.reshape(-1, T, D) @ head_p
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y_ids[..., None],
                                             axis=-1))

    @jax.jit
    def step(params, lr):
        l, g = jax.value_and_grad(loss_fn)(params)
        return jax.tree.map(lambda p, gg: p - lr * gg, params, g), l

    params = (stacked, embed, head)
    losses = []
    for i in range(60):
        params, l = step(params, 0.5)
        losses.append(float(l))
    print(f"pipeline transformer ({n} stages): loss {losses[0]:.4f} -> "
          f"{losses[-1]:.4f}")
    assert losses[-1] < losses[0] * 0.5, "did not train"
    return losses


if __name__ == "__main__":
    import os
    # the sandbox pre-imports jax with the platform latched from env; honor
    # an explicit JAX_PLATFORMS=cpu request (virtual mesh) the same way
    # __graft_entry__.dryrun_multichip does
    if os.environ.get("JAX_PLATFORMS") == "cpu" and \
            (jax.config.jax_platforms or "") != "cpu":
        jax.config.update("jax_platforms", "cpu")
    main()
