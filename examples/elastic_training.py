"""Elastic fault-tolerant training end to end: a supervised step loop
with async checkpointing survives a worker kill + a truncated newest
checkpoint, degrades to SparkNet averaging windows under a slow
interconnect, and exits a (simulated) preemption cleanly — then a fresh
"process" resumes from the directory and finishes the run.

Run: python examples/elastic_training.py [ckpt_dir]
"""
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from deeplearning4j_tpu import (MultiLayerNetwork, NeuralNetConfiguration,
                                telemetry)
from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.parallel import (CorruptCheckpoint, ElasticTrainer,
                                         FaultInjector, FaultPlan,
                                         KillWorker, PreemptAt,
                                         SlowCollective)


def make_net():
    conf = (NeuralNetConfiguration(seed=7, updater=Adam(1e-2),
                                   dtype="float32")
            .list(DenseLayer(n_in=8, n_out=32, activation="tanh"),
                  OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def make_iterator():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 256)]
    return ListDataSetIterator(features=x, labels=y, batch_size=16)


def main():
    ckpt_dir = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp()
    telemetry.reset()
    devices = jax.devices()[:4] if len(jax.devices()) >= 4 else jax.devices()

    # scripted cluster weather: a worker dies at step 30 with the newest
    # checkpoint truncated on disk; the interconnect crawls over steps
    # 50-70; a preemption notice lands at step 90
    plan = FaultPlan(
        CorruptCheckpoint(step=30, mode="truncate"),
        KillWorker(step=30, worker=len(devices) - 1, rejoin=True),
        SlowCollective(step=50, until_step=70, delay_ms=300.0),
        PreemptAt(step=90),
    )
    net = make_net()
    trainer = ElasticTrainer(
        net, checkpoint_dir=ckpt_dir, devices=devices,
        checkpoint_every_n_steps=10, keep_last=4,
        sync_latency_budget_ms=50.0, degraded_averaging_window=4,
        fault_injector=FaultInjector(plan))
    with trainer.preemption_guard():      # real SIGTERM takes the same path
        trainer.fit(make_iterator(), num_steps=120)
    print(f"run 1: stopped at step {trainer.steps_done} "
          f"(preempted={trainer.preempted}), recoveries={trainer.recoveries}, "
          f"mode transitions={trainer.mode_history}")

    # a fresh "process" resumes from the directory and finishes
    net2 = make_net()
    trainer2 = ElasticTrainer(net2, checkpoint_dir=ckpt_dir,
                              devices=devices, checkpoint_every_n_steps=10)
    trainer2.fit(make_iterator(), num_steps=120)
    print(f"run 2: resumed and finished at step {trainer2.steps_done}")

    snap = telemetry.get_registry().snapshot()
    ctr, hist = snap["counters"], snap["histograms"]
    print(f"recoveries={ctr.get('elastic.recoveries')}, "
          f"degraded_transitions={ctr.get('elastic.degraded_transitions')}, "
          f"preemptions={ctr.get('elastic.preemptions')}")
    w = hist.get("elastic.checkpoint.write_ms")
    if w:
        print(f"checkpoint write p95: {w['p95']:.1f} ms over {w['count']} "
              f"writes; recover p95: "
              f"{hist['elastic.recover_ms']['p95']:.0f} ms")
    print(f"checkpoints in {ckpt_dir}: "
          f"{sorted(n for n in os.listdir(ckpt_dir) if n.endswith('.json'))}")


if __name__ == "__main__":
    main()
