# -*- coding: utf-8 -*-
"""Real-scale CJK dictionary evaluation (VERDICT r3 item 5).

The bundled lexicons (nlp/data/*.tsv, built by tools/build_cjk_dicts.py)
are graded on text NOT authored against the embedded vocabulary:
  - zh: gold segmentation from jieba's full 349k-entry dictionary (an
    independent segmenter, MIT-licensed, installed in the image);
  - ja: a held-out slice of an ipadic-tokenized public-domain corpus that
    the dictionary build never saw.
Reference analogue: the vendored dictionaries behind
deeplearning4j-nlp-chinese (org/ansj) and -japanese (kuromoji).
"""
import json
import os
import statistics

import pytest

from deeplearning4j_tpu.nlp.segmentation import (ChineseSegmenter,
                                                 JapaneseSegmenter,
                                                 LatticeSegmenter)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _spans(tokens):
    out, i = set(), 0
    for t in tokens:
        out.add((i, i + len(t)))
        i += len(t)
    return out


def _span_f1(gold, pred):
    g, p = _spans(gold), _spans(pred)
    tp = len(g & p)
    prec, rec = tp / max(len(p), 1), tp / max(len(g), 1)
    return 2 * prec * rec / max(prec + rec, 1e-9)


def _load(name):
    with open(os.path.join(FIXTURES, name), encoding="utf-8") as f:
        return json.load(f)["data"]


def _mean_f1(seg, data):
    return statistics.mean(
        _span_f1([t for t in item["tokens"] if t.strip()],
                 seg.segment(item["sentence"]))
        for item in data)


def test_zh_bundled_dict_scale_and_pos():
    seg = ChineseSegmenter()
    assert len(seg) > 40_000, "bundled zh lexicon must be real-scale"
    # POS tags ride along from the lexicon (ansj natures capability)
    assert seg.pos_of("天气") != ""
    assert seg.pos_of("不存在的词汇串") == ""


def test_zh_f1_vs_independent_segmenter():
    """Span-F1 >= 0.85 against jieba's full-dictionary segmentation — and
    the bundled dictionary must beat the bootstrap core by a wide margin
    (the r3 weakness: gold authored against the embedded vocab)."""
    data = _load("zh_gold_jieba.json")
    full = _mean_f1(ChineseSegmenter(), data)
    core = _mean_f1(ChineseSegmenter(use_bundled=False), data)
    assert full >= 0.85, f"bundled-dict F1 {full:.3f}"
    assert full - core >= 0.3, (full, core)


def test_ja_bundled_dict_scale():
    seg = JapaneseSegmenter()
    assert len(seg) > 5_000, "bundled ja lexicon must be corpus-scale"
    assert seg.pos_of("学校") != ""


def test_ja_f1_on_heldout_corpus():
    """Span-F1 >= 0.8 on the held-out 15% of the corpus the dictionary was
    compiled from (sentences the build never saw; gold = kuromoji+ipadic).
    Degrades gracefully: the bootstrap core alone scores far lower but
    does not collapse."""
    data = _load("ja_heldout_gold.json")
    full = _mean_f1(JapaneseSegmenter(), data)
    core = _mean_f1(JapaneseSegmenter(use_bundled=False), data)
    assert full >= 0.8, f"bundled-dict F1 {full:.3f}"
    assert full - core >= 0.2, (full, core)
    assert core >= 0.3, f"core fallback collapsed: {core:.3f}"


def test_user_dictionary_wins_over_bundled():
    """The user-dict seam: an added domain compound beats the bundled
    unigram split (reference user-dictionary behavior)."""
    seg = ChineseSegmenter()
    text = "量子纠错码非常重要"
    assert "量子纠错码" not in seg.segment(text)
    seg.add_word("量子纠错码", 100000, pos="n")
    assert "量子纠错码" in seg.segment(text)
    assert seg.pos_of("量子纠错码") == "n"


def test_dict_tsv_round_trip(tmp_path):
    from deeplearning4j_tpu.nlp.dict_build import (compile_dictionary,
                                                   read_dict_tsv,
                                                   write_dict_tsv)
    entries = compile_dictionary(
        [("猫", "名詞"), ("猫", "名詞"), ("走る", "動詞"), ("猫", "代名詞")])
    assert entries["猫"] == (3, "名詞")     # majority POS
    p = str(tmp_path / "d.tsv")
    write_dict_tsv(entries, p, header="test dict")
    back = read_dict_tsv(p)
    assert back == entries
    seg = LatticeSegmenter()
    seg.load_tsv(p)
    assert "猫" in seg and seg.pos_of("猫") == "名詞"
