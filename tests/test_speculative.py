"""Speculative decoding (ISSUE 14 tentpole, draft/verify leg).

Pins:
  - exact-output: speculative greedy decode (draft propose + batched
    verify + longest-agreeing-prefix acceptance) is token-for-token
    identical to plain greedy decode, f32 AND bf16, truncated-transformer
    (dense cache) AND LSTM (state cache) drafts, sequential AND under
    concurrent continuous-batched admission, composed with prefix-cache
    hits/COW;
  - full-acceptance regression: a self-draft (draft == target) accepts
    every proposal — the draft cache can never carry an unwritten gap
    behind the next verify window;
  - stop tokens / max_tokens landing MID-window truncate exactly as plain
    decode; sampling requests and per-request opt-outs ride the plain
    path;
  - hot-swap cohort pinning: in-flight requests finish on the old params
    AND old draft; same-arch swaps reuse every compiled executable;
  - zero steady-state recompiles with prefix cache + speculation BOTH
    enabled (ISSUE acceptance).
"""
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.models.decode import (TransformerDecodeSpec,
                                              naive_generate,
                                              truncated_draft)
from deeplearning4j_tpu.models.zoo_extra import (text_generation_lstm,
                                                 transformer_lm)
from deeplearning4j_tpu.serving import (GenerationEngine,
                                        xla_compile_count)
from deeplearning4j_tpu.serving.generation import accept_greedy
from deeplearning4j_tpu.telemetry import RecompileDetector

R = np.random.default_rng(4321)


def _lm(seed=7, vocab=53, d_model=32, n_heads=2, n_blocks=2, max_length=64,
        dtype="float32"):
    return transformer_lm(vocab_size=vocab, d_model=d_model,
                          n_heads=n_heads, n_blocks=n_blocks,
                          max_length=max_length, seed=seed, dtype=dtype,
                          token_input=True).init()


# ------------------------------------------------------------ rule + builder
def test_accept_greedy_rule():
    props = np.array([[5, 6, 7], [5, 6, 7], [5, 9, 7], [1, 2, 3]])
    targs = np.array([[5, 6, 7, 8], [5, 6, 9, 8], [5, 6, 7, 8],
                      [9, 2, 3, 4]])
    counts, emitted = accept_greedy(props, targs)
    assert counts.tolist() == [3, 2, 1, 0]
    assert emitted[0] == [5, 6, 7, 8]       # all accepted + bonus token
    assert emitted[1] == [5, 6, 9]          # correction replaces p_3
    assert emitted[2] == [5, 6]
    assert emitted[3] == [9]                # immediate correction


def test_truncated_draft_shares_target_weights():
    net = _lm()
    draft = truncated_draft(net, 1)
    src = dict(zip(net.vertex_names, net.params))
    dst = dict(zip(draft.vertex_names, draft.params))
    assert "b1_attn" not in dst and "b0_attn" in dst
    assert np.array_equal(np.asarray(dst["embed"]["W"]),
                          np.asarray(src["embed"]["W"]))
    assert np.array_equal(np.asarray(dst["b0_attn"]["Wq"]),
                          np.asarray(src["b0_attn"]["Wq"]))
    with pytest.raises(ValueError):
        truncated_draft(net, 3)             # only 2 blocks exist


def test_spec_config_validation():
    net = _lm(seed=11, vocab=37, d_model=16, n_blocks=1, max_length=32)
    lstm = text_generation_lstm(vocab_size=37, hidden=12,
                                max_length=32, seed=5).init()
    # LSTM target cannot speculate (no block tables to verify over)
    with pytest.raises(ValueError, match="paged"):
        GenerationEngine(lstm, model_name="x", block_len=8, max_seq_len=32,
                         decode_slots=1, prefill_batches=(1,),
                         prompt_rungs=(16,), draft=net, warm=False)
    # draft/target vocab mismatch
    bad = text_generation_lstm(vocab_size=29, hidden=12,
                               max_length=32, seed=5).init()
    with pytest.raises(ValueError, match="vocab"):
        GenerationEngine(net, model_name="x", block_len=8, max_seq_len=32,
                         decode_slots=1, prefill_batches=(1,),
                         prompt_rungs=(32,), draft=bad, warm=False)
    with pytest.raises(ValueError, match="spec_k"):
        GenerationEngine(net, model_name="x", block_len=8, max_seq_len=32,
                         spec_k=-1, warm=False)


# ------------------------------------------------- shared engine + the pins
@pytest.fixture(scope="module")
def spec_lm():
    """One warmed f32 engine with a truncated-transformer draft (dense
    adapter, k=3) AND the prefix cache on — the two tentpole features
    composed. Read-only for the tests below."""
    net = _lm()
    draft = truncated_draft(net, 1)
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=64,
                           decode_slots=4, prefill_batches=(1, 2),
                           prompt_rungs=(64,), draft=draft, spec_k=3)
    yield net, TransformerDecodeSpec(net), eng
    eng.stop()


def test_speculative_greedy_bit_identical_f32(spec_lm):
    """THE pin: speculative greedy output == naive full-recompute greedy,
    sequential AND 8 concurrent clients over 4 slots (verify windows
    interleaving with step-boundary admission), WITH prefix hits/COW from
    the repeated prompts."""
    net, spec, eng = spec_lm
    prompts = [R.integers(1, 53, size=n).tolist() for n in (5, 16, 9)]
    refs = [naive_generate(net, p, 12, pad_to=64, spec=spec)
            for p in prompts]
    for p, want in zip(prompts, refs):
        toks, reason = eng.generate(p, max_tokens=12)
        assert (toks, reason) == (want, "length")
    outs = {}

    def client(i):
        st = eng.generate(prompts[i % 3], max_tokens=12, stream=True)
        outs[i] = (list(st), st.finish_reason)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(8):
        assert outs[i] == (refs[i % 3], "length"), f"client {i} diverged"
    snap = eng.metrics()["lm"]
    assert snap["speculative"]["verify_steps"] > 0
    assert snap["speculative"]["emitted"] > 0
    assert snap["prefix"]["hits"] >= 3          # repeats hit the cache


def test_stop_token_and_length_mid_window(spec_lm):
    """A stop token (or the max_tokens budget) landing in the MIDDLE of a
    verify window truncates exactly where plain greedy decode stops."""
    net, spec, eng = spec_lm
    p = [3, 9, 4]
    greedy = naive_generate(net, p, 9, pad_to=64, spec=spec)
    stop = greedy[4]                             # mid-window position
    toks, reason = eng.generate(p, max_tokens=9, stop=[stop])
    assert reason == "stop"
    assert toks == greedy[:greedy.index(stop)]
    # odd max_tokens not divisible by the k+1 window
    toks, reason = eng.generate(p, max_tokens=7)
    assert (toks, reason) == (greedy[:7], "length")


def test_sampling_and_opt_out_ride_plain_path(spec_lm):
    net, spec, eng = spec_lm
    p = [5, 7, 11]
    v0 = eng.metrics()["lm"]["speculative"]["verify_steps"]
    # per-request opt-out: exact greedy, no verify windows
    want = naive_generate(net, p, 6, pad_to=64, spec=spec)
    toks, _ = eng.generate(p, max_tokens=6, speculative=False)
    assert toks == want
    assert eng.metrics()["lm"]["speculative"]["verify_steps"] == v0
    # sampling opts out automatically (exactness is greedy-only)
    toks, reason = eng.generate(p, max_tokens=8, temperature=1.0, top_k=5)
    assert reason == "length" and len(toks) == 8
    assert all(0 <= t < 53 for t in toks)
    assert eng.metrics()["lm"]["speculative"]["verify_steps"] == v0


def test_self_draft_accepts_every_proposal():
    """Regression for the draft-cache gap bug: with draft == target every
    proposal must agree (the draft writes K/V for ALL fed positions,
    including p_k's, so no window ever reads an unwritten position)."""
    net = _lm(seed=31, vocab=41, d_model=16, n_blocks=1, max_length=64)
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=64,
                           decode_slots=2, prefill_batches=(1,),
                           prompt_rungs=(64,), draft=net, spec_k=3)
    try:
        spec = TransformerDecodeSpec(net)
        p = R.integers(1, 41, size=6).tolist()
        want = naive_generate(net, p, 13, pad_to=64, spec=spec)
        toks, _ = eng.generate(p, max_tokens=13)
        assert toks == want
        s = eng.metrics()["lm"]["speculative"]
        assert s["accepted"] == s["proposed"], \
            f"self-draft disagreed with itself: {s}"
    finally:
        eng.stop()


@pytest.mark.slow
def test_speculative_lstm_draft_bit_identical():
    """The state-adapter draft: an LSTM proposes, the stacked-state rewind
    rolls its recurrent state back to exactly what verify accepted —
    output stays plain-greedy-identical even at near-zero acceptance.
    Slow lane (ISSUE 19 tier-1 budget reclaim): the transformer-draft
    bit-identity + acceptance pins in this file keep the speculative
    greedy-identity contract tier-1."""
    net = _lm(seed=11, vocab=37, d_model=16, n_blocks=1, max_length=32)
    lstm = text_generation_lstm(vocab_size=37, hidden=12, max_length=32,
                                seed=5).init()
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=32,
                           decode_slots=2, prefill_batches=(1, 2),
                           prompt_rungs=(32,), draft=lstm, spec_k=3)
    try:
        assert eng.models()["lm"]["speculative"]["draft_adapter"] == "state"
        spec = TransformerDecodeSpec(net)
        prompts = [R.integers(1, 37, size=n).tolist() for n in (4, 8, 7)]
        refs = [naive_generate(net, p, 10, pad_to=32, spec=spec)
                for p in prompts]
        outs = {}

        def client(i):
            outs[i] = eng.generate(prompts[i % 3], max_tokens=10)[0]

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(6):
            assert outs[i] == refs[i % 3], f"client {i} diverged"
        assert eng.metrics()["lm"]["speculative"]["verify_steps"] > 0
    finally:
        eng.stop()


@pytest.mark.slow   # bf16 variant; tier-1 keeps the f32 pin
# (test_speculative_greedy_bit_identical_f32) and the core bf16 decode
# pin (test_generation.py::test_paged_greedy_bit_identical_dtypes_and_embeds)
def test_speculative_bf16_bit_identical():
    net = _lm(seed=13, vocab=37, d_model=16, n_blocks=2, max_length=32,
              dtype="bfloat16")
    draft = truncated_draft(net, 1)
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=32,
                           decode_slots=2, prefill_batches=(1,),
                           prompt_rungs=(32,), draft=draft, spec_k=3)
    try:
        spec = TransformerDecodeSpec(net)
        for n in (4, 8):
            p = R.integers(1, 37, size=n).tolist()
            want = naive_generate(net, p, 10, pad_to=32, spec=spec)
            assert eng.generate(p, max_tokens=10)[0] == want
    finally:
        eng.stop()


# ----------------------------------------------------------------- hot-swap
def test_hot_swap_spec_cohort_pinning():
    """In-flight speculative generations finish on the OLD params + OLD
    draft; post-swap admissions run the new params. Same-arch swap reuses
    every compiled executable (draft/verify included): zero new traces."""
    net_a = _lm(seed=7)
    net_b = _lm(seed=8)
    spec_a, spec_b = TransformerDecodeSpec(net_a), TransformerDecodeSpec(net_b)
    draft = truncated_draft(net_a, 1)
    prompt = R.integers(1, 53, size=6).tolist()
    want_a = naive_generate(net_a, prompt, 40, pad_to=64, spec=spec_a)
    want_b = naive_generate(net_b, prompt, 40, pad_to=64, spec=spec_b)
    assert want_a != want_b
    eng = GenerationEngine(net_a, model_name="lm", block_len=8,
                           max_seq_len=64, decode_slots=2,
                           prefill_batches=(1,), prompt_rungs=(64,),
                           draft=draft, spec_k=3)
    try:
        traces0 = eng.trace_count
        compiles0 = xla_compile_count()
        st_a = eng.generate(prompt, max_tokens=40, stream=True)
        deadline = time.monotonic() + 5.0
        while eng.metrics()["lm"]["prefills"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.002)
        assert eng.hot_swap("lm", net_b) == 2
        st_b = eng.generate(prompt, max_tokens=40, stream=True)
        assert st_a.result() == (want_a, "length"), \
            "in-flight speculative generation must finish on OLD params"
        assert st_b.result() == (want_b, "length")
        assert eng.trace_count == traces0
        assert xla_compile_count() == compiles0
    finally:
        eng.stop()


# ------------------------------------------------- zero-recompile acceptance
@pytest.mark.bench_smoke
def test_zero_recompiles_prefix_and_speculative():
    """ISSUE acceptance: with BOTH features enabled, a mixed stream —
    cache misses, block-aligned hits (COW), partial hits, sampling,
    greedy speculation, concurrency — triggers ZERO backend compiles
    after warm-up (RecompileDetector + process compile counter + trace
    hook)."""
    net = _lm(seed=21, vocab=41, d_model=16, n_blocks=2, max_length=64)
    draft = truncated_draft(net, 1)
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=64,
                           decode_slots=4, prefill_batches=(1, 2),
                           prompt_rungs=(16, 64), draft=draft, spec_k=3,
                           seed=3)
    try:
        traces0 = eng.trace_count
        compiles0 = xla_compile_count()
        work = [(8, 6, 0.0), (8, 6, 0.0), (16, 5, 0.0), (16, 5, 0.0),
                (3, 8, 0.7), (30, 4, 0.0), (8, 6, 0.0), (13, 9, 0.0)]
        res = {}

        def client(i):
            plen, mx, temp = work[i]
            p = [(j * 7 + 1) % 40 + 1 for j in range(plen)]
            st = eng.generate(p, max_tokens=mx, temperature=temp,
                              stream=True)
            res[i] = (list(st), st.finish_reason)

        with RecompileDetector(allowed=0) as det:
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(work))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        for i, (plen, mx, _) in enumerate(work):
            assert len(res[i][0]) == mx and res[i][1] == "length", \
                (i, res[i])
        assert det.count == 0, f"steady state compiled: {det.events}"
        assert xla_compile_count() == compiles0
        assert eng.trace_count == traces0
        snap = eng.metrics()["lm"]
        assert snap["prefix"]["hits"] >= 2
        assert snap["prefix"]["cow_copies"] >= 1
        assert snap["speculative"]["verify_steps"] > 0
    finally:
        eng.stop()


# ----------------------------------------------------------------- HTTP opt-in
def test_http_speculative_surface():
    """/generate honors "speculative": false; /models and /metrics expose
    the per-model opt-in state and the new economics sections."""
    import json
    import urllib.request
    from deeplearning4j_tpu.serving import ServingHTTPServer
    net = _lm(seed=67, vocab=29, d_model=16, n_blocks=1, max_length=32)
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=32,
                           decode_slots=2, prefill_batches=(1,),
                           prompt_rungs=(32,), draft=net, spec_k=2)
    srv = ServingHTTPServer(generation=eng)
    base = f"http://127.0.0.1:{srv.start()}"
    try:
        spec = TransformerDecodeSpec(net)
        p = [3, 5, 7]
        want = naive_generate(net, p, 6, pad_to=32, spec=spec)

        def post(body):
            req = urllib.request.Request(
                base + "/generate", json.dumps(body).encode(),
                {"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        out = post({"prompt": p, "max_tokens": 6, "stream": False})
        assert out["tokens"] == want
        v1 = eng.metrics()["lm"]["speculative"]["verify_steps"]
        assert v1 > 0
        out = post({"prompt": p, "max_tokens": 6, "stream": False,
                    "speculative": False})
        assert out["tokens"] == want
        assert eng.metrics()["lm"]["speculative"]["verify_steps"] == v1
        with urllib.request.urlopen(base + "/models", timeout=10) as r:
            models = json.loads(r.read())["generation"]["lm"]
        assert models["speculative"] == {"enabled": True, "k": 2,
                                         "draft_adapter": "dense"}
        assert models["prefix_cache"] is True
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            metrics = json.loads(r.read())["generation"]["lm"]
        assert "prefix" in metrics and "speculative" in metrics
        assert "accepted_tokens_per_verify" in metrics["speculative"]
    finally:
        srv.stop()


# -------------------------------------------------------------------- bench
@pytest.mark.bench_smoke
def test_speculative_bench_smoke():
    """Tier-1 guard for the speculative_decode row (ISSUE 14 acceptance):
    accepted_tokens_per_verify >= 2 on the truncated-draft workload, zero
    steady-state compiles, and the paired best-of spec/plain ratio not
    catastrophically regressed. Three consecutive failing attempts
    required to fail (rig co-tenant bursts; the acceptance yield itself is
    deterministic, the tokens/sec ratio is the noisy part)."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    row = None
    for _ in range(3):
        row = bench.bench_speculative(duration=0.8, clients=3, k=4,
                                      decode_slots=4, repeats=2)
        assert row["steady_state_compiles"] == 0
        assert row["verify_steps"] > 0
        assert row["accepted_tokens_per_verify"] >= 2.0, row
        if row["spec_vs_plain"] >= 1.0:
            return
    pytest.fail(f"speculative decode slower than plain in 3 attempts: "
                f"{row}")
