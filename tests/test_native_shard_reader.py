"""C++ mmap shard reader (native/shard_reader.cpp): exact parity with
numpy's npz parsing on the export-shard format, through both the raw
NativeNpzFile protocol and the iterator seam."""
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.export import (
    NativeShardedFileDataSetIterator, ShardedFileDataSetIterator,
    export_dataset_iterator, make_shard_iterator)
from deeplearning4j_tpu.native import NativeNpzFile, shard_reader_available

pytestmark = pytest.mark.skipif(not shard_reader_available(),
                                reason="no g++ toolchain on this host")

R = np.random.default_rng(3)


def _export(tmp_path, n_batches=5):
    def gen():
        for i in range(n_batches):
            x = R.normal(size=(8, 6, 4)).astype(np.float32)
            y = np.eye(3, dtype=np.float32)[R.integers(0, 3, 8)]
            m = (R.random((8, 6)) > 0.3).astype(np.float32)
            yield DataSet(x, y, m, None)
    export_dataset_iterator(gen(), str(tmp_path), batches_per_shard=2)


def test_native_npz_member_parity(tmp_path):
    """Every member of every shard: same names, dtypes, shapes, bytes."""
    _export(tmp_path)
    import glob
    import os
    for path in sorted(glob.glob(os.path.join(str(tmp_path), "*.npz"))):
        with np.load(path) as z_np, NativeNpzFile(path) as z_nat:
            assert sorted(z_nat.files) == sorted(z_np.files)
            for name in z_np.files:
                a, b = z_np[name], z_nat[name]
                assert a.dtype == b.dtype, name
                assert a.shape == b.shape, name
                np.testing.assert_array_equal(a, b, err_msg=name)


def test_native_iterator_matches_python_iterator(tmp_path):
    _export(tmp_path)
    py_batches = list(ShardedFileDataSetIterator(str(tmp_path)))
    nat_batches = list(NativeShardedFileDataSetIterator(str(tmp_path)))
    assert len(py_batches) == len(nat_batches) == 5
    for a, b in zip(py_batches, nat_batches):
        np.testing.assert_array_equal(a.features, b.features)
        np.testing.assert_array_equal(a.labels, b.labels)
        np.testing.assert_array_equal(a.features_mask, b.features_mask)
        assert b.labels_mask is None


def test_make_shard_iterator_prefers_native(tmp_path):
    _export(tmp_path, n_batches=2)
    it = make_shard_iterator(str(tmp_path))
    assert isinstance(it, NativeShardedFileDataSetIterator)
    it2 = make_shard_iterator(str(tmp_path), prefer_native=False)
    assert type(it2) is ShardedFileDataSetIterator
    assert len(list(it)) == len(list(it2)) == 2


def test_dtype_zoo_round_trip(tmp_path):
    """uint8/int32/int64/f32/f64/bf16-as-void members all parse."""
    import jax.numpy as jnp
    path = str(tmp_path / "mixed.npz")
    arrs = {
        "u8": R.integers(0, 255, (4, 5)).astype(np.uint8),
        "i32": R.integers(-9, 9, (7,)).astype(np.int32),
        "i64": R.integers(-9, 9, (2, 2, 2)).astype(np.int64),
        "f32": R.normal(size=(3, 3)).astype(np.float32),
        "f64": R.normal(size=(6,)),
        "bf16": np.asarray(jnp.asarray([1.5, -2.25], jnp.bfloat16)),
        "scalar": np.asarray(3.25, np.float32),
    }
    np.savez(path, **arrs)
    with NativeNpzFile(path) as z:
        for name, a in arrs.items():
            b = z[name]
            assert b.dtype == a.dtype and b.shape == a.shape, name
            np.testing.assert_array_equal(a.view(np.uint8) if a.dtype.kind == "V"
                                          else a,
                                          b.view(np.uint8) if b.dtype.kind == "V"
                                          else b, err_msg=name)


def test_compressed_npz_falls_back(tmp_path):
    """A COMPRESSED npz (np.savez_compressed) is rejected by the native
    parser and served by numpy through the iterator's fallback."""
    path = str(tmp_path / "c.npz")
    np.savez_compressed(path, x=np.arange(10.0))
    with pytest.raises(OSError):
        NativeNpzFile(path)
    # the iterator seam still reads it
    export_dataset_iterator(iter([DataSet(
        np.zeros((2, 2), np.float32), np.zeros((2, 2), np.float32))]),
        str(tmp_path / "shards"))
    it = NativeShardedFileDataSetIterator(str(tmp_path / "shards"))
    assert len(list(it)) == 1


def test_non_bf16_void_dtype_is_rejected_not_mistyped(tmp_path):
    """Regression (ADVICE r5): ONLY descr '|V2' (raw bfloat16, the shard
    format's sole void producer) is reinterpreted; any other void layout
    (here '|V4') must raise instead of silently passing through — or worse,
    being viewed — as the wrong type."""
    path = str(tmp_path / "weird.npz")
    np.savez(path, arr=np.zeros(4, dtype="V4"))
    with NativeNpzFile(path) as z:
        with pytest.raises(ValueError):
            z["arr"]


def test_bf16_v2_members_still_round_trip(tmp_path):
    """The '|V2' gate must not break the bf16 recovery path."""
    import jax.numpy as jnp
    path = str(tmp_path / "bf16.npz")
    a = np.asarray(jnp.asarray([1.5, -2.25, 0.125], jnp.bfloat16))
    np.savez(path, w=a)
    with NativeNpzFile(path) as z:
        b = z["w"]
    assert b.dtype == a.dtype
    np.testing.assert_array_equal(a.view(np.uint16), b.view(np.uint16))
