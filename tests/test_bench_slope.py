"""bench.py measurement-contract regressions (the bench is an artifact the
driver parses; its helpers must stay portable across JAX versions)."""
import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


def test_slope_measure_lowering_avals_match_call_args():
    """Regression (ADVICE r5): _slope_measure must LOWER its AOT program
    with np.float32 salt so the lowering avals (incl. weak_type) exactly
    match the np.float32(s) it later calls with — strict JAX versions
    reject the mismatch on every compiled call. This exercises the full
    lower->compile->call path; an aval mismatch raises TypeError."""
    def step(xs, carry):
        (a,) = carry
        return (a @ a + xs[0, 0],)

    x = jnp.zeros((8, 128), jnp.float32)
    state = (jnp.eye(64, dtype=jnp.float32),)
    try:
        dt, _ = bench._slope_measure(step, (x, state), n_pair=(4, 64))
    except bench.BenchImplausible:
        # CPU timing jitter can defeat the slope on a loaded test box; the
        # aval contract was still exercised (compiled calls happened before
        # the slope check)
        return
    assert dt > 0


def test_piped_row_reports_etl_wait(monkeypatch):
    """bench_piped's row contract: the overlapped path (thread-pool shard
    reads -> device prefetch) reports the measured per-iteration feed
    block so the pipeline tax stays a number. A tiny model stands in for
    ResNet-50 — the row's FEED path, not the model, is under test."""
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                              OutputLayer)

    def tiny_cnn(n_classes, height, width, channels, updater, dtype,
                 compute_dtype=None):
        conf = (NeuralNetConfiguration(seed=0, updater=updater, dtype=dtype,
                                       compute_dtype=compute_dtype)
                .list(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                       stride=(4, 4), activation="relu",
                                       convolution_mode="same"),
                      DenseLayer(n_out=8, activation="relu"),
                      OutputLayer(n_out=n_classes, activation="softmax",
                                  loss="mcxent"))
                .set_input_type(InputType.convolutional(height, width,
                                                        channels))
                .build())
        return MultiLayerNetwork(conf)

    monkeypatch.setattr(zoo, "resnet50", tiny_cnn)
    monkeypatch.setattr(bench, "IMG", 8)
    row, dt, flops = bench.bench_piped(batch=4)
    assert isinstance(row, dict)
    assert "etl_wait_ms" in row, row
    assert row["etl_wait_ms"] is None or row["etl_wait_ms"] >= 0.0
    assert row["value"] is None or row["value"] > 0
