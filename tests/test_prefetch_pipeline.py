"""Overlapped input pipeline: DevicePrefetchIterator (device-side prefetch),
the thread-pool shard reader, fit() routing, and ETL-wait observability.

Reference: AsyncDataSetIterator.java:30 (host prefetch) +
PerformanceListener.java:111,178 (ETL time per iteration). The device-side
half is TPU-new (datasets/prefetch.py): batch N+1 ships via jax.device_put
while step N computes. These tests pin the contract: bit-identical training
results, bounded in-flight depth, pre-sharded placement, clean shutdown,
and preserved back-pressure for live streams.
"""
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import (DataSet, DataSetIterator,
                                                 ListDataSetIterator)
from deeplearning4j_tpu.datasets.export import (ShardedFileDataSetIterator,
                                                export_dataset_iterator)
from deeplearning4j_tpu.datasets.iterators import (ExistingDataSetIterator,
                                                   MultiDataSet)
from deeplearning4j_tpu.datasets.prefetch import DevicePrefetchIterator
from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.listeners import PerformanceListener
from deeplearning4j_tpu.optimize.updaters import Sgd
from deeplearning4j_tpu.parallel.mesh import data_sharding, make_mesh
from deeplearning4j_tpu.parallel.streaming import StreamingDataSetIterator


def _tiny_net(seed=12):
    conf = (NeuralNetConfiguration(seed=seed, updater=Sgd(0.1))
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _toy(rng, n=64):
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=n)]
    return x, y


class CountingIterator(DataSetIterator):
    """Instrumented base: counts how many batches the consumer side has
    pulled out of it (the prefetcher's look-ahead)."""

    def __init__(self, data):
        self.data = list(data)
        self.pulled = 0

    def __iter__(self):
        for ds in self.data:
            self.pulled += 1
            yield ds


def _prefetch_threads():
    return [t for t in threading.enumerate()
            if t.name == "device-prefetch" and t.is_alive()]


def _await_no_prefetch_threads(timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if not _prefetch_threads():
            return True
        time.sleep(0.02)
    return False


# ------------------------------------------------------------- correctness
def test_training_results_bit_exact_vs_unwrapped(rng):
    """The tentpole contract: prefetched fit == serial fit, bit for bit."""
    x, y = _toy(rng)
    a = _tiny_net().fit(iterator=ListDataSetIterator(
        features=x, labels=y, batch_size=16), epochs=3)
    b = _tiny_net().fit(iterator=ListDataSetIterator(
        features=x, labels=y, batch_size=16), epochs=3, async_prefetch=False)
    np.testing.assert_array_equal(np.asarray(a.params_flat()),
                                  np.asarray(b.params_flat()))


def test_explicit_prefetched_iterator_bit_exact(rng):
    """A caller-supplied DevicePrefetchIterator (the .prefetch() sugar)
    trains identically too."""
    x, y = _toy(rng)
    a = _tiny_net().fit(iterator=ListDataSetIterator(
        features=x, labels=y, batch_size=16).prefetch(depth=3), epochs=2)
    b = _tiny_net().fit(iterator=ListDataSetIterator(
        features=x, labels=y, batch_size=16), epochs=2, async_prefetch=False)
    np.testing.assert_array_equal(np.asarray(a.params_flat()),
                                  np.asarray(b.params_flat()))


def test_stream_values_and_order_preserved(rng):
    x, y = _toy(rng, n=40)
    base = ListDataSetIterator(features=x, labels=y, batch_size=8)
    got = list(DevicePrefetchIterator(base, depth=2, dtype="float32"))
    want = list(ListDataSetIterator(features=x, labels=y, batch_size=8))
    assert len(got) == len(want) == 5
    for g, w in zip(got, want):
        assert isinstance(g.features, jax.Array)
        np.testing.assert_array_equal(np.asarray(g.features), w.features)
        np.testing.assert_array_equal(np.asarray(g.labels), w.labels)


def test_dtype_cast_floats_only(rng):
    """Float arrays land as the requested dtype; ints (uint8 wire images,
    token ids) pass through untouched — the 4x-less-wire contract."""
    ds = DataSet(rng.integers(0, 255, (4, 3)).astype(np.uint8),
                 rng.normal(size=(4, 2)).astype(np.float64))
    out = next(iter(DevicePrefetchIterator(
        ExistingDataSetIterator([ds]), depth=1, dtype="float32")))
    assert out.features.dtype == np.uint8
    assert out.labels.dtype == np.float32


def test_multidataset_batches_ship_per_input(rng):
    """ComputationGraph multi-input batches: every array of the per-input
    lists lands on device, None mask holes survive."""
    mds = MultiDataSet([rng.normal(size=(4, 3)).astype(np.float32),
                        rng.normal(size=(4, 5)).astype(np.float32)],
                       [rng.normal(size=(4, 2)).astype(np.float32)],
                       labels_mask=[None])
    out = next(iter(DevicePrefetchIterator(
        ExistingDataSetIterator([mds]), depth=1, dtype="float32")))
    assert isinstance(out, MultiDataSet)
    assert all(isinstance(f, jax.Array) for f in out.features)
    assert out.labels_mask == [None]
    np.testing.assert_array_equal(np.asarray(out.features[1]),
                                  mds.features[1])


# ------------------------------------------------------------------- depth
def test_in_flight_depth_respected(rng):
    """The producer never runs more than depth (queue) + 1 (in hand)
    batches ahead of the consumer."""
    x, y = _toy(rng, n=240)
    depth = 2
    base = CountingIterator(ListDataSetIterator(features=x, labels=y,
                                                batch_size=8).data)
    it = iter(DevicePrefetchIterator(base, depth=depth))
    consumed = 0
    for _ in range(10):
        next(it)
        consumed += 1
        time.sleep(0.05)       # let the producer run as far as it can
        assert base.pulled <= consumed + depth + 1, (
            f"pulled {base.pulled} with only {consumed} consumed")
    it.close()


# ---------------------------------------------------------------- sharding
def test_sharded_device_put_placement(rng):
    """With a NamedSharding over a 2-device mesh, batches land PRE-SHARDED
    on the data axis."""
    mesh = make_mesh((2,), ("data",), jax.devices()[:2])
    sh = data_sharding(mesh)
    x, y = _toy(rng, n=32)
    base = ListDataSetIterator(features=x, labels=y, batch_size=16)
    for ds in DevicePrefetchIterator(base, depth=2, sharding=sh,
                                     dtype="float32"):
        assert ds.features.sharding == sh
        assert ds.labels.sharding == sh
        # the batch dim is actually split: each device holds half
        shards = ds.features.addressable_shards
        assert {s.data.shape[0] for s in shards} == {8}
    np.testing.assert_array_equal(
        np.asarray(jax.device_put(x[:16], sh)), x[:16])


def test_remainder_batch_ships_unsharded_instead_of_failing(rng):
    """A final batch that doesn't tile the mesh must not kill the epoch."""
    mesh = make_mesh((2,), ("data",), jax.devices()[:2])
    sh = data_sharding(mesh)
    x, y = _toy(rng, n=21)     # 16 + remainder 5
    base = ListDataSetIterator(features=x, labels=y, batch_size=16)
    got = list(DevicePrefetchIterator(base, depth=2, sharding=sh))
    assert [g.features.shape[0] for g in got] == [16, 5]


def test_parallel_wrapper_sync_uses_device_prefetch(rng):
    """ParallelWrapper's per-step all-reduce path trains through the
    sharded device prefetcher and matches the host-fed result."""
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
    x, y = _toy(rng)
    pw = ParallelWrapper(_tiny_net(), workers=2)
    perf = PerformanceListener(frequency=1)
    pw.net.set_listeners(perf)
    pw.fit(ListDataSetIterator(features=x, labels=y, batch_size=16),
           epochs=2)
    single = _tiny_net().fit(iterator=ListDataSetIterator(
        features=x, labels=y, batch_size=16), epochs=2, async_prefetch=False)
    np.testing.assert_allclose(np.asarray(pw.net.params_flat()),
                               np.asarray(single.params_flat()),
                               rtol=2e-5, atol=2e-6)
    rec = perf.history[-1]
    assert rec["etl_wait_ms_per_iteration"] >= 0.0
    assert rec["device_ms_per_iteration"] > 0.0


# ---------------------------------------------------------------- shutdown
def test_early_break_stops_producer_thread(rng):
    x, y = _toy(rng, n=800)
    base = CountingIterator(ListDataSetIterator(features=x, labels=y,
                                                batch_size=8).data)
    for i, _ in enumerate(DevicePrefetchIterator(base, depth=2)):
        if i == 1:
            break
    assert _await_no_prefetch_threads(), "producer thread leaked after break"
    pulled = base.pulled
    time.sleep(0.15)
    assert base.pulled == pulled, "producer kept pulling after shutdown"
    assert base.pulled < len(base.data)


def test_consumer_exception_stops_producer(rng):
    x, y = _toy(rng, n=800)
    base = CountingIterator(ListDataSetIterator(features=x, labels=y,
                                                batch_size=8).data)
    with pytest.raises(RuntimeError, match="boom"):
        for i, _ in enumerate(DevicePrefetchIterator(base, depth=2)):
            if i == 2:
                raise RuntimeError("boom")
    assert _await_no_prefetch_threads()


def test_base_exception_propagates_to_consumer(rng):
    x, y = _toy(rng, n=32)

    class Exploding(DataSetIterator):
        def __iter__(self):
            yield from ListDataSetIterator(features=x, labels=y,
                                           batch_size=16)
            raise ValueError("disk on fire")

    with pytest.raises(ValueError, match="disk on fire"):
        list(DevicePrefetchIterator(Exploding(), depth=2))
    assert _await_no_prefetch_threads()


# --------------------------------------------------------------- streaming
def test_streaming_back_pressure_preserved_under_prefetch():
    """The prefetcher's bounded queue must NOT turn a live stream into an
    unbounded buffer: once topic capacity + prefetch depth (+1 in flight)
    are saturated, non-blocking publishes are rejected; consuming frees
    slots again."""
    topic = StreamingDataSetIterator(capacity=2)
    pf = DevicePrefetchIterator(topic, depth=1)
    x = np.ones((2, 3), np.float32)
    y = np.ones((2, 1), np.float32)
    assert topic.publish(x, y, block=False)
    it = iter(pf)
    next(it)                            # starts the producer thread

    accepted, rejections = 0, 0
    for _ in range(200):
        if topic.publish(x, y, block=False):
            accepted += 1
            rejections = 0
        else:
            rejections += 1
            if rejections >= 5:
                break
        time.sleep(0.01)
    assert rejections >= 5, "publish never saw back-pressure"
    # bound: topic queue (2) + prefetch queue (1) + 1 in the producer's hand
    assert accepted <= 2 + 1 + 1

    next(it)                            # consume one -> a slot frees up
    ok = False
    for _ in range(100):
        if topic.publish(x, y, block=False):
            ok = True
            break
        time.sleep(0.01)
    assert ok, "slot did not free after consuming"
    topic.end_of_stream()
    list(it)                            # drain + clean exit
    assert _await_no_prefetch_threads()


# ------------------------------------------------- fit() routing smoke test
def test_fit_routes_iterator_feeds_through_prefetcher(rng, monkeypatch):
    """CI guard: a regression back to serial feeding must fail tier-1, not
    only show up in bench_piped."""
    from deeplearning4j_tpu.optimize import solver as solver_mod
    used = []

    class Spy(DevicePrefetchIterator):
        def __iter__(self):
            used.append(True)
            return super().__iter__()

    monkeypatch.setattr(solver_mod, "DevicePrefetchIterator", Spy)
    x, y = _toy(rng)
    _tiny_net().fit(iterator=ListDataSetIterator(features=x, labels=y,
                                                 batch_size=16), epochs=1)
    assert used, ("fit() no longer routes iterator feeds through "
                  "DevicePrefetchIterator")


def test_etl_wait_and_device_ms_surfaced_by_listener(rng):
    """PerformanceListener history carries the reference's ETL split:
    etl_wait_ms (feed block) vs device_ms (dispatch + compute)."""
    net = _tiny_net()
    perf = PerformanceListener(frequency=1)
    net.set_listeners(perf)
    x, y = _toy(rng)
    net.fit(iterator=ListDataSetIterator(features=x, labels=y,
                                         batch_size=16), epochs=2)
    assert perf.history
    rec = perf.history[-1]
    assert rec["etl_wait_ms_per_iteration"] >= 0.0
    assert rec["device_ms_per_iteration"] > 0.0
    # back-compat alias for pre-overlap consumers
    assert rec["etl_ms_per_iteration"] == rec["etl_wait_ms_per_iteration"]


# ------------------------------------------------- thread-pool shard reads
def _export_shards(tmp_path, rng, n_batches=7):
    def gen():
        for _ in range(n_batches):
            yield DataSet(rng.normal(size=(8, 4)).astype(np.float32),
                          np.eye(3, dtype=np.float32)[
                              rng.integers(0, 3, 8)])
    export_dataset_iterator(gen(), str(tmp_path), batches_per_shard=2)


def test_prefetch_buffer_zero_means_no_prefetch(rng):
    """Back-compat: ParallelWrapper(prefetch_buffer=0) and
    fit(prefetch_depth=0) opt OUT of prefetching instead of raising."""
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
    x, y = _toy(rng)
    pw = ParallelWrapper(_tiny_net(), workers=2, prefetch_buffer=0)
    pw.fit(ListDataSetIterator(features=x, labels=y, batch_size=16),
           epochs=1)
    _tiny_net().fit(iterator=ListDataSetIterator(features=x, labels=y,
                                                 batch_size=16),
                    epochs=1, prefetch_depth=0)


def test_pooled_shard_reader_bit_identical_to_serial(tmp_path, rng):
    _export_shards(tmp_path, rng)
    # pooling is opt-in: the default keeps the lazy one-batch footprint
    assert ShardedFileDataSetIterator(str(tmp_path)).reader_threads == 1
    serial = list(ShardedFileDataSetIterator(str(tmp_path),
                                             reader_threads=1))
    pooled = list(ShardedFileDataSetIterator(str(tmp_path),
                                             reader_threads=3))
    assert len(serial) == len(pooled) == 7
    for a, b in zip(serial, pooled):
        np.testing.assert_array_equal(a.features, b.features)
        np.testing.assert_array_equal(a.labels, b.labels)


def test_pooled_shard_reader_early_break(tmp_path, rng):
    _export_shards(tmp_path, rng, n_batches=12)
    it = ShardedFileDataSetIterator(str(tmp_path), reader_threads=2)
    for i, _ in enumerate(it):
        if i == 2:
            break
    # a second full pass still works (no wedged pool state)
    assert len(list(it)) == 12


def test_full_overlapped_pipeline_end_to_end(tmp_path, rng):
    """Shards on disk -> thread-pool reads -> device prefetch -> fit():
    same params as the serial, host-fed path."""
    x, y = _toy(rng)

    def gen():
        for s in range(0, 64, 16):
            yield DataSet(x[s:s + 16], y[s:s + 16])
    export_dataset_iterator(gen(), str(tmp_path), batches_per_shard=2)

    piped = ShardedFileDataSetIterator(str(tmp_path), reader_threads=2)
    a = _tiny_net().fit(iterator=piped.prefetch(depth=2), epochs=2)
    b = _tiny_net().fit(iterator=ListDataSetIterator(
        features=x, labels=y, batch_size=16), epochs=2, async_prefetch=False)
    np.testing.assert_array_equal(np.asarray(a.params_flat()),
                                  np.asarray(b.params_flat()))


# ----------------------------------------- staging pool + bandwidth gauge
def test_host_to_device_gbps_gauge_published(rng):
    """The producer's periodic blocking transfer sample must land on the
    iterator attribute AND the prefetch.host_to_device_gbps gauge."""
    from deeplearning4j_tpu import telemetry
    telemetry.reset()
    x = rng.normal(size=(64, 4)).astype(np.float64)
    y = np.eye(3, dtype=np.float64)[rng.integers(0, 3, 64)]
    it = DevicePrefetchIterator(
        ListDataSetIterator(features=x, labels=y, batch_size=16),
        depth=2, dtype="float32")
    list(it)
    assert it.host_to_device_gbps > 0
    gauge = telemetry.get_registry().gauge("prefetch.host_to_device_gbps")
    assert gauge.value == pytest.approx(it.host_to_device_gbps)


def test_cast_batches_correct_with_staging_pool(rng):
    """The staging pool must NEVER corrupt shipped batches — on this
    zero-copy CPU backend every aliased slot is retired instead of
    reused, and the data of every batch (two epochs) stays exact."""
    x = rng.normal(size=(160, 4)).astype(np.float64)
    y = np.eye(3, dtype=np.float64)[rng.integers(0, 3, 160)]
    it = DevicePrefetchIterator(
        ListDataSetIterator(features=x, labels=y, batch_size=16),
        depth=2, dtype="float32")
    for _ in range(2):
        for i, b in enumerate(it):
            np.testing.assert_array_equal(
                np.asarray(b.features),
                x[i * 16:(i + 1) * 16].astype(np.float32))
            np.testing.assert_array_equal(
                np.asarray(b.labels),
                y[i * 16:(i + 1) * 16].astype(np.float32))


def test_staging_pool_is_private_to_each_iteration(rng):
    """Regression: the staging pool was shared per-instance, so a stale
    producer thread that outlived an early-broken epoch by one batch
    could stage into the SAME slots as the next epoch's producer and
    overwrite a buffer whose transfer was still in flight. Each __iter__
    must own a fresh pool (the stale producer keeps its old one), and
    data after an early break must stay exact."""
    x = rng.normal(size=(160, 4)).astype(np.float64)
    y = np.eye(3, dtype=np.float64)[rng.integers(0, 3, 160)]
    it = DevicePrefetchIterator(
        ListDataSetIterator(features=x, labels=y, batch_size=16),
        depth=2, dtype="float32")
    for b in it:                     # early break: producer may still be
        break                        # one batch deep in its epoch
    pool_first = it._staging
    it.reset()
    for i, b in enumerate(it):
        np.testing.assert_array_equal(
            np.asarray(b.features),
            x[i * 16:(i + 1) * 16].astype(np.float32))
    assert it._staging is not pool_first


def test_staging_pool_reuses_buffers_on_copying_backend():
    """Pool mechanics against a fake COPYING backend: allocations stop at
    the slot count, every rotated slot waits for its previous transfer,
    and an alias-suspected slot is retired, never overwritten."""
    from deeplearning4j_tpu.datasets.prefetch import (_NEVER_REUSE,
                                                      _StagingPool)

    class Copied:
        def __init__(self):
            self.blocked = False

        def devices(self):
            return [type("D", (), {"platform": "tpu"})()]

        def block_until_ready(self):
            self.blocked = True

    pool = _StagingPool(3)
    a = np.arange(8, dtype=np.float64)
    fakes = []
    for i in range(7):
        slot = pool.stage(a + i, np.float32)
        np.testing.assert_array_equal(slot[0], (a + i).astype(np.float32))
        fake = Copied()
        pool.mark(slot, fake)
        fakes.append(fake)
    assert pool.allocations == 3
    # slots rotated 4 times; each rotation blocked on the prior transfer
    assert sum(f.blocked for f in fakes) == 4

    class Aliased:
        def devices(self):
            return [type("D", (), {"platform": "cpu"})()]

        def unsafe_buffer_pointer(self):
            return self.buf.ctypes.data

    pool2 = _StagingPool(2)
    s1 = pool2.stage(a, np.float32)
    al = Aliased()
    al.buf = s1[0]
    pool2.mark(s1, al)
    assert s1[1] is _NEVER_REUSE
    buf_before = s1[0]
    pool2.stage(a + 1, np.float32)      # fills slot 2
    pool2.mark(pool2.stage(a + 2, np.float32), Copied())  # retires slot 1
    # the aliased buffer was left untouched (the device array owns it)
    np.testing.assert_array_equal(buf_before, a.astype(np.float32))
    assert pool2.allocations == 3
