"""Tensor-parallel (data, model) meshes (parallel/tensor_parallel.py).

Covers the ISSUE 20 tentpole acceptance criteria on the 8-virtual-device
CPU mesh: the Megatron layout rules (attention Q/K/V column- / Wo
row-parallel, MLP ff1/ff2 split, LSTM 4H gate blocks), m=1 bit-identity
with the 1-D data path, (2, 2) float-tolerance parity including the
steps_per_dispatch / zero_stage compositions, per-replica memory
reduction, model-sharded paged decode (token-identical, pool bytes/m per
chip, hot-swap executable reuse), the write_model host-gather seam, the
per-chip ProgramCostIndex division, and the tensor_parallel bench row
guard."""
import os

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models.zoo_extra import (text_generation_lstm,
                                                 transformer_lm)
from deeplearning4j_tpu.parallel import (ParallelWrapper, build_param_specs,
                                         host_gather, per_replica_bytes,
                                         sharded_leaf_count)
from deeplearning4j_tpu.parallel.mesh import make_mesh

V = 29


def _net(seed=11, d_model=16, n_heads=4, max_length=16):
    return transformer_lm(vocab_size=V, d_model=d_model, n_heads=n_heads,
                          n_blocks=1, max_length=max_length, seed=seed,
                          token_input=True).init()


def _data(n=2, b=8, t=8, seed=0):
    rs = np.random.RandomState(seed)
    return [DataSet(rs.randint(1, V, (b, t)).astype(np.int32),
                    np.eye(V)[rs.randint(0, V, (b, t))].astype(np.float32))
            for _ in range(n)]


def _flat(net):
    return np.concatenate([np.asarray(l).ravel()
                           for l in jax.tree.leaves(host_gather(net.params))])


def _maxdiff(a, b):
    return float(np.max(np.abs(a - b))) if a.size else 0.0


# ------------------------------------------------------------ layout rules
def test_transformer_spec_rules():
    net = _net()
    specs = build_param_specs(net, 2)
    names = list(net.vertex_names)
    checked = {"attn": 0, "ff1": 0, "ff2": 0}
    for name, vspecs in zip(names, specs):
        if not isinstance(vspecs, dict):
            continue
        if name.endswith("_attn"):
            checked["attn"] += 1
            for k, s in vspecs.items():
                if k in ("Wq", "Wk", "Wv"):
                    assert s == P(None, "model"), (name, k, s)
                elif k == "Wo":
                    assert s == P("model", None), (name, k, s)
                else:           # biases ride the post-psum add
                    assert s == P(), (name, k, s)
        elif name.endswith("_ff1"):
            checked["ff1"] += 1
            assert vspecs["W"] == P(None, "model")
            assert vspecs["b"] == P("model")
        elif name.endswith("_ff2"):
            checked["ff2"] += 1
            assert vspecs["W"] == P("model", None)
            assert vspecs.get("b", P()) == P()
        else:                   # embeddings / layernorms / head: replicated
            for k, s in vspecs.items():
                assert s == P(), (name, k, s)
    assert all(checked.values()), checked
    assert sharded_leaf_count(specs) >= 6


def test_m1_specs_are_fully_replicated():
    specs = build_param_specs(_net(), 1)
    assert sharded_leaf_count(specs) == 0
    for s in jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P)):
        assert s == P()


def test_indivisible_leaf_degrades_alone():
    """d_model=18 does not divide by m=4, so the attention projections
    fall back to replicated — but the 4*18-wide MLP still shards. The
    rule table degrades per leaf, never the whole mesh."""
    net = _net(d_model=18, n_heads=3)
    specs = build_param_specs(net, 4)
    for name, vspecs in zip(net.vertex_names, specs):
        if isinstance(vspecs, dict) and name.endswith("_attn"):
            for k, s in vspecs.items():
                assert s == P(), (name, k, s)
    assert sharded_leaf_count(specs) > 0


def test_lstm_gate_spec_rules():
    lstm = text_generation_lstm(vocab_size=20, hidden=16).init()
    specs = build_param_specs(lstm, 2)
    gates = 0
    for lspecs in specs:
        if not isinstance(lspecs, dict) or "R" not in lspecs:
            for s in jax.tree.leaves(
                    lspecs, is_leaf=lambda x: isinstance(x, P)):
                assert s == P()     # embedding / dense head: replicated
            continue
        gates += 1
        assert lspecs["W"] == P(None, "model")
        assert lspecs["R"] == P(None, "model")
        assert lspecs["b"] == P("model")
    assert gates >= 1


def test_model_axis_refuses_averaging_and_accumulator():
    net = _net()
    with pytest.raises(ValueError, match="model-axis"):
        ParallelWrapper(net, mesh_shape=(2, 2), training_mode="averaging",
                        averaging_frequency=2)
    with pytest.raises(ValueError, match="model-sharded"):
        ParallelWrapper(net, mesh_shape=(2, 2),
                        gradient_accumulator=object())
    with pytest.raises(ValueError, match="mesh_shape"):
        ParallelWrapper(net, mesh_shape=(2, 2, 2))


# ------------------------------------------------------- training parity
@pytest.fixture(scope="module")
def dp_ref():
    """Flat 4-device data-parallel baseline (the pre-ISSUE-20 path)."""
    net = _net()
    ParallelWrapper(net, mesh_shape=(4,)).fit(_data(), epochs=1)
    return _flat(net)


@pytest.fixture(scope="module")
def tp22():
    """One (2, 2) training shared by the parity / bytes / save tests."""
    net = _net()
    ParallelWrapper(net, mesh_shape=(2, 2)).fit(_data(), epochs=1)
    return net


def test_41_mesh_bit_identical_to_flat_dp(dp_ref):
    """(4, 1) is the SAME program as the 1-D data mesh: m=1 leaves every
    spec P(), so the results must be bitwise equal, not just close."""
    net = _net()
    ParallelWrapper(net, mesh_shape=(4, 1)).fit(_data(), epochs=1)
    np.testing.assert_array_equal(_flat(net), dp_ref)


def test_22_mesh_tracks_dp_and_shrinks_replicas(dp_ref, tp22):
    d = _maxdiff(_flat(tp22), dp_ref)
    assert d < 1e-4, f"(2,2) diverged from dp: maxdiff {d}"
    full = int(dp_ref.nbytes)
    assert per_replica_bytes(tp22.params) < full
    assert per_replica_bytes(tp22.opt_state) < 2 * full


def test_22_composes_with_steps_per_dispatch_and_zero(dp_ref):
    net = _net()
    ParallelWrapper(net, mesh_shape=(2, 2), steps_per_dispatch=2,
                    zero_stage=2).fit(_data(), epochs=1)
    d = _maxdiff(_flat(net), dp_ref)
    assert d < 1e-4, f"(2,2)+spd2+zero2 diverged from dp: maxdiff {d}"


def test_write_model_gathers_model_sharded_params(tp22, tmp_path):
    """Satellite: a zip written from a tensor-parallel net is layout-free
    — restore on an unsharded process round-trips bitwise."""
    from deeplearning4j_tpu.util.serialization import (
        restore_computation_graph, write_model)
    path = str(tmp_path / "tp.zip")
    write_model(tp22, path)
    back = restore_computation_graph(path)
    np.testing.assert_array_equal(_flat(back), _flat(tp22))
    ref_opt = np.concatenate([np.asarray(l).ravel() for l in
                              jax.tree.leaves(host_gather(tp22.opt_state))])
    got_opt = np.concatenate([np.asarray(l).ravel() for l in
                              jax.tree.leaves(back.opt_state)])
    np.testing.assert_allclose(got_opt, ref_opt, atol=1e-6)


# -------------------------------------------------------- sharded decode
@pytest.fixture(scope="module")
def decode_pair():
    from deeplearning4j_tpu.serving.generation.programs import (
        GenerationConfig, GenerationProgramSet)
    net = _net(seed=3)
    cfg = dict(block_len=8, max_seq_len=16, decode_slots=2,
               prefill_batches=(1,))
    mesh = make_mesh((1, 2), ("data", "model"), jax.devices()[:2])
    rep = GenerationProgramSet(net, config=GenerationConfig(**cfg)).warm()
    sh = GenerationProgramSet(net, config=GenerationConfig(**cfg),
                              mesh=mesh).warm()
    return net, rep, sh


def _greedy_tokens(ps, n_decode=3):
    cache, key = ps.make_cache(), ps.fresh_key()
    prompt = np.zeros((1, 16), np.int32)
    prompt[0, :3] = [3, 5, 7]
    t, cache, key = ps.run_prefill(
        cache, prompt, np.array([3], np.int32),
        np.array([[1, 2]], np.int32), np.array([0], np.int32), key,
        np.zeros((1,), np.float32), np.zeros((1,), np.int32))
    out = [int(np.asarray(t)[0])]
    for i in range(n_decode):
        t, cache, key = ps.run_decode(
            cache, np.array([out[-1], 0], np.int32),
            np.array([3 + i, 0], np.int32),
            np.array([[1, 2], [0, 0]], np.int32),
            np.array([True, False]), key,
            np.zeros((2,), np.float32), np.zeros((2,), np.int32))
        out.append(int(np.asarray(t)[0]))
    return out


def test_sharded_decode_token_identical_and_pool_halved(decode_pair):
    _, rep, sh = decode_pair
    assert sh.model_shards == 2 and rep.model_shards == 1
    toks_rep, toks_sh = _greedy_tokens(rep), _greedy_tokens(sh)
    assert toks_rep == toks_sh, (toks_rep, toks_sh)
    assert sh.kv_pool_chip_bytes * 2 == rep.kv_pool_chip_bytes


def test_with_params_from_keeps_mesh_and_executables(decode_pair):
    from deeplearning4j_tpu.telemetry import xla_compile_count
    net, _, sh = decode_pair
    swapped = sh.with_params_from(_net(seed=9))
    assert swapped.model_shards == 2
    assert swapped.kv_pool_chip_bytes == sh.kv_pool_chip_bytes
    compiles0 = xla_compile_count()
    _greedy_tokens(swapped, n_decode=1)
    assert xla_compile_count() == compiles0, \
        "param swap on a sharded set must reuse the warmed executables"


def test_sharded_decode_refusals(decode_pair):
    from deeplearning4j_tpu.serving.generation.programs import (
        GenerationConfig, GenerationProgramSet)
    _, _, sh = decode_pair
    mesh = sh.mesh
    cfg = GenerationConfig(block_len=8, max_seq_len=16, decode_slots=2)
    lstm = text_generation_lstm(vocab_size=20, hidden=16).init()
    with pytest.raises(ValueError, match="paged"):
        GenerationProgramSet(lstm, config=cfg, mesh=mesh)
    odd = _net(d_model=18, n_heads=3)
    with pytest.raises(ValueError, match="n_heads"):
        GenerationProgramSet(odd, config=cfg, mesh=mesh)


# ---------------------------------------------------------- cost index
def test_cost_index_divides_by_model_axis():
    """A tp program's XLA cost counts the whole model; each chip runs
    1/m of it, so the per-chip MFU gauges must fold flops/m."""
    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.telemetry import MetricsRegistry
    from deeplearning4j_tpu.telemetry.perf import ProgramCostIndex
    reg = MetricsRegistry(enabled=True)
    prev = telemetry.set_registry(reg)
    try:
        idx = ProgramCostIndex()
        e = idx.register("fit/tp_step", flops_per_step=2e9,
                         bytes_per_step=1e6, model_axis_size=2,
                         timing_metric="t_ms")
        assert e.flops_per_step == pytest.approx(1e9)
        assert e.bytes_per_step == pytest.approx(5e5)
        assert e.model_axis_size == 2
        for _ in range(4):
            reg.histogram("t_ms").observe(2.0)
        row = {r["path"]: r for r in idx.fold(reg)}["fit/tp_step"]
        assert row["model_axis_size"] == 2
        # 1e9 per-chip flops / 2ms = 0.5 achieved TFLOP/s per chip
        assert row["achieved_tflops"] == pytest.approx(0.5, rel=1e-6)
    finally:
        telemetry.set_registry(prev)


# ------------------------------------------------------------- bench smoke
@pytest.mark.bench_smoke
def test_tensor_parallel_bench_smoke():
    """Tier-1 guard: the tensor_parallel bench row must run end to end
    and report the ~m-x per-replica byte reductions; the (2, 2) step must
    not be catastrophically slower than (4, 1) (shared-CI CPU timings
    swing, so three consecutive failing attempts are required to fail)."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    row = None
    for _ in range(3):
        # shrunk model (d16, 1 block): the guard buys the contract, not
        # the bench's production-sized timings
        row = bench.bench_tensor_parallel(train_batches=2, decode_steps=4,
                                          timeout=300, d_model=16,
                                          n_blocks=1)
        assert row["train_bytes_reduction"] > 1.2
        assert row["kv_pool_reduction"] >= 1.9
        assert row["4x1"]["step_ms"] > 0 and row["2x2"]["step_ms"] > 0
        assert row["decode"]["sharded"]["kv_pool_bytes_per_chip"] < \
            row["decode"]["replicated"]["kv_pool_bytes_per_chip"]
        if row["2x2"]["step_ms"] < 3 * row["4x1"]["step_ms"]:
            return
    pytest.fail(f"(2,2) step catastrophically slow in 3 attempts: {row}")
