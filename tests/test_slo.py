"""SLO watchdogs + training-health watch (ISSUE 13 tentpole) and the
Prometheus exposition-format conformance satellite.

Pinned here:
- multi-window error-budget burn rates from live counters/histograms,
  breach edges firing the flight recorder + slo.* gauges, recovery
  clearing the breach;
- SLO section on the serving GET /metrics + conformant text dump on
  GET /metrics/prometheus;
- exposition-format round trip: _bucket/le histograms parse back, bucket
  counts are cumulative/monotonic, label values escape;
- TrainingWatch detection rules (nonfinite / grad_norm / loss_spike) and
  the acceptance sync-freedom contract: the watch-armed steady-state fit
  records ZERO HostSyncDetector hits on the loop thread and zero
  steady-state recompiles;
- RecompileDetector warnings carry span attrs + source hint (satellite).
"""
import json
import logging
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.telemetry import (ErrorRateSLO, FlightRecorder,
                                          HostSyncDetector, LatencySLO,
                                          MetricsRegistry, RecompileDetector,
                                          SLOWatchdog, TrainingWatch,
                                          set_slo_watchdog,
                                          set_training_watch, span)


@pytest.fixture
def fresh_registry():
    reg = MetricsRegistry(enabled=True)
    prev = telemetry.set_registry(reg)
    try:
        yield reg
    finally:
        telemetry.set_registry(prev)


@pytest.fixture
def recorder(fresh_registry, tmp_path):
    from deeplearning4j_tpu.telemetry import set_flight_recorder
    rec = FlightRecorder(directory=str(tmp_path / "fr"), min_interval_s=0.0)
    prev = set_flight_recorder(rec)
    try:
        yield rec
    finally:
        set_flight_recorder(prev)


def _tiny_net(seed=12):
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd
    conf = (NeuralNetConfiguration(seed=seed, updater=Sgd(0.1))
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


# ------------------------------------------------------------ burn rates
def test_error_rate_burn_and_breach_edge(fresh_registry, recorder):
    reg = fresh_registry
    obj = ErrorRateSLO("admission", good="srv.ok", bad="srv.err",
                       target=0.99)                    # budget = 1%
    wd = SLOWatchdog([obj], windows=(10.0, 60.0), burn_limits=(10.0, 2.0),
                     registry=reg, flight_recorder=recorder)
    # healthy traffic: 1000 good, 0 bad
    reg.counter("srv.ok").inc(1000)
    out = wd.check(now=0.0)
    out = wd.check(now=5.0)
    row = out["objectives"]["admission"]
    assert row["burn_rates"]["10s"] == 0.0
    assert not row["breached"] and out["breached"] == []
    # an outage: 30% of the next 100 requests fail -> burn 30x budget
    reg.counter("srv.ok").inc(70)
    reg.counter("srv.err").inc(30)
    out = wd.check(now=8.0)
    row = out["objectives"]["admission"]
    assert row["burn_rates"]["10s"] == pytest.approx(30.0, rel=0.01)
    assert row["breached"] and "10s" in row["breached_windows"]
    assert out["breached"] == ["admission"]
    snap = reg.snapshot()
    assert snap["gauges"]["slo.admission.breached"]["value"] == 1.0
    assert snap["gauges"]["slo.admission.burn_rate_10s"]["value"] == \
        pytest.approx(30.0, rel=0.01)
    assert snap["counters"]["slo.breaches"] == 1
    # the breach edge fired the flight recorder exactly once
    assert len(recorder.dumps) == 1
    dump = json.load(open(recorder.dumps[0]))
    assert dump["trigger"] == "slo_breach_admission"
    # recovery: healthy traffic pushes the window burn back under limit
    reg.counter("srv.ok").inc(5000)
    wd.check(now=30.0)
    out = wd.check(now=40.0)           # 10s window now all-healthy
    assert not out["objectives"]["admission"]["breached"]
    assert reg.gauge("slo.admission.breached").value == 0.0
    # no second dump without a new edge
    assert len(recorder.dumps) == 1


def test_latency_slo_reads_histogram_buckets(fresh_registry, recorder):
    reg = fresh_registry
    h = reg.histogram("serving.m.latency_ms")
    obj = LatencySLO("p99_latency", "serving.m.latency_ms",
                     threshold_ms=50.0, target=0.9)    # budget = 10%
    wd = SLOWatchdog([obj], windows=(10.0,), burn_limits=(3.0,),
                     registry=reg, flight_recorder=recorder)
    for _ in range(100):
        h.observe(5.0)                                 # all fast
    wd.check(now=0.0)
    out = wd.check(now=5.0)
    assert out["objectives"]["p99_latency"]["burn_rates"]["10s"] == 0.0
    for _ in range(50):
        h.observe(500.0)                               # latency cliff
    out = wd.check(now=8.0)
    row = out["objectives"]["p99_latency"]
    # 50 of 50 new observations over threshold -> bad_frac 1.0 / 0.1 = 10x
    assert row["burn_rates"]["10s"] == pytest.approx(10.0, rel=0.01)
    assert row["breached"]


def test_watchdog_single_sample_window_cannot_breach(fresh_registry):
    reg = fresh_registry
    reg.counter("bad").inc(100)
    wd = SLOWatchdog([ErrorRateSLO("x", good="good", bad="bad",
                                   target=0.999)],
                     windows=(10.0,), burn_limits=(1.0,), registry=reg)
    out = wd.check(now=0.0)            # one sample: no delta, no verdict
    assert not out["objectives"]["x"]["breached"]


def test_watchdog_background_thread_and_duplicate_names():
    reg = MetricsRegistry(enabled=True)
    with pytest.raises(ValueError, match="duplicate"):
        SLOWatchdog([ErrorRateSLO("a", good="g", bad="b"),
                     ErrorRateSLO("a", good="g2", bad="b2")], registry=reg)
    wd = SLOWatchdog([ErrorRateSLO("a", good="g", bad="b")], registry=reg)
    wd.start(period_s=0.01)
    import time
    deadline = time.monotonic() + 5.0
    while not wd.snapshot() and time.monotonic() < deadline:
        time.sleep(0.01)
    wd.stop()
    assert "objectives" in wd.snapshot()


# ----------------------------------------------------- /metrics surfacing
def test_http_metrics_carries_slo_and_prometheus_route(fresh_registry,
                                                       recorder):
    import urllib.request
    from deeplearning4j_tpu.serving import InferenceEngine, ServingHTTPServer
    net = _tiny_net(seed=21)
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(4,),
                          batch_window_ms=0.5)
    wd = SLOWatchdog([LatencySLO("predict", "serving.default.latency_ms",
                                 threshold_ms=1000.0, target=0.99)],
                     registry=fresh_registry, flight_recorder=recorder)
    prev = set_slo_watchdog(wd)
    srv = ServingHTTPServer(engine=eng)
    base = f"http://127.0.0.1:{srv.start()}"
    try:
        x = np.random.default_rng(3).normal(size=(2, 4)).astype(np.float32)
        eng.predict(x)
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            m = json.loads(r.read())
        assert "predict" in m["slo"]["objectives"]
        assert "burn_rates" in m["slo"]["objectives"]["predict"]
        with urllib.request.urlopen(base + "/metrics/prometheus",
                                    timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        assert "dl4j_tpu_slo_predict_breached 0.0" in text
        assert re.search(
            r'dl4j_tpu_serving_default_latency_ms_bucket\{le="\+Inf"\} \d+',
            text)
    finally:
        srv.stop()
        set_slo_watchdog(prev)


# ------------------------------------------- exposition-format round trip
def _parse_prometheus(text):
    """Minimal exposition-format parser for the round-trip test: returns
    {metric: {(labelset): value}} and {metric: type}."""
    values, types = {}, {}
    for line in text.strip().splitlines():
        if line.startswith("# TYPE"):
            _, _, name, typ = line.split()
            types[name] = typ
            continue
        if line.startswith("#"):
            continue
        m = re.match(r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
                     r'(?:\{(.*)\})?\s+(\S+)$', line)
        assert m, f"unparseable exposition line: {line!r}"
        name, labels, val = m.group(1), m.group(2) or "", m.group(3)
        parsed = ()
        if labels:
            for part in re.findall(r'(\w+)="((?:[^"\\]|\\.)*)"', labels):
                key, raw = part
                unescaped = (raw.replace("\\n", "\n").replace('\\"', '"')
                             .replace("\\\\", "\\"))
                parsed += ((key, unescaped),)
        values.setdefault(name, {})[parsed] = float(val)
    return values, types


def test_prometheus_round_trip_conformance(fresh_registry):
    reg = fresh_registry
    reg.counter("train.iterations").inc(42)
    reg.gauge("queue.depth").set(3.5)
    h = reg.histogram("lat_ms")
    for v in (0.2, 0.7, 3.0, 30.0, 77.0, 1e5):
        h.observe(v)
    values, types = _parse_prometheus(reg.to_prometheus_text())
    assert types["dl4j_tpu_train_iterations"] == "counter"
    assert types["dl4j_tpu_lat_ms"] == "histogram"
    assert values["dl4j_tpu_train_iterations"][()] == 42
    buckets = values["dl4j_tpu_lat_ms_bucket"]
    # cumulative + monotone nondecreasing in le order, +Inf == count
    by_le = {dict(k)["le"]: v for k, v in buckets.items()}
    bounds = [le for le in by_le if le != "+Inf"]
    ordered = sorted(bounds, key=float)
    counts = [by_le[le] for le in ordered]
    assert counts == sorted(counts)
    assert by_le["+Inf"] == values["dl4j_tpu_lat_ms_count"][()] == 6
    assert by_le["0.5"] == 1 and by_le["1"] == 2 and by_le["50"] == 4
    assert values["dl4j_tpu_lat_ms_sum"][()] == pytest.approx(h.sum)
    # exact threshold accounting the SLO layer relies on
    assert h.count_le(50.0) == 4
    assert h.count_le(1e9) == 6


def test_prometheus_label_escaping():
    from deeplearning4j_tpu.telemetry.registry import escape_label_value
    assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    # parses back through the round-trip parser
    line = f'm{{k="{escape_label_value(chr(34) + "x" + chr(92))}"}} 1'
    values, _ = _parse_prometheus("# TYPE m gauge\n" + line)
    assert dict(list(values["m"].keys())[0])["k"] == '"x\\'


# ------------------------------------------------------- training watch
def _health(loss, gsq, nonfin):
    return np.array([loss, gsq, nonfin], np.float32)


def test_training_watch_detection_rules(fresh_registry, recorder):
    w = TrainingWatch(window=1, grad_norm_limit=10.0, loss_spike_factor=5.0,
                      registry=fresh_registry, flight_recorder=recorder)
    for it in range(6):
        w.on_health(it, _health(1.0, 4.0, 0))          # healthy history
    assert w.drain() and w.healthy
    w.on_health(6, _health(1.0, 400.0, 0))             # |g| = 20 > 10
    w.on_health(7, _health(50.0, 4.0, 0))              # 50 > 5 * median(1)
    w.on_health(8, _health(float("nan"), 4.0, 2))      # nonfinite
    assert w.drain()
    reasons = [u["reason"] for u in w.unhealthy]
    assert reasons == ["grad_norm", "loss_spike", "nonfinite"]
    assert w.unhealthy[0]["iteration"] == 6
    snap = fresh_registry.snapshot()
    assert snap["counters"]["training_watch.unhealthy"] == 3
    assert snap["counters"]["training_watch.unhealthy.nonfinite"] == 1
    assert snap["gauges"]["training_watch.healthy"]["value"] == 0.0
    assert recorder.dumps                       # evidence shipped
    w.close()


def test_training_watch_window_boundary_flush(fresh_registry):
    w = TrainingWatch(window=8, loss_spike_factor=None,
                      registry=fresh_registry)
    for it in range(7):
        w.on_health(it, _health(1.0, 1.0, 0))
    assert w._buffered == 7                     # below window: buffered
    w.on_health(7, _health(1.0, 1.0, 0))
    assert w._buffered == 0                     # boundary: handed off
    # fused windows count k steps at once
    w.on_health(8, np.ones((8, 3), np.float32), k=8)
    assert w._buffered == 0
    assert w.drain()
    assert w.steps_seen == 16
    w.close()


def test_training_health_vec_in_program():
    from deeplearning4j_tpu.telemetry.slo import training_health_vec
    grads = {"w": jnp.array([3.0, 4.0]), "b": jnp.array([jnp.inf])}
    v = np.asarray(jax.jit(training_health_vec)(jnp.float32(2.5), grads))
    assert v[0] == 2.5
    assert not np.isfinite(v[1])               # inf**2 rides the norm
    assert v[2] == 1                           # one nonfinite grad value
    clean = {"w": jnp.array([3.0, 4.0])}
    v = np.asarray(training_health_vec(jnp.float32(1.0), clean))
    assert v[1] == pytest.approx(25.0) and v[2] == 0


# --------------------------------------- acceptance: sync-free + no retrace
def test_watch_armed_fit_sync_free_and_zero_recompiles(fresh_registry, rng):
    """Acceptance: the watch-armed steady-state fit records ZERO
    HostSyncDetector hits on the loop thread and zero steady-state
    recompiles — in per-step AND fused-window mode (the health vector
    rides the program; materialization happens on the watch's worker)."""
    from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=32)]

    def it():
        return ListDataSetIterator(features=x, labels=y, batch_size=8)

    for k in (1, 2):
        net = _tiny_net()
        watch = TrainingWatch(window=2, registry=fresh_registry)
        prev = set_training_watch(watch)
        try:
            # warm-up epoch compiles the health-carrying program
            net.fit(iterator=it(), epochs=1, steps_per_dispatch=k,
                    async_prefetch=False)
            with HostSyncDetector(action="count") as sync_det, \
                    RecompileDetector(allowed=0, warn=False) as comp_det:
                net.fit(iterator=it(), epochs=1, steps_per_dispatch=k,
                        async_prefetch=False)
            assert watch.drain()
            assert sync_det.count == 0, \
                f"K={k}: syncs at " \
                f"{[e['span_path'] for e in sync_det.events]}"
            assert comp_det.count == 0, f"K={k}: {comp_det.events}"
            assert watch.steps_seen == 8    # second fit's 4 steps/epoch x2
        finally:
            set_training_watch(prev)
            watch.close()


# --------------------------------------- RecompileDetector enrichment (sat)
def test_recompile_warning_carries_span_attrs_and_source(fresh_registry,
                                                         caplog):
    f = jax.jit(lambda a: (a * 2.0).sum())
    with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
        with RecompileDetector(allowed=0) as det:
            with span("decode_loop", model="lm", iteration=14):
                f(jnp.ones((7,), jnp.float32))     # fresh shape: retrace
    assert det.count >= 1
    ev = det.events[0]
    assert ev["span_attrs"]["model"] == "lm"
    assert ev["span_attrs"]["iteration"] == 14
    assert "test_slo.py" in ev["source"]           # this file drove it
    msg = "\n".join(r.message for r in caplog.records)
    assert "model" in msg and "test_slo.py" in msg
