"""Stage-1 tests: weight init stats, activations, losses, config serde,
flat-param round trip (SURVEY.md §7 stage 1)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerConfiguration,
                                MultiLayerNetwork, NeuralNetConfiguration)
from deeplearning4j_tpu.nn.activations import get_activation, activation_names
from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.losses import get_loss, loss_names
from deeplearning4j_tpu.nn.weights import init_weights, NormalDistribution
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd


def test_weight_init_stats():
    rng = jax.random.PRNGKey(0)
    w = init_weights(rng, (200, 300), "xavier", 200, 300)
    assert abs(float(jnp.std(w)) - np.sqrt(2.0 / 500)) < 0.002
    w = init_weights(rng, (200, 300), "relu", 200, 300)
    assert abs(float(jnp.std(w)) - np.sqrt(2.0 / 200)) < 0.005
    w = init_weights(rng, (50, 50), "zero", 50, 50)
    assert float(jnp.max(jnp.abs(w))) == 0.0
    w = init_weights(rng, (100, 100), "xavier_uniform", 100, 100)
    lim = np.sqrt(6.0 / 200)
    assert float(jnp.max(w)) <= lim and float(jnp.min(w)) >= -lim
    w = init_weights(rng, (500, 100), "distribution", 500, 100,
                     distribution=NormalDistribution(2.0, 0.1))
    assert abs(float(jnp.mean(w)) - 2.0) < 0.01


def test_activations_all_finite():
    x = jnp.linspace(-4, 4, 64)
    for name in activation_names():
        y = get_activation(name)(x)
        assert jnp.all(jnp.isfinite(y)), name


def test_rationaltanh_close_to_scaled_tanh():
    x = jnp.linspace(-3, 3, 50)
    approx = get_activation("rationaltanh")(x)
    exact = 1.7159 * jnp.tanh(2 * x / 3)
    assert float(jnp.max(jnp.abs(approx - exact))) < 0.1


def test_losses_basic():
    labels = jnp.array([[0.0, 1.0], [1.0, 0.0]])
    logits = jnp.array([[-2.0, 2.0], [3.0, -1.0]])
    mc = get_loss("mcxent")(labels, logits, "softmax", None)
    assert mc.shape == (2,)
    assert float(jnp.max(mc)) < 0.1  # confident correct predictions
    mse = get_loss("mse")(labels, labels, "identity", None)
    assert float(jnp.max(jnp.abs(mse))) == 0.0
    # masked loss zeroes masked-out examples' contributions
    xent = get_loss("xent")(labels, logits, "sigmoid", jnp.array([1.0, 0.0]))
    assert float(xent[1]) == 0.0


def _mlp_conf(**kw):
    return (NeuralNetConfiguration(seed=42, updater=Adam(1e-2),
                                   weight_init="xavier", **kw)
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())


def test_config_json_round_trip():
    conf = _mlp_conf(l2=1e-4)
    s = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(s)
    assert conf2.to_json() == s
    assert conf2.layers[0].n_out == 8
    assert conf2.layers[0].activation == "tanh"
    assert isinstance(conf2.updater, Adam)
    # round-tripped config builds an identical network
    n1, n2 = MultiLayerNetwork(conf).init(), MultiLayerNetwork(conf2).init()
    assert np.allclose(np.asarray(n1.params_flat()), np.asarray(n2.params_flat()))


def test_flat_param_round_trip():
    net = MultiLayerNetwork(_mlp_conf()).init()
    flat = net.params_flat()
    assert flat.shape == (4 * 8 + 8 + 8 * 3 + 3,)
    assert net.num_params() == flat.shape[0]
    x = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
    out1 = np.asarray(net.output(x))
    net.set_params_flat(jnp.asarray(np.asarray(flat)))
    out2 = np.asarray(net.output(x))
    assert np.allclose(out1, out2)
    # perturbing flat params changes output
    net.set_params_flat(flat + 0.1)
    assert not np.allclose(out1, np.asarray(net.output(x)))


def test_cascade_defaults():
    conf = (NeuralNetConfiguration(seed=1, activation="relu", l2=0.5,
                                   weight_init="relu")
            .list(DenseLayer(n_in=4, n_out=4),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    assert conf.layers[0].activation == "relu"     # cascaded
    assert conf.layers[1].activation == "softmax"  # per-layer override wins
    assert conf.layers[0].l2 == 0.5
    assert conf.layers[0].weight_init == "relu"


def test_unknown_updater_and_compute_dtype_fail_clearly():
    """Misconfigurations fail at build time naming the alternatives, not as
    opaque KeyError/dtype traces at first use."""
    from deeplearning4j_tpu import NeuralNetConfiguration
    with pytest.raises(ValueError, match="adamm"):
        NeuralNetConfiguration(seed=1, updater="adamm")
    with pytest.raises(ValueError, match="bf17"):
        NeuralNetConfiguration(seed=1, compute_dtype="bf17")
