"""ComputationGraph tests (mirror reference TestComputationGraphNetwork,
GradientCheckTestsComputationGraph, zoo model build+step tests)."""
import numpy as np
import pytest

from deeplearning4j_tpu import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.graph.graph import ComputationGraph
from deeplearning4j_tpu.nn.graph.vertices import (ElementWiseVertex,
                                                  L2NormalizeVertex,
                                                  MergeVertex, SubsetVertex)
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd

R = np.random.default_rng(7)


def _simple_graph(updater=None, dtype="float32"):
    g = (NeuralNetConfiguration(seed=5, updater=updater or Adam(5e-3), dtype=dtype)
         .graph_builder()
         .add_inputs("in")
         .add_layer("d1", DenseLayer(n_out=16, activation="tanh"), "in")
         .add_layer("d2", DenseLayer(n_out=16, activation="relu"), "in")
         .add_vertex("merge", MergeVertex(), "d1", "d2")
         .add_layer("out", OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
                    "merge")
         .set_outputs("out")
         .set_input_types(InputType.feed_forward(4)))
    return ComputationGraph(g.build()).init()


def test_graph_forward_shapes_and_fit():
    net = _simple_graph()
    x = R.normal(size=(32, 4)).astype(np.float32)
    yi = (x.sum(-1) > 0).astype(int) + (x[:, 0] > 1).astype(int)
    y = np.eye(3, dtype=np.float32)[yi]
    out = np.asarray(net.output(x))
    assert out.shape == (32, 3)
    assert np.allclose(out.sum(-1), 1.0, atol=1e-5)
    s0 = net.score(x, y)
    net.fit(x, y, epochs=30, batch_size=32)
    assert net.score(x, y) < s0
    ev = net.evaluate(x, y)
    assert ev.accuracy() > 0.8


def test_graph_json_round_trip():
    net = _simple_graph()
    js = net.conf.to_json()
    conf2 = ComputationGraphConfiguration.from_json(js)
    net2 = ComputationGraph(conf2).init()
    assert net2.num_params() == net.num_params()
    net2.set_params_flat(net.params_flat())
    x = R.normal(size=(5, 4)).astype(np.float32)
    assert np.allclose(np.asarray(net.output(x)), np.asarray(net2.output(x)),
                       atol=1e-6)


def test_multi_input_multi_output():
    g = (NeuralNetConfiguration(seed=3, updater=Sgd(0.1))
         .graph_builder()
         .add_inputs("inA", "inB")
         .add_layer("dA", DenseLayer(n_out=8, activation="tanh"), "inA")
         .add_layer("dB", DenseLayer(n_out=8, activation="tanh"), "inB")
         .add_vertex("sum", ElementWiseVertex(op="add"), "dA", "dB")
         .add_layer("out1", OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
                    "sum")
         .add_layer("out2", OutputLayer(n_out=1, activation="identity", loss="mse"),
                    "sum")
         .set_outputs("out1", "out2")
         .set_input_types(InputType.feed_forward(4), InputType.feed_forward(6)))
    net = ComputationGraph(g.build()).init()
    xa = R.normal(size=(16, 4)).astype(np.float32)
    xb = R.normal(size=(16, 6)).astype(np.float32)
    y1 = np.eye(2, dtype=np.float32)[R.integers(0, 2, 16)]
    y2 = R.normal(size=(16, 1)).astype(np.float32)
    o1, o2 = net.output(xa, xb)
    assert np.asarray(o1).shape == (16, 2)
    assert np.asarray(o2).shape == (16, 1)
    s0 = net.score([xa, xb], [y1, y2])
    net.fit([xa, xb], [y1, y2], epochs=20)
    assert net.score([xa, xb], [y1, y2]) < s0


def test_vertices_subset_l2norm():
    g = (NeuralNetConfiguration(seed=3, updater=Sgd(0.1))
         .graph_builder()
         .add_inputs("in")
         .add_vertex("subset", SubsetVertex(from_idx=1, to_idx=2), "in")
         .add_vertex("l2n", L2NormalizeVertex(), "subset")
         .add_layer("out", OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
                    "l2n")
         .set_outputs("out")
         .set_input_types(InputType.feed_forward(4)))
    net = ComputationGraph(g.build()).init()
    x = R.normal(size=(8, 4)).astype(np.float32)
    acts = net.feed_forward(x)
    assert np.asarray(acts["subset"]).shape == (8, 2)
    norms = np.linalg.norm(np.asarray(acts["l2n"]), axis=-1)
    assert np.allclose(norms, 1.0, atol=1e-4)


def test_graph_gradient_check():
    from deeplearning4j_tpu.util.gradcheck import check_gradients
    net = _simple_graph(updater=Sgd(0.1), dtype="float64")
    x = R.normal(size=(6, 4))
    y = np.eye(3)[R.integers(0, 3, 6)]
    assert check_gradients(net, x, y, print_results=True)


@pytest.mark.slow
def test_resnet50_builds_and_steps():
    from deeplearning4j_tpu.models.zoo import resnet50
    net = resnet50(n_classes=10, height=32, width=32, channels=3).init()
    assert net.num_params() > 23_000_000
    x = R.normal(size=(2, 32, 32, 3)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[R.integers(0, 10, 2)]
    out = np.asarray(net.output(x))
    assert out.shape == (2, 10)
    s0 = net.score(x, y)
    net.fit(x, y, epochs=2)
    assert np.isfinite(net.score(x, y))


def test_simple_cnn_and_vgg_build():
    from deeplearning4j_tpu.models.zoo import simple_cnn, vgg16
    net = simple_cnn(n_classes=5, height=16, width=16, channels=3).init()
    x = R.normal(size=(2, 16, 16, 3)).astype(np.float32)
    assert np.asarray(net.output(x)).shape == (2, 5)
    v = vgg16(n_classes=10, height=32, width=32, channels=3).init()
    assert np.asarray(v.output(x.repeat(2, axis=1).repeat(2, axis=2))).shape == (2, 10)


def test_multi_output_evaluate_returns_per_output_evaluations():
    """Reference evaluate is single-output; the TPU build returns one
    Evaluation per network output for multi-output graphs."""
    g = (NeuralNetConfiguration(seed=5, updater=Adam(5e-3), dtype="float32")
         .graph_builder()
         .add_inputs("in")
         .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
         .add_layer("o1", OutputLayer(n_out=2, activation="softmax", loss="mcxent"), "d")
         .add_layer("o2", OutputLayer(n_out=3, activation="softmax", loss="mcxent"), "d")
         .set_outputs("o1", "o2")
         .set_input_types(InputType.feed_forward(4)))
    net = ComputationGraph(g.build()).init()
    x = R.normal(size=(20, 4)).astype(np.float32)
    y1 = np.eye(2, dtype=np.float32)[R.integers(0, 2, 20)]
    y2 = np.eye(3, dtype=np.float32)[R.integers(0, 3, 20)]
    evs = net.evaluate(x, [y1, y2])
    assert len(evs) == 2
    assert 0.0 <= evs[0].accuracy() <= 1.0
    assert 0.0 <= evs[1].accuracy() <= 1.0
    # single-output graphs still return one Evaluation
    single = _simple_graph()
    xs = R.normal(size=(8, 4)).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[R.integers(0, 3, 8)]
    assert hasattr(single.evaluate(xs, ys), "accuracy")


def test_transfer_learning_graph():
    """Reference TransferLearning.GraphBuilder: freeze ancestors, replace the
    output head for new classes, keep surviving weights."""
    from deeplearning4j_tpu.nn.transfer import (FineTuneConfiguration,
                                                TransferLearningGraph)
    src = _simple_graph()
    x = R.normal(size=(24, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[R.integers(0, 3, 24)]
    src.fit(x, y, epochs=5, batch_size=24)

    new = (TransferLearningGraph(src)
           .set_feature_extractor("merge")
           .n_out_replace("out", 5, weight_init="xavier")
           .fine_tune_configuration(FineTuneConfiguration(learning_rate=0.01))
           .build())
    # frozen ancestors kept their trained weights
    for name in ("d1", "d2"):
        si = src.vertex_names.index(name)
        ni = new.vertex_names.index(name)
        np.testing.assert_allclose(np.asarray(new.params[ni]["W"]),
                                   np.asarray(src.params[si]["W"]))
        assert new.layers[ni].frozen
    # new head: 5 classes
    out = np.asarray(new.output(x))
    assert out.shape == (24, 5)
    # training the new net leaves frozen weights untouched
    y5 = np.eye(5, dtype=np.float32)[R.integers(0, 5, 24)]
    before = np.asarray(new.params[new.vertex_names.index("d1")]["W"]).copy()
    head_before = np.asarray(new.params[new.vertex_names.index("out")]["W"]).copy()
    new.fit(x, y5, epochs=3, batch_size=24)
    np.testing.assert_allclose(
        np.asarray(new.params[new.vertex_names.index("d1")]["W"]), before)
    # ...while the replaced head's weights actually moved
    assert not np.allclose(
        np.asarray(new.params[new.vertex_names.index("out")]["W"]),
        head_before)


def test_malformed_graph_fails_at_build_naming_vertex():
    """Eager config validation (reference nn/conf/layers/LayerValidation.java):
    a shape mismatch fails at .build() naming the offending vertex, not as an
    opaque trace-time error."""
    b = (NeuralNetConfiguration(seed=5, updater=Sgd(0.1))
         .graph_builder()
         .add_inputs("in")
         .add_layer("d1", DenseLayer(n_out=16, activation="tanh"), "in")
         .add_layer("d2", DenseLayer(n_out=8, activation="tanh"), "in")
         .add_vertex("ew", ElementWiseVertex(op="add"), "d1", "d2")
         .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                       loss="mcxent"), "ew")
         .set_outputs("out")
         .set_input_types(InputType.feed_forward(4)))
    with pytest.raises(ValueError, match="'ew'"):
        b.build()
