"""serving/generation: paged KV-cache decode with continuous batching.

Pins (ISSUE 9):
  - bit-exactness: greedy decode through the paged-cache path matches
    naive full-recompute decode token-for-token (f32 AND bf16, token-id
    and one-hot embed inputs) — same pinning pattern as
    tests/test_overlap_sync.py;
  - zero recompiles: after warm-up, a mixed stream of prompt lengths and
    generation lengths triggers ZERO backend compiles (asserted via the
    telemetry RecompileDetector, as test_zero_recompiles_after_warmup
    does for forward serving);
  - continuous batching: requests admitted into an in-flight decode batch
    at step boundaries produce the same tokens as isolated decodes;
  - admission-control/deadline/drain semantics carried over from
    serving/engine.py, plus the block-pool exhaustion taxonomy;
  - hot-swap cutover rule: in-flight generations finish on old params,
    new admissions run the new model.

Heavy soak variants are marked ``slow``; tier-1 keeps the same assertions
at a handful-of-requests scale.
"""
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.models.decode import (LSTMDecodeSpec,
                                              TransformerDecodeSpec,
                                              naive_generate,
                                              naive_generate_lstm)
from deeplearning4j_tpu.models.zoo_extra import (text_generation_lstm,
                                                 transformer_lm)
from deeplearning4j_tpu.serving import (BlockPoolExhaustedError,
                                        DrainingError, GenerationConfig,
                                        GenerationEngine, QueueFullError,
                                        ShapeMismatchError,
                                        xla_compile_count)
from deeplearning4j_tpu.serving.generation import BlockAllocator
from deeplearning4j_tpu.telemetry import RecompileDetector, get_registry

R = np.random.default_rng(99)


def _lm(seed=7, vocab=53, d_model=32, n_heads=2, n_blocks=2, max_length=64,
        dtype="float32", token_input=True):
    return transformer_lm(vocab_size=vocab, d_model=d_model,
                          n_heads=n_heads, n_blocks=n_blocks,
                          max_length=max_length, seed=seed, dtype=dtype,
                          token_input=token_input).init()


def _prompts(vocab, sizes, seed=3):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab, size=n).tolist() for n in sizes]


# ------------------------------------------------------- pool + config units
def test_block_allocator():
    a = BlockAllocator(5)              # ids 1..4 usable, 0 is trash
    assert a.total_usable == 4 and a.free_blocks == 4
    got = a.alloc(3)
    assert len(got) == 3 and 0 not in got
    assert a.free_blocks == 1 and a.used_blocks == 3
    with pytest.raises(BlockPoolExhaustedError):
        a.alloc(2)
    a.free(got[:2])
    assert a.free_blocks == 3
    with pytest.raises(ValueError):
        a.free([got[0]])               # double free
    with pytest.raises(ValueError):
        a.free([0])                    # trash block is not freeable
    with pytest.raises(ValueError):
        BlockAllocator(1)


def test_generation_config_plan():
    cfg = GenerationConfig(block_len=16, max_seq_len=100, decode_slots=4,
                           prompt_rungs=(20, 50), prefill_batches=(4, 1, 1))
    assert cfg.capacity == 112                 # rounded up to block_len
    assert cfg.blocks_per_seq == 7
    # rungs round up to block multiples and always include the capacity
    assert cfg.prompt_rungs == (32, 64, 112)
    assert cfg.prefill_batches == (1, 4)
    assert cfg.blocks_needed(10, 6) == 1
    assert cfg.blocks_needed(10, 7) == 2
    assert cfg.prompt_rung(33) == 64
    assert cfg.prefill_rung(3) == 4
    assert cfg.num_blocks == 4 * 7 + 1
    with pytest.raises(ValueError):
        cfg.prompt_rung(113)


# ------------------------------------------- shared read-only engine + pins
@pytest.fixture(scope="module")
def shared_lm():
    """One warmed f32 engine shared by the read-only tests below (every
    AOT warm-up is seconds of tier-1 budget). Tests using it must leave it
    healthy: no stop(), no monkeypatching, no pool reconfiguration."""
    net = _lm(dtype="float32")
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=64,
                           decode_slots=4, prefill_batches=(1, 2),
                           prompt_rungs=(64,))
    yield net, TransformerDecodeSpec(net), eng
    eng.stop()


def test_generation_programs_registered_in_cost_index(shared_lm):
    """ISSUE 15: warm-up registers every generation executable's XLA cost
    analysis in the process cost index (decode step paired with the
    decode_step_ms histogram the scheduler observes; prefill rungs
    cost-only) — read-only against the shared engine."""
    from deeplearning4j_tpu.telemetry.perf import get_cost_index
    idx = get_cost_index()
    e = idx.get("generation.lm.decode_step")
    assert e is not None and e.source == "compiled"
    assert e.flops_per_step and e.flops_per_step > 0
    assert e.timing_metric == "generation.lm.decode_step_ms"
    assert any(p.startswith("generation.lm.prefill.")
               for p in idx.paths())


def test_paged_greedy_bit_identical_to_naive_f32(shared_lm):
    """THE pin: greedy decode through the paged KV cache — sequential AND
    continuous-batched concurrent — matches cache-free full-recompute
    decode token-for-token."""
    net, spec, eng = shared_lm
    prompts = _prompts(53, (5, 9, 13))
    refs = [naive_generate(net, p, 10, pad_to=64, spec=spec)
            for p in prompts]
    req0 = eng.metrics()["lm"]["requests"]
    for p, want in zip(prompts, refs):
        toks, reason = eng.generate(p, max_tokens=10)
        assert reason == "length"
        assert toks == want
    # continuous batching: 6 concurrent clients share 4 decode slots —
    # step-boundary admission + slot backfill must not perturb numerics
    outs = {}

    def client(i):
        st = eng.generate(prompts[i % 3], max_tokens=10, stream=True)
        outs[i] = (list(st), st.finish_reason)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(6):
        assert outs[i][0] == refs[i % 3], f"client {i} diverged"
        assert outs[i][1] == "length"
    snap = eng.metrics()["lm"]
    assert snap["requests"] == req0 + 9
    assert snap["finished"].get("length", 0) >= 9


@pytest.mark.parametrize("dtype,token_input", [("bfloat16", True),
                                               ("float32", False)])
def test_paged_greedy_bit_identical_dtypes_and_embeds(dtype, token_input):
    """Same pin in bf16 and through the legacy one-hot embed input."""
    net = _lm(seed=11, vocab=37, d_model=16, n_blocks=1, max_length=32,
              dtype=dtype, token_input=token_input)
    spec = TransformerDecodeSpec(net)
    prompts = _prompts(37, (4, 7), seed=5)
    refs = [naive_generate(net, p, 8, pad_to=32, spec=spec)
            for p in prompts]
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=32,
                           decode_slots=2, prefill_batches=(1,),
                           prompt_rungs=(32,))
    try:
        for p, want in zip(prompts, refs):
            toks, _ = eng.generate(p, max_tokens=8)
            assert toks == want
    finally:
        eng.stop()


def test_lstm_generation_matches_rnn_time_step():
    """The recurrent leg: engine decode (fixed-shape state cache) matches
    the public rnn_time_step greedy loop token-for-token."""
    net = text_generation_lstm(vocab_size=31, hidden=24, max_length=32,
                               seed=5).init()
    assert LSTMDecodeSpec(net).vocab == 31
    prompts = _prompts(31, (3, 7), seed=11)
    refs = [naive_generate_lstm(net, p, 8) for p in prompts]
    eng = GenerationEngine(net, model_name="charlm", block_len=8,
                           max_seq_len=32, decode_slots=2,
                           prefill_batches=(1, 2), prompt_rungs=(16,))
    try:
        assert eng.models()["charlm"]["adapter"] == "state"
        outs = {}

        def client(i):
            outs[i] = eng.generate(prompts[i % 2], max_tokens=8)[0]

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(4):
            assert outs[i] == refs[i % 2]
    finally:
        eng.stop()


# -------------------------------------------------------- zero recompiles
@pytest.mark.bench_smoke
def test_zero_recompiles_generation_after_warmup():
    """Tier-1 guard (ISSUE acceptance): after warm-up, a mixed stream of
    prompt lengths (two rungs), generation lengths, sampling settings and
    concurrent admissions triggers ZERO backend compiles — asserted via
    the telemetry RecompileDetector AND the process-wide compile counter
    AND the engine's own trace hook."""
    net = _lm(seed=21, vocab=41, d_model=16, n_blocks=1, max_length=64)
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=64,
                           decode_slots=4, prefill_batches=(1, 2),
                           prompt_rungs=(16, 64), seed=3)
    try:
        traces0 = eng.trace_count
        compiles0 = xla_compile_count()
        work = [(3, 5, 0.0, 0), (14, 9, 0.0, 0), (30, 4, 0.7, 5),
                (7, 12, 1.2, 0), (40, 3, 0.0, 2), (2, 17, 0.3, 3)]
        results = {}

        def client(i):
            plen, mx, temp, topk = work[i]
            p = [(i * 7 + j) % 40 + 1 for j in range(plen)]
            st = eng.generate(p, max_tokens=mx, temperature=temp,
                              top_k=topk, stream=True)
            results[i] = (list(st), st.finish_reason)

        with RecompileDetector(allowed=0) as det:
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(work))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        for i, (plen, mx, _, _) in enumerate(work):
            assert len(results[i][0]) == mx
            assert results[i][1] == "length"
            assert all(0 <= t < 41 for t in results[i][0])
        assert det.count == 0, \
            f"steady-state decode compiled: {det.events}"
        assert xla_compile_count() == compiles0
        assert eng.trace_count == traces0, "generation re-traced a program"
        # telemetry mirror: the decode loop published its gauges/counters
        reg = get_registry()
        snap = reg.snapshot()
        assert snap["counters"].get("generation.lm.tokens_out", 0) >= 50
        assert "generation.lm.slot_occupancy" in snap["gauges"]
    finally:
        eng.stop()


# ------------------------------------------------------------- sampling
def test_sampling_modes_and_stop_tokens(shared_lm):
    net, spec, eng = shared_lm
    prompt = [3, 9, 4]
    greedy = naive_generate(net, prompt, 6, pad_to=64, spec=spec)
    # top_k=1 collapses sampling to greedy at ANY temperature
    toks, _ = eng.generate(prompt, max_tokens=6, temperature=5.0,
                           top_k=1)
    assert toks == greedy
    # temperature sampling emits valid ids and the full budget
    toks, reason = eng.generate(prompt, max_tokens=12, temperature=1.0,
                                top_k=4)
    assert reason == "length" and len(toks) == 12
    assert all(0 <= t < 53 for t in toks)
    # stop tokens terminate with reason "stop" and are NOT emitted
    stop = greedy[3]
    toks, reason = eng.generate(prompt, max_tokens=6, stop=[stop])
    assert reason == "stop"
    assert toks == greedy[:greedy.index(stop)]
    assert eng.metrics()["lm"]["finished"].get("stop", 0) >= 1


# ---------------------------------------------- admission control + errors
def test_block_pool_exhaustion_and_queue_taxonomy():
    """Tiny pool: one request's blocks occupy it entirely. The queue
    head-of-line waits for blocks; an over-limit submit while the pool is
    dry raises BlockPoolExhaustedError (429 + retry hint), and a request
    that can NEVER fit fails immediately."""
    net = _lm(seed=41, vocab=29, d_model=16, n_blocks=1, max_length=32)
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=32,
                           decode_slots=2, prefill_batches=(1,),
                           prompt_rungs=(32,), num_blocks=3, queue_limit=1)
    try:
        # within capacity but needs more blocks than the pool HAS: a retry
        # can never help -> immediate 429-with-hint
        with pytest.raises(BlockPoolExhaustedError) as ei:
            eng.generate([1, 2], max_tokens=28)     # 4 blocks, pool has 2
        assert "retry" in str(ei.value)
        # slow decode down so r1 deterministically holds its blocks for
        # the whole submit sequence below (un-slowed it finishes in ms)
        rt = eng._get("lm")
        orig_decode = rt.active_ps.run_decode

        def slow_decode(*a, **k):
            time.sleep(0.01)
            return orig_decode(*a, **k)

        rt.active_ps.run_decode = slow_decode
        # r1 takes both usable blocks (plen 2 + 14 new = 16 = 2 blocks)
        s1 = eng.generate([1, 2], max_tokens=14, stream=True)
        # wait until r1 is admitted (blocks held) before probing the queue
        deadline = time.monotonic() + 5.0
        while eng.metrics()["lm"]["prefills"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.002)
        s2 = eng.generate([3, 4], max_tokens=14, stream=True)   # queued
        with pytest.raises(QueueFullError):          # queue_limit=1, dry pool
            eng.generate([5, 6], max_tokens=14)
        assert eng.metrics()["lm"]["rejected"]["exhausted"] >= 1
        # head-of-line admission once r1's blocks free: both complete
        t1, r1 = s1.result()
        t2, r2 = s2.result()
        assert (len(t1), r1) == (14, "length")
        assert (len(t2), r2) == (14, "length")
    finally:
        eng.stop()


def test_shape_validation(shared_lm):
    _, _, eng = shared_lm                    # capacity 64, prompt rung 64
    with pytest.raises(ShapeMismatchError):
        eng.generate([], max_tokens=4)                  # empty prompt
    with pytest.raises(ShapeMismatchError):
        eng.generate([1] * 65, max_tokens=4)    # > largest prompt rung
    with pytest.raises(ShapeMismatchError):
        eng.generate([1, 2], max_tokens=63)             # > capacity
    with pytest.raises(ShapeMismatchError):
        eng.generate([1, 2], max_tokens=0)


def test_deadline_mid_stream_terminates_cleanly(shared_lm):
    """A deadline expiring mid-generation closes the stream with reason
    'deadline' — the consumer's iteration ENDS (no hang), partial tokens
    stand, and the slot/blocks are released for the next request."""
    net, spec, eng = shared_lm
    # 8ms: long enough to clear admission + one warmed prefill, short
    # enough that no rig decodes all 60 tokens first (each step syncs a
    # token readback) — the deadline must win, whatever the machine speed
    st = eng.generate([1, 2, 3], max_tokens=60, timeout=0.008,
                      stream=True)
    toks = list(st)                      # must terminate on its own
    assert st.finish_reason == "deadline"
    assert len(toks) < 60
    assert st.emitted == len(toks)
    # the slot is free again: a normal request completes afterwards
    toks2, reason = eng.generate([4, 5], max_tokens=3)
    assert (len(toks2), reason) == (3, "length")
    # mid-generation expiry counts as finished; a (rare, loaded-rig)
    # expiry while still queued counts as rejected — either terminates
    m = eng.metrics()["lm"]
    assert (m["finished"].get("deadline", 0)
            + m["rejected"].get("deadline", 0)) >= 1


def test_drain_and_stop_semantics():
    """drain=True completes in-flight + queued work then refuses new
    submissions (503); drain=False terminates everything NOW — either way
    every stream finishes and no caller hangs."""
    net = _lm(seed=53, vocab=29, d_model=16, n_blocks=1, max_length=256)
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=64,
                           decode_slots=1, prefill_batches=(1,),
                           prompt_rungs=(64,))
    st = eng.generate([1, 2], max_tokens=20, stream=True)
    eng.stop(drain=True, timeout=30.0)
    toks, reason = st.result()
    assert (len(toks), reason) == (20, "length")    # drained to completion
    with pytest.raises(DrainingError):
        eng.generate([1], max_tokens=1)

    # 250 tokens of runway: no rig finishes them inside the 10ms window,
    # so stop(drain=False) always lands mid-flight
    eng2 = GenerationEngine(net, model_name="lm", block_len=8,
                            max_seq_len=256, decode_slots=1,
                            prefill_batches=(1,), prompt_rungs=(64,))
    st2 = eng2.generate([1, 2], max_tokens=250, stream=True)
    time.sleep(0.01)                       # let it get in flight
    eng2.stop(drain=False, timeout=5.0)
    toks2 = list(st2)                      # terminates, partial or empty
    assert st2.finish_reason == "shutdown"
    assert len(toks2) < 250


def test_prefill_failure_fails_caller_and_engine_recovers():
    """A device-side program failure must resolve EVERY caller (no hung
    streams), release the failed requests' slots and blocks, and drop the
    cohort (its donated cache may be invalid) so the next admission runs
    on a fresh pool — regression for the admitted-but-not-yet-in-cohort
    window where a prefill exception previously leaked the slot and left
    the stream waiting forever."""
    net = _lm(seed=67, vocab=29, d_model=16, n_blocks=1, max_length=32)
    spec = TransformerDecodeSpec(net)
    want = naive_generate(net, [1, 2, 3], 4, pad_to=32, spec=spec)
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=32,
                           decode_slots=2, prefill_batches=(1,),
                           prompt_rungs=(32,))
    try:
        rt = eng._get("lm")
        orig = rt.active_ps.run_prefill
        calls = {"n": 0}

        def boom(*a, **k):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected device failure")
            return orig(*a, **k)

        rt.active_ps.run_prefill = boom
        st = eng.generate([1, 2, 3], max_tokens=4, stream=True)
        toks, reason = st.result(raise_on_error=False)   # must NOT hang
        assert reason == "error"
        assert isinstance(st.error, RuntimeError)
        assert toks == []
        # slot + blocks released, cohort rebuilt: next request is correct
        toks2, r2 = eng.generate([1, 2, 3], max_tokens=4)
        assert (toks2, r2) == (want, "length")
        assert eng.models()["lm"]["in_flight"] == 0
        snap = eng.metrics()["lm"]
        assert snap["rejected"]["error"] >= 1
        assert snap["finished"].get("error") == 1
    finally:
        eng.stop()


# ----------------------------------------------------------------- hot-swap
def test_hot_swap_cutover_in_flight_on_old_params():
    """The cutover rule: a generation in flight at swap time finishes on
    the OLD params; the next admission runs the new ones. Same-arch swap
    reuses compiled executables (no new traces/compiles)."""
    net_a = _lm(seed=7)
    net_b = _lm(seed=8)            # same arch, different params
    spec_a, spec_b = TransformerDecodeSpec(net_a), TransformerDecodeSpec(net_b)
    prompt = _prompts(53, (6,), seed=9)[0]
    want_a = naive_generate(net_a, prompt, 40, pad_to=64, spec=spec_a)
    want_b = naive_generate(net_b, prompt, 40, pad_to=64, spec=spec_b)
    assert want_a != want_b        # the pin below must be discriminating
    eng = GenerationEngine(net_a, model_name="lm", block_len=8,
                           max_seq_len=64, decode_slots=2,
                           prefill_batches=(1,), prompt_rungs=(64,))
    try:
        traces0 = eng.trace_count
        compiles0 = xla_compile_count()
        st_a = eng.generate(prompt, max_tokens=40, stream=True)
        deadline = time.monotonic() + 5.0        # wait for admission
        while eng.metrics()["lm"]["prefills"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.002)
        version = eng.hot_swap("lm", net_b)
        assert version == 2
        st_b = eng.generate(prompt, max_tokens=40, stream=True)
        toks_a, reason_a = st_a.result()
        toks_b, reason_b = st_b.result()
        assert (toks_a, reason_a) == (want_a, "length"), \
            "in-flight generation must finish on the OLD params"
        assert (toks_b, reason_b) == (want_b, "length"), \
            "post-swap admission must run the NEW params"
        assert eng.trace_count == traces0          # executables reused
        assert xla_compile_count() == compiles0
        assert eng.metrics()["lm"]["hot_swaps"] == 1
    finally:
        eng.stop()


def _swap_soak(n_swaps: int, clients: int, max_new: int):
    net_a = _lm(seed=7)
    net_b = _lm(seed=8)
    spec_a, spec_b = TransformerDecodeSpec(net_a), TransformerDecodeSpec(net_b)
    prompts = _prompts(53, (5, 9), seed=13)
    want = {}
    for i, p in enumerate(prompts):
        want[i] = (naive_generate(net_a, p, max_new, pad_to=64, spec=spec_a),
                   naive_generate(net_b, p, max_new, pad_to=64, spec=spec_b))
    eng = GenerationEngine(net_a, model_name="lm", block_len=8,
                           max_seq_len=64, decode_slots=4,
                           prefill_batches=(1, 2), prompt_rungs=(64,))
    errors = []
    stop_flag = threading.Event()

    def client(tid):
        k = tid
        while not stop_flag.is_set():
            i = k % 2
            toks, reason = eng.generate(prompts[i], max_tokens=max_new)
            if reason != "length" or \
                    (toks != want[i][0] and toks != want[i][1]):
                errors.append((tid, k, reason, toks))
                return
            k += 1

    try:
        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(clients)]
        for t in threads:
            t.start()
        nets = [net_b, net_a]
        for s in range(n_swaps):
            time.sleep(0.05)
            eng.hot_swap("lm", nets[s % 2])
        stop_flag.set()
        for t in threads:
            t.join(timeout=30.0)
        assert not errors, f"hot-swap soak diverged: {errors[:3]}"
        assert eng.metrics()["lm"]["hot_swaps"] == n_swaps
    finally:
        stop_flag.set()
        eng.stop()


@pytest.mark.slow   # tier-1 keeps the hot-swap contract via
# test_hot_swap_cutover_in_flight_on_old_params; the 20-swap soak below
# covers the under-load interleaving
def test_hot_swap_under_decode_soak_fast():
    """Fast variant of the hot-swap-under-decode soak: swaps land
    while clients stream; every result must match ONE of the two param
    sets exactly — never a mixture."""
    _swap_soak(n_swaps=3, clients=3, max_new=12)


@pytest.mark.slow
def test_hot_swap_under_decode_soak():
    _swap_soak(n_swaps=20, clients=6, max_new=24)


# ------------------------------------------------------------------- bench
@pytest.mark.bench_smoke
def test_generate_bench_smoke():
    """Tier-1 guard for the generate_tokens_per_sec row: both modes run end
    to end, emit tokens, and stay at zero steady-state compiles. The >=3x
    continuous-vs-sequential acceptance ratio is measured by bench.py on
    the real rig at full duration; CI pins 'not broken'."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    # prefix=False: the prefix sub-rows have their OWN tier-1 guard
    # (tests/test_prefix_cache.py::test_prefix_cache_bench_smoke) — no
    # need to warm the d=128 prefix-phase engine twice per tier-1 run
    row = bench.bench_generate(duration=0.8, clients=3, decode_slots=4,
                               max_new=8, prompt_len=4, prefix=False)
    assert row["continuous_tokens_per_sec"] > 0
    assert row["sequential_tokens_per_sec"] > 0
    assert row["continuous_steady_state_compiles"] == 0
    assert row["sequential_steady_state_compiles"] == 0
    assert row["continuous_ttft_p50_ms"] > 0


@pytest.mark.slow
def test_generation_hammer_soak():
    """Sustained mixed traffic: many clients, mixed prompt rungs and
    sampling settings, full-length streams — result integrity + zero
    recompiles over thousands of tokens."""
    net = _lm(seed=61, vocab=41, d_model=16, n_blocks=1, max_length=64)
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=64,
                           decode_slots=8, prefill_batches=(1, 2, 4),
                           prompt_rungs=(16, 64), queue_limit=4096)
    try:
        compiles0 = xla_compile_count()
        stop_at = time.monotonic() + 8.0
        errors = []

        def client(tid):
            rng = np.random.default_rng(tid)
            while time.monotonic() < stop_at:
                plen = int(rng.integers(1, 40))
                mx = int(rng.integers(1, 20))
                temp = float(rng.choice([0.0, 0.8]))
                toks, reason = eng.generate(
                    rng.integers(1, 41, size=plen).tolist(),
                    max_tokens=mx, temperature=temp, timeout=60.0)
                if reason != "length" or len(toks) != mx or \
                        not all(0 <= t < 41 for t in toks):
                    errors.append((tid, plen, mx, reason, len(toks)))
                    return

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
        assert xla_compile_count() == compiles0
        assert eng.metrics()["lm"]["tokens_out"] > 500
    finally:
        eng.stop()
