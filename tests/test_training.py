"""Training-loop tests: updater math vs hand-rolled expectations, convergence
on a toy problem, listeners, schedules, clipping (SURVEY.md §7 stage 3)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.listeners import (CollectScoresIterationListener,
                                                   PerformanceListener,
                                                   ScoreIterationListener)
from deeplearning4j_tpu.optimize.updaters import (Adam, AdaDelta, AdaGrad,
                                                  AdaMax, MapSchedule,
                                                  MultiLayerUpdater, Nadam,
                                                  Nesterovs, NoOp, RmsProp,
                                                  Sgd, StepSchedule,
                                                  normalize_gradients)

ALL_RULES = [Sgd(0.1), Adam(1e-2), AdaMax(1e-2), AdaDelta(), Nesterovs(0.1),
             Nadam(1e-2), AdaGrad(0.1), RmsProp(0.05), NoOp()]


def _xor_data(n=200, seed=0):
    r = np.random.default_rng(seed)
    x = r.uniform(-1, 1, size=(n, 2)).astype(np.float32)
    y_idx = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.int64)
    y = np.eye(2, dtype=np.float32)[y_idx]
    return x, y


@pytest.mark.parametrize("rule", ALL_RULES, ids=lambda r: type(r).__name__)
def test_updater_rules_decrease_loss(rule):
    x, y = _xor_data(128)
    conf = (NeuralNetConfiguration(seed=7, updater=rule, weight_init="xavier")
            .list(DenseLayer(n_in=2, n_out=16, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    s0 = net.score(x, y)
    net.fit(x, y, epochs=30 if isinstance(rule, (NoOp, AdaDelta)) else 15,
            batch_size=64)
    s1 = net.score(x, y)
    assert s1 < s0, f"{type(rule).__name__}: {s0} -> {s1}"


def test_sgd_matches_manual_math():
    """One SGD step must equal p - lr*grad exactly."""
    x, y = _xor_data(16)
    conf = (NeuralNetConfiguration(seed=3, updater=Sgd(0.5))
            .list(DenseLayer(n_in=2, n_out=4, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    params0 = jax.tree.map(lambda a: np.asarray(a), net.params)

    def lf(p):
        return net.loss_fn(p, net.state, jnp.asarray(x), jnp.asarray(y),
                           train=False)[0]
    grads = jax.grad(lf)(net.params)
    net.fit(x, y, epochs=1, batch_size=16)
    for p0, g, p1 in zip(params0, grads, net.params):
        for k in p0:
            # dropout off => train/eval forward identical; exact match expected
            assert np.allclose(np.asarray(p1[k]), p0[k] - 0.5 * np.asarray(g[k]),
                               atol=1e-6), k


def test_adam_single_step_math():
    rule = Adam(learning_rate=0.1, beta1=0.9, beta2=0.999, epsilon=1e-8)
    g = jnp.array([1.0, -2.0])
    s = rule.init_one(g)
    upd, s2 = rule.update_one(g, s, 0.1, 0)
    m = 0.1 * np.array([1.0, -2.0])
    v = 0.001 * np.array([1.0, 4.0])
    mhat, vhat = m / 0.1, v / 0.001
    expect = 0.1 * mhat / (np.sqrt(vhat) + 1e-8)
    assert np.allclose(np.asarray(upd), expect, atol=1e-7)


def test_schedules():
    step_sched = StepSchedule(decay_rate=0.5, step_size=10)
    assert float(step_sched(1.0, 0)) == 1.0
    assert float(step_sched(1.0, 10)) == 0.5
    assert float(step_sched(1.0, 25)) == 0.25
    m = MapSchedule({"0": 1.0, "5": 0.1, "20": 0.01})
    assert float(m(1.0, 3)) == 1.0
    assert float(m(1.0, 7)) == pytest.approx(0.1)
    assert float(m(1.0, 30)) == pytest.approx(0.01)


def test_gradient_clipping_modes():
    grads = ({"W": jnp.array([[3.0, -4.0]]), "b": jnp.array([10.0])},)
    out = normalize_gradients(grads, "clipelementwiseabsolutevalue", 2.0)
    assert float(jnp.max(jnp.abs(out[0]["W"]))) <= 2.0
    assert float(out[0]["b"][0]) == 2.0
    out = normalize_gradients(grads, "clipl2perparamtype", 1.0)
    assert float(jnp.linalg.norm(out[0]["W"])) <= 1.0 + 1e-5
    out = normalize_gradients(grads, "renormalizel2perlayer", 1.0)
    total = np.sqrt(sum(float(jnp.sum(v * v)) for v in out[0].values()))
    assert abs(total - 1.0) < 1e-5


def test_xor_convergence_and_listeners():
    x, y = _xor_data(512)
    conf = (NeuralNetConfiguration(seed=11, updater=Adam(5e-3))
            .list(DenseLayer(n_in=2, n_out=32, activation="relu"),
                  DenseLayer(n_out=32, activation="relu"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    scores = CollectScoresIterationListener()
    perf = PerformanceListener(frequency=5)
    net.set_listeners(scores, perf, ScoreIterationListener(50))
    net.fit(x, y, epochs=60, batch_size=128)
    ev = net.evaluate(x, y)
    assert ev.accuracy() > 0.95, ev.stats()
    assert len(scores.scores) > 100
    assert scores.scores[-1][1] < scores.scores[0][1]
    assert perf.history and perf.history[-1]["samples_per_sec"] > 0


def test_masked_training():
    x, y = _xor_data(64)
    mask = np.ones((64,), np.float32)
    mask[32:] = 0.0  # second half ignored
    ds = DataSet(x, y, labels_mask=mask)
    conf = (NeuralNetConfiguration(seed=5, updater=Sgd(0.1))
            .list(DenseLayer(n_in=2, n_out=8, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(iterator=ListDataSetIterator([ds]), epochs=2)
    assert np.all(np.isfinite(np.asarray(net.params_flat())))


def test_performance_listener_reports_etl_time():
    """ETL (batch fetch + host prep) time is measured per iteration and
    reported by PerformanceListener (reference PerformanceListener.java:
    111,178 fed from the fit loop's lastEtlTime)."""
    import time as _time

    import numpy as np

    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.listeners import PerformanceListener
    from deeplearning4j_tpu.optimize.updaters import Sgd

    r = np.random.default_rng(0)

    class SlowIterator:
        """Iterator whose next() takes measurable host time."""
        def __iter__(self):
            for _ in range(4):
                _time.sleep(0.02)
                x = r.normal(size=(16, 4)).astype(np.float32)
                y = np.eye(2, dtype=np.float32)[(x.sum(-1) > 0).astype(int)]
                yield DataSet(x, y)

        def reset(self):
            pass

    conf = (NeuralNetConfiguration(seed=1, updater=Sgd(0.1))
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    perf = PerformanceListener(frequency=1)
    net.set_listeners(perf)
    net.fit(iterator=SlowIterator(), epochs=1)
    assert perf.history, "no performance records"
    etl = [rec["etl_ms_per_iteration"] for rec in perf.history]
    # the 20ms sleep in the iterator must show up as ETL time
    assert max(etl) >= 10.0, etl
