"""NearestNeighborsServer/-Client + EarlyStoppingParallelTrainer (reference
NearestNeighborsServer.java + parallelism/EarlyStoppingParallelTrainer.java)."""
import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.clustering.server import (NearestNeighborsClient,
                                                  NearestNeighborsServer)
from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
from deeplearning4j_tpu.earlystopping.early_stopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration,
    MaxEpochsTerminationCondition, ScoreImprovementEpochTerminationCondition)
from deeplearning4j_tpu.earlystopping.parallel_trainer import (
    EarlyStoppingParallelTrainer)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam

R = np.random.default_rng(23)


def test_nn_server_roundtrip():
    pts = R.normal(size=(60, 8))
    srv = NearestNeighborsServer(pts)
    port = srv.start()
    try:
        cl = NearestNeighborsClient(port=port)
        out = cl.knn(index=5, k=3)
        brute = np.argsort(np.linalg.norm(pts - pts[5], axis=1))[1:4]
        assert set(out["indices"]) == set(int(i) for i in brute)
        assert 5 not in out["indices"]

        q = R.normal(size=8)
        out2 = cl.knn_new(q, k=4)
        brute2 = np.argsort(np.linalg.norm(pts - q, axis=1))[:4]
        assert set(out2["indices"]) == set(int(i) for i in brute2)
        assert out2["distances"] == sorted(out2["distances"])

        # error surface: bad index -> 400 with message
        import urllib.error
        with pytest.raises(urllib.error.HTTPError):
            cl.knn(index=1000, k=2)
    finally:
        srv.stop()


def test_early_stopping_parallel_trainer():
    x = R.normal(size=(256, 6)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(-1) > 0).astype(int)]
    conf = (NeuralNetConfiguration(seed=2, updater=Adam(5e-3), dtype="float32")
            .list(DenseLayer(n_in=6, n_out=16, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    train_it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    val_it = ListDataSetIterator(features=x, labels=y, batch_size=128)
    es_conf = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(val_it),
        epoch_termination_conditions=[
            MaxEpochsTerminationCondition(12),
            ScoreImprovementEpochTerminationCondition(5)])
    trainer = EarlyStoppingParallelTrainer(es_conf, net, train_it, workers=None)
    result = trainer.fit()
    assert result.total_epochs <= 13
    assert result.best_model is not None
    assert result.best_model_score < list(result.score_vs_epoch.values())[0]
    # fit was restored to the normal path
    net.fit(x, y, epochs=1, batch_size=64)


def test_timeseries_utils_and_viterbi():
    from deeplearning4j_tpu.util.timeseries import (
        Viterbi, moving_average, reshape_2d_to_3d, reshape_3d_to_2d,
        reshape_time_series_mask_to_vector, reshape_vector_to_time_series_mask)

    x = np.arange(6, dtype=float)
    np.testing.assert_allclose(moving_average(x, 3), [1, 2, 3, 4])

    a = R.normal(size=(4, 5, 3))
    np.testing.assert_array_equal(reshape_2d_to_3d(reshape_3d_to_2d(a), 4), a)
    m = (R.random((4, 5)) > 0.5).astype(float)
    np.testing.assert_array_equal(
        reshape_vector_to_time_series_mask(
            reshape_time_series_mask_to_vector(m), 4), m)

    # Viterbi smooths an isolated observation flip
    v = Viterbi([0, 1], meta_stability=0.9)
    ll, path = v.decode(np.array([0, 0, 1, 0, 0]))
    np.testing.assert_array_equal(path, [0, 0, 0, 0, 0])
    assert ll < 0
    # a sustained switch is kept
    _, path2 = v.decode(np.array([0, 0, 1, 1, 1, 1]))
    np.testing.assert_array_equal(path2[-3:], [1, 1, 1])
    # probability-row input
    probs = np.array([[0.9, 0.1], [0.2, 0.8], [0.1, 0.9]])
    _, path3 = v.decode(probs)
    np.testing.assert_array_equal(path3, [0, 1, 1])
