"""Java DL4J model-zip interop (interop/dl4j_zip.py): restore a
reference-format zip (ModelSerializer.java:79-96 layout, fixtures built by
tools/build_dl4j_fixtures.py) and predict.

The parity oracles here are PLAIN-NUMPY forward passes written in this
file from the fixtures' known weights — independent of the importer's
de-F-ordering / conv-transpose logic, so a layout bug cannot cancel
itself out."""
import os
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.interop.dl4j_zip import (import_dl4j_zip,
                                                 is_dl4j_zip,
                                                 read_nd4j_array,
                                                 write_nd4j_array)
from deeplearning4j_tpu.util.serialization import restore_model

FIX = os.path.join(os.path.dirname(__file__), "fixtures", "dl4j")
MLP = os.path.join(FIX, "080_mlp_3_4_5.zip")
LENET = os.path.join(FIX, "080_lenet_flat_8x8.zip")


# ------------------------------------------------------- Nd4j binary layer
@pytest.mark.parametrize("order", ["c", "f"])
@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32])
def test_nd4j_buffer_round_trip(order, dtype):
    r = np.random.default_rng(3)
    a = (r.normal(size=(4, 5)) * 10).astype(dtype)
    b = read_nd4j_array(write_nd4j_array(a, order=order))
    np.testing.assert_array_equal(a, b)


def test_nd4j_long_length_variant():
    """Some nd4j releases write the DataBuffer length as int64; the reader
    auto-detects by validating the dtype token that follows."""
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    blob = write_nd4j_array(a, order="c")
    # surgically widen both length fields from int32 to int64
    import io
    import struct
    out, off = io.BytesIO(), 0
    for _ in range(2):                       # shape-info buffer, data buffer
        n_utf = struct.unpack_from(">H", blob, off)[0]
        out.write(blob[off:off + 2 + n_utf])
        off += 2 + n_utf
        (n,) = struct.unpack_from(">i", blob, off)
        out.write(struct.pack(">q", n))
        off += 4
        n_utf2 = struct.unpack_from(">H", blob, off)[0]
        name = blob[off + 2:off + 2 + n_utf2].decode()
        out.write(blob[off:off + 2 + n_utf2])
        off += 2 + n_utf2
        itemsize = {"INT": 4, "FLOAT": 4}[name]
        out.write(blob[off:off + n * itemsize])
        off += n * itemsize
    b = read_nd4j_array(out.getvalue())
    np.testing.assert_array_equal(a, b)


# ------------------------------------------------------------- MLP fixture
def test_mlp_restore_architecture_and_params():
    """The same assertions RegressionTest080.regressionTestMLP1 makes on
    the Java side: layer types/sizes/activations, Nesterovs(0.15, 0.9),
    params == linspace(1..N), updater state == linspace(1..N)."""
    assert is_dl4j_zip(MLP)
    net = restore_model(MLP)          # ModelGuesser route
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.updaters import Nesterovs

    l0, l1 = net.conf.layers
    assert type(l0) is DenseLayer and l0.n_in == 3 and l0.n_out == 4
    assert l0.activation == "relu"
    assert type(l1) is OutputLayer and l1.n_in == 4 and l1.n_out == 5
    assert l1.activation == "softmax" and l1.loss == "mcxent"
    u = net.conf.updater
    assert isinstance(u, Nesterovs)
    assert u.learning_rate == pytest.approx(0.15)
    assert u.momentum == pytest.approx(0.9)

    n = 3 * 4 + 4 + 4 * 5 + 5
    # param layout: W0 'f'-order [3,4] from flat[0:12], b0 flat[12:16], ...
    flat = np.linspace(1, n, n).astype(np.float32)
    W0 = flat[0:12].reshape((3, 4), order="F")
    b0 = flat[12:16]
    W1 = flat[16:36].reshape((4, 5), order="F")
    b1 = flat[36:41]
    np.testing.assert_array_equal(np.asarray(net.params[0]["W"]), W0)
    np.testing.assert_array_equal(np.asarray(net.params[0]["b"]), b0)
    np.testing.assert_array_equal(np.asarray(net.params[1]["W"]), W1)
    np.testing.assert_array_equal(np.asarray(net.params[1]["b"]), b1)

    # Nesterovs momentum state view mirrors the param layout
    mom = net.opt_state
    leaves = [np.asarray(x) for x in
              __import__("jax").tree.leaves(mom) if np.asarray(x).size > 1]
    np.testing.assert_array_equal(leaves[0], W0)


def test_mlp_predict_matches_numpy_oracle():
    net = import_dl4j_zip(MLP)
    n = 41
    flat = np.linspace(1, n, n).astype(np.float32)
    W0 = flat[0:12].reshape((3, 4), order="F")
    b0 = flat[12:16]
    W1 = flat[16:36].reshape((4, 5), order="F")
    b1 = flat[36:41]
    x = np.random.default_rng(0).normal(size=(7, 3)).astype(np.float32)
    h = np.maximum(x @ W0 + b0, 0.0)
    z = h @ W1 + b1
    e = np.exp(z - z.max(axis=1, keepdims=True))
    expect = e / e.sum(axis=1, keepdims=True)
    np.testing.assert_allclose(np.asarray(net.output(x)), expect,
                               atol=1e-5)


# ----------------------------------------------------------- LeNet fixture
def _numpy_lenet(x_flat):
    """Independent forward pass for the LeNet fixture: explicit loops, no
    shared code with the importer."""
    w = np.load(os.path.join(FIX, "lenet_raw_weights.npy"),
                allow_pickle=True).item()
    B = x_flat.shape[0]
    x = x_flat.reshape(B, 1, 8, 8)          # DL4J NCHW flattening
    convW, convb = w["convW"], w["convb"]   # [out,in,kh,kw]
    conv = np.zeros((B, 4, 6, 6), np.float32)
    for o in range(4):
        for i in range(6):
            for j in range(6):
                patch = x[:, 0, i:i + 3, j:j + 3]
                conv[:, o, i, j] = (patch * convW[o, 0]).sum(axis=(1, 2)) \
                    + convb[o]
    conv = np.maximum(conv, 0.0)
    pool = conv.reshape(B, 4, 3, 2, 3, 2).max(axis=(3, 5))   # 2x2 max
    flat = pool.reshape(B, -1)              # NCHW flatten: c, h, w
    h = np.maximum(flat @ w["dW"] + w["db"], 0.0)
    z = h @ w["oW"] + w["ob"]
    e = np.exp(z - z.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


def test_lenet_restore_and_predict_parity():
    """Conv kernels cross the 'c'[out,in,kh,kw] -> [kh,kw,in,out] layout
    boundary; parity against the loop-based numpy conv proves the
    transpose is right (not merely self-consistent)."""
    net = import_dl4j_zip(LENET)
    x = np.random.default_rng(1).normal(size=(5, 64)).astype(np.float32)
    ours = np.asarray(net.output(x))
    expect = _numpy_lenet(x)
    np.testing.assert_allclose(ours, expect, atol=1e-4)


def test_unsupported_layer_is_a_clear_error(tmp_path):
    import json
    conf = {"confs": [{"layer": {"RBM": {"activationFunction": "sigmoid",
                                         "nin": 3, "nout": 4}}}]}
    p = tmp_path / "bad.zip"
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("configuration.json", json.dumps(conf))
        z.writestr("coefficients.bin", b"")
    with pytest.raises(ValueError, match="unsupported DL4J layer"):
        import_dl4j_zip(str(p))


# ------------------------------------------------------ GravesLSTM fixture
def _numpy_graves_lstm(x):
    """Independent DL4J-semantics LSTM forward, straight from the JAVA
    layout (LSTMHelpers.java): gate columns (g, f, o, i) — block input
    first, "input modulation gate" last — and peephole columns
    (wFF, wOO, wGG) = (forget, output, input-gate). No shared code with
    the importer's gate permutation."""
    w = np.load(os.path.join(FIX, "graves_raw_weights.npy"),
                allow_pickle=True).item()
    W, RW, b, oW, ob = w["W"], w["RW"], w["b"], w["oW"], w["ob"]
    B, T, nin = x.shape
    h = RW.shape[0]
    sig = lambda z: 1.0 / (1.0 + np.exp(-z))
    R4 = RW[:, :4 * h]
    wFF, wOO, wGG = RW[:, 4 * h], RW[:, 4 * h + 1], RW[:, 4 * h + 2]
    hs = np.zeros((B, T, h), np.float32)
    hp = np.zeros((B, h), np.float32)
    cp = np.zeros((B, h), np.float32)
    for t in range(T):
        z = x[:, t] @ W + hp @ R4 + b            # [B, 4H], (g,f,o,i)
        zg, zf, zo, zi = (z[:, :h], z[:, h:2*h], z[:, 2*h:3*h], z[:, 3*h:])
        f = sig(zf + cp * wFF)
        i = sig(zi + cp * wGG)
        g = np.tanh(zg)
        c = f * cp + i * g
        o = sig(zo + c * wOO)
        hp = o * np.tanh(c)
        cp = c
        hs[:, t] = hp
    z = hs @ oW + ob
    e = np.exp(z - z.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def test_graves_lstm_restore_and_predict_parity():
    """The reference's flagship recurrent layer crosses the gate-order
    (g,f,o,i)->(i,f,o,g) and peephole-column boundaries; parity against a
    from-the-Java-layout numpy forward proves both mappings."""
    net = import_dl4j_zip(os.path.join(FIX, "080_graves_char_rnn.zip"))
    from deeplearning4j_tpu.nn.layers import GravesLSTM
    assert type(net.conf.layers[0]) is GravesLSTM
    x = np.random.default_rng(2).normal(size=(3, 6, 5)).astype(np.float32)
    ours = np.asarray(net.output(x))
    expect = _numpy_graves_lstm(x)
    np.testing.assert_allclose(ours, expect, atol=2e-4)


def test_lstm_updater_state_lands_on_correct_leaves(tmp_path):
    """Regression (r5 review): jax.tree.flatten SORTS dict keys, so the
    updater-state blocks must be ordered by sorted param name. With
    nIn == nOut every shape coincides and a wrong order would pass the
    shape guard silently — pin each momentum buffer to its param."""
    import json
    import jax
    from deeplearning4j_tpu.interop.dl4j_zip import write_nd4j_array

    nin = h = 4
    lstm = {"layerName": "l0", "activationFunction": "tanh", "nin": nin,
            "nout": h, "updater": "NESTEROVS", "learningRate": 0.1,
            "momentum": 0.9, "l1": 0.0, "l2": 0.0, "dropOut": 0.0}
    conf = {"backprop": True, "confs": [
        {"seed": 1, "pretrain": False, "layer": {"gravesLSTM": lstm}}]}
    n = nin * 4 * h + h * (4 * h + 3) + 4 * h
    params = np.arange(1, n + 1, dtype=np.float32)
    upd = np.arange(1001, 1001 + n, dtype=np.float32)
    p = tmp_path / "lstm.zip"
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("configuration.json", json.dumps(conf))
        z.writestr("coefficients.bin",
                   write_nd4j_array(params.reshape(1, -1), order="c"))
        z.writestr("updaterState.bin",
                   write_nd4j_array(upd.reshape(1, -1), order="c"))
    net = import_dl4j_zip(str(p))
    assert not net.import_notes, net.import_notes

    # expected layout, computed independently (Java order: W 'f', RW 'f',
    # b; gate blocks (g,f,o,i) -> ours (i,f,o,g); peepholes (pf,po,pi))
    def gates(a):
        return np.concatenate([a[..., 3*h:4*h], a[..., h:2*h],
                               a[..., 2*h:3*h], a[..., 0:h]], axis=-1)

    def split(flat):
        W = gates(flat[:nin*4*h].reshape((nin, 4*h), order="F"))
        RW = flat[nin*4*h:nin*4*h + h*(4*h+3)].reshape((h, 4*h+3), order="F")
        b = gates(flat[-4*h:])
        return {"W": W, "R": gates(RW[:, :4*h]), "b": b,
                "pf": RW[:, 4*h], "po": RW[:, 4*h+1], "pi": RW[:, 4*h+2]}

    want_p = split(params)
    for name, arr in want_p.items():
        np.testing.assert_array_equal(np.asarray(net.params[0][name]), arr,
                                      err_msg=f"param {name}")
    # momentum tree: leaves are SORTED by param name per layer
    want_u = split(upd)
    leaves = [np.asarray(l) for l in jax.tree.leaves(net.opt_state)
              if np.asarray(l).size > 1]
    for leaf, name in zip(leaves, sorted(want_u)):
        np.testing.assert_array_equal(leaf, want_u[name],
                                      err_msg=f"momentum {name}")


def test_batchnorm_restore_params_and_running_stats(tmp_path):
    """DL4J stores BN running mean/var as PARAMS in the flat buffer
    (BatchNormalizationParamInitializer.java:61-84); here they are
    functional state. Inference parity against the closed-form numpy
    BN proves gamma/beta land in params and mean/var in state."""
    import json
    nf = 6
    r = np.random.default_rng(4)
    gamma = r.normal(1, 0.1, nf).astype(np.float32)
    beta = r.normal(0, 0.1, nf).astype(np.float32)
    mean = r.normal(0, 1, nf).astype(np.float32)
    var = r.uniform(0.5, 2.0, nf).astype(np.float32)
    W = r.normal(0, 0.3, (4, nf)).astype(np.float32)
    b = r.normal(0, 0.1, nf).astype(np.float32)
    oW = r.normal(0, 0.3, (nf, 3)).astype(np.float32)
    ob = r.normal(0, 0.1, 3).astype(np.float32)
    conf = {"backprop": True, "confs": [
        {"seed": 1, "pretrain": False, "layer": {"dense": {
            "activationFunction": "identity", "nin": 4, "nout": nf,
            "updater": "NESTEROVS", "learningRate": 0.1, "momentum": 0.9}}},
        {"seed": 1, "pretrain": False, "layer": {"batchNormalization": {
            "nin": nf, "nout": nf, "decay": 0.9, "eps": 1e-5,
            "activationFunction": "relu"}}},
        {"seed": 1, "pretrain": False, "layer": {"output": {
            "activationFunction": "softmax", "lossFunction": "MCXENT",
            "nin": nf, "nout": 3}}},
    ]}
    flat = np.concatenate([W.ravel(order="F"), b, gamma, beta, mean, var,
                           oW.ravel(order="F"), ob]).astype(np.float32)
    p = tmp_path / "bn.zip"
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("configuration.json", json.dumps(conf))
        z.writestr("coefficients.bin",
                   write_nd4j_array(flat.reshape(1, -1), order="c"))
    net = import_dl4j_zip(str(p))
    np.testing.assert_array_equal(np.asarray(net.params[1]["gamma"]), gamma)
    np.testing.assert_array_equal(np.asarray(net.state[1]["mean"]), mean)
    x = r.normal(size=(5, 4)).astype(np.float32)
    h = x @ W + b
    y = np.maximum((h - mean) / np.sqrt(var + 1e-5) * gamma + beta, 0.0)
    z2 = y @ oW + ob
    e = np.exp(z2 - z2.max(1, keepdims=True))
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               e / e.sum(1, keepdims=True), atol=1e-5)


def test_graves_bidirectional_restore_and_predict_parity(tmp_path):
    """Bidirectional layout = forward (W,RW,b) then backward (W,RW,b),
    each GravesLSTM-shaped (GravesBidirectionalLSTMParamInitializer
    .java:98-112); DL4J SUMS the direction outputs. Oracle: run the
    same numpy Graves cell both ways from the raw Java buffers."""
    import json

    nin, h, nout = 3, 5, 2
    r = np.random.default_rng(11)

    def direction():
        return (r.normal(0, 0.3, (nin, 4 * h)).astype(np.float32),
                r.normal(0, 0.3, (h, 4 * h + 3)).astype(np.float32),
                r.normal(0, 0.1, (4 * h,)).astype(np.float32))

    Wf, RWf, bf = direction()
    Wb, RWb, bb = direction()
    oW = r.normal(0, 0.3, (h, nout)).astype(np.float32)
    ob = r.normal(0, 0.1, (nout,)).astype(np.float32)
    conf = {"backprop": True, "confs": [
        {"seed": 1, "pretrain": False, "layer": {"gravesBidirectionalLSTM": {
            "activationFunction": "tanh", "nin": nin, "nout": h,
            "updater": "SGD", "learningRate": 0.1}}},
        {"seed": 1, "pretrain": False, "layer": {"rnnoutput": {
            "activationFunction": "softmax", "lossFunction": "MCXENT",
            "nin": h, "nout": nout}}}]}
    flat = np.concatenate([
        Wf.ravel(order="F"), RWf.ravel(order="F"), bf,
        Wb.ravel(order="F"), RWb.ravel(order="F"), bb,
        oW.ravel(order="F"), ob]).astype(np.float32)
    p = tmp_path / "bi.zip"
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("configuration.json", json.dumps(conf))
        z.writestr("coefficients.bin",
                   write_nd4j_array(flat.reshape(1, -1), order="c"))
    net = import_dl4j_zip(str(p))
    from deeplearning4j_tpu.nn.layers import GravesBidirectionalLSTM
    assert type(net.conf.layers[0]) is GravesBidirectionalLSTM

    def cell(x, W, RW, b):        # DL4J-layout numpy Graves cell
        sig = lambda z: 1.0 / (1.0 + np.exp(-z))
        R4, wFF, wOO, wGG = (RW[:, :4*h], RW[:, 4*h], RW[:, 4*h+1],
                             RW[:, 4*h+2])
        B, T = x.shape[:2]
        hs = np.zeros((B, T, h), np.float32)
        hp = np.zeros((B, h), np.float32)
        cp = np.zeros((B, h), np.float32)
        for t in range(T):
            z = x[:, t] @ W + hp @ R4 + b
            zg, zf, zo, zi = (z[:, :h], z[:, h:2*h], z[:, 2*h:3*h],
                              z[:, 3*h:])
            f = sig(zf + cp * wFF)
            i = sig(zi + cp * wGG)
            c = f * cp + i * np.tanh(zg)
            o = sig(zo + c * wOO)
            hp, cp = o * np.tanh(c), c
            hs[:, t] = hp
        return hs

    x = np.random.default_rng(3).normal(size=(2, 7, nin)).astype(np.float32)
    fwd = cell(x, Wf, RWf, bf)
    bwd = cell(x[:, ::-1], Wb, RWb, bb)[:, ::-1]
    z = (fwd + bwd) @ oW + ob
    e = np.exp(z - z.max(-1, keepdims=True))
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               e / e.sum(-1, keepdims=True), atol=2e-4)


def _two_layer_conf(lr0=0.1, lr1=0.1, upd0="SGD", upd1="SGD"):
    import json
    mk = lambda nin, nout, upd, lr, extra: dict(
        {"layerName": "l", "activationFunction": "relu", "nin": nin,
         "nout": nout, "updater": upd, "learningRate": lr, "l1": 0.0,
         "l2": 0.0, "dropOut": 0.0}, **extra)
    return json.dumps({"backprop": True, "confs": [
        {"seed": 1, "pretrain": False,
         "layer": {"dense": mk(3, 4, upd0, lr0, {})}},
        {"seed": 1, "pretrain": False,
         "layer": {"output": mk(4, 5, upd1, lr1,
                                {"activationFunction": "softmax",
                                 "lossFunction": "MCXENT"})}}]})


def _write_two_layer_zip(path, conf_json):
    from deeplearning4j_tpu.interop.dl4j_zip import write_nd4j_array
    n = 3 * 4 + 4 + 4 * 5 + 5
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("configuration.json", conf_json)
        z.writestr("coefficients.bin", write_nd4j_array(
            np.linspace(1, n, n, dtype=np.float32).reshape(1, -1),
            order="c"))


def test_heterogeneous_per_layer_updaters_warn_on_import(tmp_path):
    """Regression (ADVICE r5): DL4J permits per-layer updaters/learning
    rates; this runtime builds ONE network updater from layer 0. A zip
    whose layers disagree must say so in import_notes instead of silently
    training later layers with the wrong optimizer."""
    p = tmp_path / "hetero.zip"
    _write_two_layer_zip(p, _two_layer_conf(lr0=0.1, lr1=0.01))
    net = import_dl4j_zip(str(p))
    assert any("heterogeneous" in n for n in net.import_notes), \
        net.import_notes

    # different updater RULE, same lr: also flagged
    p2 = tmp_path / "hetero2.zip"
    _write_two_layer_zip(p2, _two_layer_conf(upd0="NESTEROVS", upd1="ADAM"))
    net2 = import_dl4j_zip(str(p2))
    assert any("heterogeneous" in n for n in net2.import_notes)

    # layer 0 with NO updater keys (import defaults) vs an explicit Adam
    # on layer 1: the comparison is against layer 0 — the config the
    # import actually uses — so this must be flagged too
    import json
    conf = json.loads(_two_layer_conf(upd1="ADAM"))
    for key in ("updater", "learningRate"):
        del conf["confs"][0]["layer"]["dense"][key]
    p3 = tmp_path / "hetero3.zip"
    _write_two_layer_zip(p3, json.dumps(conf))
    net3 = import_dl4j_zip(str(p3))
    assert any("heterogeneous" in n for n in net3.import_notes)


def test_homogeneous_updaters_import_without_warning(tmp_path):
    """The common case (one updater everywhere) must stay note-free."""
    p = tmp_path / "homo.zip"
    _write_two_layer_zip(p, _two_layer_conf())
    net = import_dl4j_zip(str(p))
    assert not any("heterogeneous" in n for n in net.import_notes), \
        net.import_notes
