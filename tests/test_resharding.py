"""Any-to-any resharding (parallel/resharding.py): a checkpoint saved
under ANY (data, model) topology restores onto ANY other (ISSUE 20
satellite — the full topology-portability matrix over (1,1) / (2,1) /
(2,2) / (4,1)), with the truncated-newest walk-back discipline intact
when the resharder is in play."""
import os

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models.zoo_extra import transformer_lm
from deeplearning4j_tpu.parallel import (ParallelWrapper, build_param_specs,
                                         host_gather, make_any_resharder,
                                         redistribute, shard_params)
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.tensor_parallel import build_opt_shardings
from deeplearning4j_tpu.util.distributed_checkpoint import (
    restore_latest_sharded_checkpoint, save_sharded_checkpoint)

V = 29
TOPOS = [(1, 1), (2, 1), (2, 2), (4, 1)]


def _mesh(shape):
    d, m = shape
    return make_mesh(shape, ("data", "model"), jax.devices()[:d * m])


@pytest.fixture(scope="module")
def base():
    """One short training for non-trivial params AND updater state; the
    matrix below is purely about layout, so the same host values are
    device_put onto each source topology before saving."""
    net = transformer_lm(vocab_size=V, d_model=16, n_heads=4, n_blocks=1,
                         max_length=16, seed=11, token_input=True).init()
    rs = np.random.RandomState(0)
    data = [DataSet(rs.randint(1, V, (8, 8)).astype(np.int32),
                    np.eye(V)[rs.randint(0, V, (8, 8))].astype(np.float32))
            for _ in range(2)]
    ParallelWrapper(net, mesh_shape=(2, 1)).fit(data, epochs=1)
    return net, {"params": host_gather(net.params),
                 "opt": host_gather(net.opt_state)}


def _placed(net, values, shape):
    """values placed on ``shape``'s tp layout (params per the rule table,
    updater slots inheriting their param's spec)."""
    mesh = _mesh(shape)
    specs = build_param_specs(net, shape[1])
    params = shard_params(mesh, values["params"], specs)
    opt_sh = build_opt_shardings(mesh, specs, values["params"],
                                 values["opt"])
    opt = jax.tree.map(lambda v, s: jax.device_put(v, s),
                       values["opt"], opt_sh)
    return {"params": params, "opt": opt}


def _assert_matches(restored, like, values):
    got = host_gather(restored)
    for g, v in zip(jax.tree.leaves(got["params"]),
                    jax.tree.leaves(values["params"])):
        np.testing.assert_array_equal(g, v)         # params: bitwise
    for g, v in zip(jax.tree.leaves(got["opt"]),
                    jax.tree.leaves(values["opt"])):
        np.testing.assert_allclose(g, v, atol=1e-6)  # opt: float tolerance
    for r, l in zip(jax.tree.leaves(restored), jax.tree.leaves(like)):
        assert r.sharding == l.sharding, (r.sharding, l.sharding)


def test_topology_matrix_each_to_each(base, tmp_path):
    net, values = base
    for si, src in enumerate(TOPOS):
        d = str(tmp_path / f"src{si}")
        save_sharded_checkpoint(d, 5, _placed(net, values, src),
                                extra={"src": list(src)})
        for dst in TOPOS:
            like = _placed(net, values, dst)
            step, tree, extra = restore_latest_sharded_checkpoint(
                d, like, resharder=make_any_resharder())
            assert step == 5 and extra == {"src": list(src)}, (src, dst)
            _assert_matches(tree, like, values)


def test_truncated_newest_falls_back_past_resharder(base, tmp_path):
    """The newest save is truncated mid-write: restore (with the any
    resharder active) must walk back to the older valid save, not crash
    and not feed the resharder a damaged archive."""
    net, values = base
    d = str(tmp_path / "ckpt")
    save_sharded_checkpoint(d, 1, _placed(net, values, (2, 1)))
    save_sharded_checkpoint(d, 2, _placed(net, values, (2, 1)))
    shard = os.path.join(d, "ckpt_step2_p000.npz")
    with open(shard, "rb") as f:
        head = f.read(64)
    with open(shard, "wb") as f:
        f.write(head)
    like = _placed(net, values, (4, 1))
    step, tree, _ = restore_latest_sharded_checkpoint(
        d, like, resharder=make_any_resharder())
    assert step == 1
    _assert_matches(tree, like, values)


def test_leaf_count_mismatch_walks_to_nothing(base, tmp_path):
    """A save the resharder cannot interpret (leaf count disagrees with
    ``like``) falls back like any other restore failure — here to
    'nothing restorable', never a mis-sliced tree."""
    net, values = base
    d = str(tmp_path / "ckpt")
    save_sharded_checkpoint(d, 3, _placed(net, values, (2, 2)))
    like = {"params": _placed(net, values, (2, 1))["params"]}
    step, tree, extra = restore_latest_sharded_checkpoint(
        d, like, resharder=make_any_resharder())
    assert step is None and tree is like and extra == {}


def test_redistribute_is_pure_layout(base):
    net, values = base
    placed = _placed(net, values, (2, 2))["params"]
    mesh41 = _mesh((4, 1))
    specs41 = build_param_specs(net, 1)
    back = redistribute(placed, mesh41, specs41)
    for g, v in zip(jax.tree.leaves(host_gather(back)),
                    jax.tree.leaves(values["params"])):
        np.testing.assert_array_equal(g, v)
    for leaf, spec in zip(
            jax.tree.leaves(back),
            jax.tree.leaves(specs41, is_leaf=lambda x: isinstance(x, P))):
        assert leaf.sharding == NamedSharding(mesh41, spec)
