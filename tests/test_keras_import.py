"""Keras HDF5 import tests (mirror reference modelimport tests: fixture h5
files produced by real Keras, loaded and prediction/shape-checked)."""
import json

import numpy as np
import pytest

keras = pytest.importorskip("keras")

from deeplearning4j_tpu.keras_import.importer import (
    import_keras_model, import_keras_sequential_model_and_weights)


def _save_h5(model, path):
    model.save(path)  # .h5 suffix selects legacy HDF5 with model_config attr


def test_import_sequential_mlp(tmp_path):
    from keras import layers
    model = keras.Sequential([
        keras.Input(shape=(4,)),
        layers.Dense(8, activation="relu"),
        layers.Dense(3, activation="softmax"),
    ])
    model.compile(loss="categorical_crossentropy", optimizer="sgd")
    path = str(tmp_path / "mlp.h5")
    _save_h5(model, path)
    net = import_keras_sequential_model_and_weights(path)
    x = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
    keras_out = np.asarray(model.predict(x, verbose=0))
    ours = np.asarray(net.output(x))
    assert np.allclose(keras_out, ours, atol=1e-5), np.abs(keras_out - ours).max()


def test_import_sequential_cnn(tmp_path):
    from keras import layers
    model = keras.Sequential([
        keras.Input(shape=(8, 8, 2)),
        layers.Conv2D(4, (3, 3), padding="same", activation="relu"),
        layers.MaxPooling2D((2, 2)),
        layers.Flatten(),
        layers.Dense(5, activation="softmax"),
    ])
    model.compile(loss="categorical_crossentropy", optimizer="sgd")
    path = str(tmp_path / "cnn.h5")
    _save_h5(model, path)
    net = import_keras_sequential_model_and_weights(path)
    x = np.random.default_rng(1).normal(size=(3, 8, 8, 2)).astype(np.float32)
    keras_out = np.asarray(model.predict(x, verbose=0))
    ours = np.asarray(net.output(x))
    assert np.allclose(keras_out, ours, atol=1e-4), np.abs(keras_out - ours).max()


def test_import_via_model_guesser(tmp_path):
    from keras import layers
    model = keras.Sequential([
        keras.Input(shape=(6,)),
        layers.Dense(4, activation="tanh"),
        layers.Dense(2, activation="softmax"),
    ])
    model.compile(loss="categorical_crossentropy", optimizer="sgd")
    path = str(tmp_path / "g.h5")
    _save_h5(model, path)
    net = import_keras_model(path)
    assert np.asarray(net.output(np.zeros((1, 6), np.float32))).shape == (1, 2)


def test_import_functional_branching(tmp_path):
    """Two-branch functional model: Add + Concatenate merge vertices
    (reference KerasModel.java:418 topo-sorted layer graph -> vertices)."""
    from keras import layers
    inp = keras.Input(shape=(8,))
    a = layers.Dense(4, activation="relu", name="d1")(inp)
    b = layers.Dense(4, activation="tanh", name="d2")(inp)
    m = layers.Add(name="add")([a, b])
    c = layers.Concatenate(name="cat")([m, a])
    out = layers.Dense(3, activation="softmax", name="out")(c)
    model = keras.Model(inp, out)
    model.compile(loss="categorical_crossentropy", optimizer="sgd")
    path = str(tmp_path / "func.h5")
    _save_h5(model, path)

    from deeplearning4j_tpu.keras_import.importer import import_keras_model_and_weights
    graph = import_keras_model_and_weights(path)
    x = np.random.default_rng(2).normal(size=(5, 8)).astype(np.float32)
    keras_out = np.asarray(model.predict(x, verbose=0))
    ours = np.asarray(graph.output(x))
    assert np.allclose(keras_out, ours, atol=1e-5), np.abs(keras_out - ours).max()


def test_import_functional_cnn_residual(tmp_path):
    """Mini residual CNN (conv + BN + add + global pool), the ResNet-50
    building-block shape, via the sniffing entry point."""
    from keras import layers
    inp = keras.Input(shape=(8, 8, 3))
    c1 = layers.Conv2D(4, (3, 3), padding="same", name="c1")(inp)
    bn = layers.BatchNormalization(name="bn")(c1)
    r = layers.Activation("relu", name="act")(bn)
    c2 = layers.Conv2D(4, (3, 3), padding="same", name="c2")(r)
    sc = layers.Conv2D(4, (1, 1), padding="same", name="sc")(inp)
    s = layers.Add(name="add")([c2, sc])
    g = layers.GlobalAveragePooling2D(name="gap")(s)
    out = layers.Dense(2, activation="softmax", name="out")(g)
    model = keras.Model(inp, out)
    model.compile(loss="categorical_crossentropy", optimizer="sgd")
    path = str(tmp_path / "rescnn.h5")
    _save_h5(model, path)

    graph = import_keras_model(path)
    x = np.random.default_rng(3).normal(size=(2, 8, 8, 3)).astype(np.float32)
    keras_out = np.asarray(model.predict(x, verbose=0))
    ours = np.asarray(graph.output(x))
    assert np.allclose(keras_out, ours, atol=1e-4), np.abs(keras_out - ours).max()


def test_import_functional_multi_input_output(tmp_path):
    from keras import layers
    in1 = keras.Input(shape=(4,), name="in1")
    in2 = keras.Input(shape=(6,), name="in2")
    h1 = layers.Dense(5, activation="relu", name="h1")(in1)
    h2 = layers.Dense(5, activation="relu", name="h2")(in2)
    m = layers.Concatenate(name="cat")([h1, h2])
    o1 = layers.Dense(3, activation="softmax", name="o1")(m)
    o2 = layers.Dense(1, activation="linear", name="o2")(m)
    model = keras.Model([in1, in2], [o1, o2])
    model.compile(loss={"o1": "categorical_crossentropy", "o2": "mse"},
                  optimizer="sgd")
    path = str(tmp_path / "mimo.h5")
    _save_h5(model, path)

    graph = import_keras_model(path)
    rng = np.random.default_rng(4)
    x1 = rng.normal(size=(3, 4)).astype(np.float32)
    x2 = rng.normal(size=(3, 6)).astype(np.float32)
    k1, k2 = model.predict([x1, x2], verbose=0)
    ours = graph.output(x1, x2)
    assert np.allclose(np.asarray(k1), np.asarray(ours[0]), atol=1e-5)
    assert np.allclose(np.asarray(k2), np.asarray(ours[1]), atol=1e-5)


def test_import_sequential_dense_plus_activation_head(tmp_path):
    """Dense(linear) + Activation('softmax') tail imports as a proper scoring
    layer instead of mis-assigning the loss to the Dense."""
    from keras import layers
    model = keras.Sequential([
        keras.Input(shape=(4,)),
        layers.Dense(3),
        layers.Activation("softmax"),
    ])
    model.compile(loss="categorical_crossentropy", optimizer="sgd")
    path = str(tmp_path / "densact.h5")
    _save_h5(model, path)
    net = import_keras_sequential_model_and_weights(path)
    x = np.random.default_rng(5).normal(size=(4, 4)).astype(np.float32)
    keras_out = np.asarray(model.predict(x, verbose=0))
    ours = np.asarray(net.output(x))
    assert np.allclose(keras_out, ours, atol=1e-5)
    # the imported net must be trainable (loss wired to the activation head)
    y = np.eye(3)[np.random.default_rng(6).integers(0, 3, 4)]
    s = net.score(x, y)
    assert np.isfinite(s)


def test_enforce_training_config_raises_on_unknown_loss(tmp_path):
    from keras import layers
    model = keras.Sequential([
        keras.Input(shape=(4,)),
        layers.Dense(2, activation="softmax"),
    ])
    model.compile(loss="huber", optimizer="sgd")
    path = str(tmp_path / "huber.h5")
    _save_h5(model, path)
    with pytest.raises(ValueError, match="huber"):
        import_keras_sequential_model_and_weights(path, enforce_training_config=True)
    net = import_keras_sequential_model_and_weights(path)  # lenient default
    assert np.asarray(net.output(np.zeros((1, 4), np.float32))).shape == (1, 2)


def test_import_functional_lstm_last_step(tmp_path):
    from keras import layers
    inp = keras.Input(shape=(7, 5))
    h = layers.LSTM(6, return_sequences=False, name="enc")(inp)
    out = layers.Dense(2, activation="softmax", name="out")(h)
    model = keras.Model(inp, out)
    model.compile(loss="categorical_crossentropy", optimizer="sgd")
    path = str(tmp_path / "lstm.h5")
    _save_h5(model, path)
    graph = import_keras_model(path)
    x = np.random.default_rng(7).normal(size=(3, 7, 5)).astype(np.float32)
    keras_out = np.asarray(model.predict(x, verbose=0))
    ours = np.asarray(graph.output(x))
    assert np.allclose(keras_out, ours, atol=1e-4), np.abs(keras_out - ours).max()


@pytest.mark.slow
def test_import_keras_applications_resnet50_vgg16(tmp_path):
    """North-star (SURVEY §7 stage 8): real keras.applications ResNet-50 and
    VGG16 functional .h5 files import unchanged and predict identically."""
    import numpy as np
    for name, ctor in [("resnet50", keras.applications.ResNet50),
                       ("vgg16", keras.applications.VGG16)]:
        model = ctor(weights=None, classes=10, input_shape=(64, 64, 3),
                     include_top=True)
        path = str(tmp_path / f"{name}.h5")
        _save_h5(model, path)
        graph = import_keras_model(path)
        x = np.random.default_rng(0).normal(size=(2, 64, 64, 3)).astype(np.float32)
        k = np.asarray(model.predict(x, verbose=0))
        o = np.asarray(graph.output(x))
        assert np.allclose(k, o, atol=1e-4), np.abs(k - o).max()


def test_import_sequential_conv1d_stack(tmp_path):
    """1D translator tail (reference KerasLayer.java:53-70 registry):
    Conv1D + MaxPooling1D + GlobalMaxPooling1D prediction parity."""
    from keras import layers
    model = keras.Sequential([
        keras.Input(shape=(12, 5)),
        layers.Conv1D(8, 3, padding="same", activation="relu"),
        layers.MaxPooling1D(2),
        layers.Conv1D(6, 3, padding="valid", activation="tanh"),
        layers.GlobalMaxPooling1D(),
        layers.Dense(3, activation="softmax"),
    ])
    model.compile(loss="categorical_crossentropy", optimizer="sgd")
    path = str(tmp_path / "conv1d.h5")
    _save_h5(model, path)
    net = import_keras_sequential_model_and_weights(path)
    x = np.random.default_rng(8).normal(size=(4, 12, 5)).astype(np.float32)
    keras_out = np.asarray(model.predict(x, verbose=0))
    ours = np.asarray(net.output(x))
    assert np.allclose(keras_out, ours, atol=1e-4), np.abs(keras_out - ours).max()


def test_import_sequential_zeropad1d_avgpool1d(tmp_path):
    from keras import layers
    model = keras.Sequential([
        keras.Input(shape=(10, 4)),
        layers.ZeroPadding1D(2),
        layers.Conv1D(6, 3, padding="valid", activation="relu"),
        layers.AveragePooling1D(2),
        layers.GlobalAveragePooling1D(),
        layers.Dense(2, activation="softmax"),
    ])
    model.compile(loss="categorical_crossentropy", optimizer="sgd")
    path = str(tmp_path / "zp1d.h5")
    _save_h5(model, path)
    net = import_keras_sequential_model_and_weights(path)
    x = np.random.default_rng(9).normal(size=(3, 10, 4)).astype(np.float32)
    keras_out = np.asarray(model.predict(x, verbose=0))
    ours = np.asarray(net.output(x))
    assert np.allclose(keras_out, ours, atol=1e-4), np.abs(keras_out - ours).max()


def test_import_time_distributed_dense(tmp_path):
    """TimeDistributed(Dense) (reference KerasLayer.java:69): dissolves to the
    natively time-distributed DenseLayer; as the last layer it becomes the
    RnnOutputLayer scoring head."""
    from keras import layers
    model = keras.Sequential([
        keras.Input(shape=(6, 4)),
        layers.LSTM(5, return_sequences=True),
        layers.TimeDistributed(layers.Dense(3, activation="softmax")),
    ])
    model.compile(loss="categorical_crossentropy", optimizer="sgd")
    path = str(tmp_path / "td.h5")
    _save_h5(model, path)
    net = import_keras_sequential_model_and_weights(path)
    x = np.random.default_rng(10).normal(size=(2, 6, 4)).astype(np.float32)
    keras_out = np.asarray(model.predict(x, verbose=0))
    ours = np.asarray(net.output(x))
    assert ours.shape == (2, 6, 3)
    assert np.allclose(keras_out, ours, atol=1e-4), np.abs(keras_out - ours).max()
    # trainable: scoring head wired to the time axis
    y = np.eye(3, dtype=np.float32)[np.random.default_rng(11).integers(0, 3, (2, 6))]
    assert np.isfinite(net.score(x, y))


def test_pool_helper_vertex():
    """PoolHelperVertex strips the first row+column (reference
    nn/graph/vertex/impl/PoolHelperVertex.java, NHWC here)."""
    from deeplearning4j_tpu.nn.graph.vertices import PoolHelperVertex
    from deeplearning4j_tpu.nn.layers import ConvolutionLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.graph.graph import ComputationGraph
    from deeplearning4j_tpu.nn.layers import OutputLayer, GlobalPoolingLayer

    b = (NeuralNetConfiguration(seed=3, updater=Sgd(0.1))
         .graph_builder()
         .add_inputs("in")
         .add_layer("c", ConvolutionLayer(n_out=3, kernel_size=(3, 3),
                                          convolution_mode="same"), "in")
         .add_vertex("ph", PoolHelperVertex(), "c")
         .add_layer("gp", GlobalPoolingLayer(pooling_type="avg"), "ph")
         .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"), "gp")
         .set_outputs("out")
         .set_input_types(InputType.convolutional(6, 6, 2)))
    net = ComputationGraph(b.build()).init()
    x = np.random.default_rng(12).normal(size=(2, 6, 6, 2)).astype(np.float32)
    acts = net.feed_forward(x)
    assert np.asarray(acts["ph"]).shape == (2, 5, 5, 3)
    assert np.asarray(net.output(x)).shape == (2, 2)


def test_import_avgpool1d_same_odd_length(tmp_path):
    """AveragePooling1D(padding='same') over an odd-length sequence: edge
    windows must average over the VALID frames only (TF/Keras semantics)."""
    from keras import layers
    model = keras.Sequential([
        keras.Input(shape=(7, 3)),
        layers.AveragePooling1D(2, padding="same"),
        layers.GlobalAveragePooling1D(),
        layers.Dense(2, activation="softmax"),
    ])
    model.compile(loss="categorical_crossentropy", optimizer="sgd")
    path = str(tmp_path / "ap1same.h5")
    _save_h5(model, path)
    net = import_keras_sequential_model_and_weights(path)
    x = np.random.default_rng(13).normal(size=(3, 7, 3)).astype(np.float32)
    keras_out = np.asarray(model.predict(x, verbose=0))
    ours = np.asarray(net.output(x))
    assert np.allclose(keras_out, ours, atol=1e-5), np.abs(keras_out - ours).max()
