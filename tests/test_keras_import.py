"""Keras HDF5 import tests (mirror reference modelimport tests: fixture h5
files produced by real Keras, loaded and prediction/shape-checked)."""
import json

import numpy as np
import pytest

keras = pytest.importorskip("keras")

from deeplearning4j_tpu.keras_import.importer import (
    import_keras_model, import_keras_sequential_model_and_weights)


def _save_h5(model, path):
    model.save(path)  # .h5 suffix selects legacy HDF5 with model_config attr


def test_import_sequential_mlp(tmp_path):
    from keras import layers
    model = keras.Sequential([
        keras.Input(shape=(4,)),
        layers.Dense(8, activation="relu"),
        layers.Dense(3, activation="softmax"),
    ])
    model.compile(loss="categorical_crossentropy", optimizer="sgd")
    path = str(tmp_path / "mlp.h5")
    _save_h5(model, path)
    net = import_keras_sequential_model_and_weights(path)
    x = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
    keras_out = np.asarray(model.predict(x, verbose=0))
    ours = np.asarray(net.output(x))
    assert np.allclose(keras_out, ours, atol=1e-5), np.abs(keras_out - ours).max()


def test_import_sequential_cnn(tmp_path):
    from keras import layers
    model = keras.Sequential([
        keras.Input(shape=(8, 8, 2)),
        layers.Conv2D(4, (3, 3), padding="same", activation="relu"),
        layers.MaxPooling2D((2, 2)),
        layers.Flatten(),
        layers.Dense(5, activation="softmax"),
    ])
    model.compile(loss="categorical_crossentropy", optimizer="sgd")
    path = str(tmp_path / "cnn.h5")
    _save_h5(model, path)
    net = import_keras_sequential_model_and_weights(path)
    x = np.random.default_rng(1).normal(size=(3, 8, 8, 2)).astype(np.float32)
    keras_out = np.asarray(model.predict(x, verbose=0))
    ours = np.asarray(net.output(x))
    assert np.allclose(keras_out, ours, atol=1e-4), np.abs(keras_out - ours).max()


def test_import_via_model_guesser(tmp_path):
    from keras import layers
    model = keras.Sequential([
        keras.Input(shape=(6,)),
        layers.Dense(4, activation="tanh"),
        layers.Dense(2, activation="softmax"),
    ])
    model.compile(loss="categorical_crossentropy", optimizer="sgd")
    path = str(tmp_path / "g.h5")
    _save_h5(model, path)
    net = import_keras_model(path)
    assert np.asarray(net.output(np.zeros((1, 6), np.float32))).shape == (1, 2)
