"""Saved-model backward-compatibility regression tests.

Reference: deeplearning4j-core regressiontest/RegressionTest050/060/071/080
— model zips produced by RELEASED versions must keep deserializing and
predicting identically; "saved-model backward compat is a contract"
(SURVEY.md §4). The committed fixtures under tests/fixtures/ were produced
by this framework at config format_version 1; every future change must keep
restoring them bit-compatibly (add new fixtures per format bump, never
regenerate old ones).

The expected outputs are CPU-pinned (conftest forces the CPU platform):
TPU MXU f32 convolutions differ from CPU by ~1e-3 — hardware numerics, not a
serialization regression.
"""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.util.serialization import restore_model

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


@pytest.mark.parametrize("name", ["regression_v1_mln_cnn",
                                  "regression_v1_mln_lstm",
                                  "regression_v1_cg_merge"])
def test_v1_fixture_restores_and_predicts_identically(name):
    net = restore_model(os.path.join(FIXTURES, f"{name}.zip"))
    exp = np.load(os.path.join(FIXTURES, f"{name}_expected.npz"))
    out = np.asarray(net.output(exp["x"]))
    np.testing.assert_allclose(out, exp["out"], atol=1e-5,
                               err_msg=f"{name}: prediction drift after "
                                       f"restore — saved-model compat broken")


def test_v1_fixture_updater_state_restores():
    net = restore_model(os.path.join(FIXTURES, "regression_v1_mln_cnn.zip"),
                        load_updater=True)
    assert net.opt_state is not None
    # training continues from the restored updater state without error
    exp = np.load(os.path.join(FIXTURES, "regression_v1_mln_cnn_expected.npz"))
    x = exp["x"]
    y = np.eye(3, dtype=np.float32)[np.arange(len(x)) % 3]
    net.fit(x, y, epochs=1, batch_size=len(x))
