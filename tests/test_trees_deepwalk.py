"""KD/Quad/Sp trees, Barnes-Hut t-SNE, graph API + DeepWalk (reference
clustering/kdtree/KDTree.java, quadtree/QuadTree.java, sptree/SpTree.java,
plot/BarnesHutTsne.java, deeplearning4j-graph DeepWalk.java:31)."""
import numpy as np
import pytest

from deeplearning4j_tpu.clustering.trees import KDTree, QuadTree, SpTree
from deeplearning4j_tpu.clustering.tsne import BarnesHutTsne, Tsne
from deeplearning4j_tpu.graphs import (DeepWalk, Graph, RandomWalkIterator,
                                       WeightedRandomWalkIterator)

R = np.random.default_rng(5)


# -------------------------------------------------------------------- KDTree
def test_kdtree_knn_matches_bruteforce():
    pts = R.normal(size=(200, 5))
    tree = KDTree(pts)
    assert len(tree) == 200
    for _ in range(10):
        q = R.normal(size=5)
        idxs, dists = tree.knn(q, 7)
        brute = np.argsort(np.linalg.norm(pts - q, axis=1))[:7]
        np.testing.assert_array_equal(np.sort(idxs), np.sort(brute))
        assert dists == sorted(dists)


def test_kdtree_insert_and_nn():
    tree = KDTree(dims=2)
    tree.insert([0.0, 0.0])
    tree.insert([1.0, 1.0])
    tree.insert([-1.0, 0.5])
    i, d = tree.nn([0.9, 0.9])
    assert i == 1
    assert abs(d - np.sqrt(0.02)) < 1e-9


# ------------------------------------------------------------------- SpTree
def test_sptree_mass_and_bh_forces_match_exact_for_small_theta():
    pts = R.normal(size=(100, 2))
    tree = SpTree.build(pts)
    assert tree.count == 100
    np.testing.assert_allclose(tree.cum_center / tree.count, pts.mean(0),
                               atol=1e-9)
    # theta=0: Barnes-Hut degenerates to the exact per-point sum
    for i in [0, 17, 55]:
        neg = np.zeros(2)
        z = tree.compute_non_edge_forces(pts[i], 0.0, neg)
        d2 = np.sum((pts[i] - pts) ** 2, 1)
        q = 1.0 / (1.0 + d2)
        mask = np.arange(100) != i
        z_exact = q[mask].sum()
        neg_exact = ((q[mask] ** 2)[:, None] * (pts[i] - pts[mask])).sum(0)
        np.testing.assert_allclose(z, z_exact, rtol=1e-9)
        np.testing.assert_allclose(neg, neg_exact, rtol=1e-7, atol=1e-10)


def test_sptree_theta_approximation_close():
    pts = R.normal(size=(300, 2))
    tree = SpTree.build(pts)
    neg_a, neg_e = np.zeros(2), np.zeros(2)
    z_a = tree.compute_non_edge_forces(pts[3], 0.5, neg_a)
    z_e = tree.compute_non_edge_forces(pts[3], 0.0, neg_e)
    assert abs(z_a - z_e) / z_e < 0.1


def test_quadtree_2d_only():
    pts = R.normal(size=(50, 2))
    t = QuadTree.build(pts)
    assert t.count == 50
    with pytest.raises(ValueError):
        QuadTree(np.zeros(3), np.ones(3))


# ----------------------------------------------------------- Barnes-Hut tSNE
@pytest.mark.slow
def test_barnes_hut_tsne_separates_clusters():
    # slow lane (ISSUE 14 tier-1 budget reclaim): ~11s end-to-end quality
    # soak; the BH force math itself stays tier-1-verified EXACTLY against
    # the theta=0 per-point sum (test_sptree_mass_and_bh_forces_...)
    a = R.normal(size=(40, 10)) + 8.0
    b = R.normal(size=(40, 10)) - 8.0
    X = np.vstack([a, b])
    Y = BarnesHutTsne(perplexity=10, n_iter=150, seed=1,
                      theta=0.5).fit_transform(X)
    assert Y.shape == (80, 2)
    da = Y[:40].mean(0)
    db = Y[40:].mean(0)
    between = np.linalg.norm(da - db)
    within = max(np.linalg.norm(Y[:40] - da, axis=1).mean(),
                 np.linalg.norm(Y[40:] - db, axis=1).mean())
    assert between > 2 * within


# ------------------------------------------------------------ graph/DeepWalk
def _two_cliques(k=6):
    g = Graph(2 * k)
    for i in range(k):
        for j in range(i + 1, k):
            g.add_edge(i, j)
            g.add_edge(k + i, k + j)
    g.add_edge(0, k)   # single bridge
    return g


def test_random_walks_stay_mostly_in_clique():
    g = _two_cliques()
    walks = list(RandomWalkIterator(g, walk_length=10, seed=3))
    assert len(walks) == g.num_vertices()
    assert all(len(w) == 11 for w in walks)
    # disconnected vertex self-loops
    g2 = Graph(3)
    g2.add_edge(0, 1)
    walks2 = {w[0]: w for w in RandomWalkIterator(g2, walk_length=4, seed=1)}
    assert walks2[2] == [2, 2, 2, 2, 2]


def test_weighted_walks_follow_weights():
    g = Graph(3, directed=True)
    g.add_edge(0, 1, weight=100.0)
    g.add_edge(0, 2, weight=0.001)
    seen1 = sum(1 for w in
                [next(iter(WeightedRandomWalkIterator(g, 1, seed=s)))
                 for s in range(30)]
                if w[0] == 0 and len(w) > 1 and w[1] == 1)
    starts0 = sum(1 for s in range(30)
                  for w in [next(iter(WeightedRandomWalkIterator(g, 1, seed=s)))]
                  if w[0] == 0)
    if starts0:
        assert seen1 / starts0 > 0.9


def test_deepwalk_embeds_cliques_closer():
    g = _two_cliques()
    dw = DeepWalk(vector_size=16, window_size=4, walk_length=20,
                  walks_per_vertex=8, epochs=3, seed=7).fit(g)
    table = dw.lookup_table
    assert table.shape == (12, 16)
    same = np.mean([dw.similarity(i, j) for i in range(1, 6)
                    for j in range(1, 6) if i < j])
    cross = np.mean([dw.similarity(i, j) for i in range(1, 6)
                     for j in range(7, 12)])
    assert same > cross
    assert dw.verts_nearest(1, 3)


def test_deepwalk_from_explicit_walks():
    walks = [[0, 1, 2, 1, 0] for _ in range(20)] + \
            [[3, 4, 5, 4, 3] for _ in range(20)]
    dw = DeepWalk(vector_size=8, window_size=2, epochs=2, seed=2).fit(walks)
    assert dw.lookup_table.shape == (6, 8)
    assert dw.similarity(0, 1) > dw.similarity(0, 4)


def test_deepwalk_hierarchical_softmax_embeds_cliques_closer():
    """DeepWalk trained over the Huffman tree (reference DeepWalk.java:31
    hierarchical softmax over GraphHuffman; VERDICT r2 missing #3)."""
    g = _two_cliques()
    dw = DeepWalk(vector_size=16, window_size=3, walk_length=20,
                  walks_per_vertex=6, epochs=4, seed=11,
                  use_hierarchical_softmax=True)
    dw.fit(g)
    assert dw._sv.use_hierarchical_softmax
    same = dw.similarity(0, 1)
    cross = dw.similarity(0, 9)
    assert same > cross, (same, cross)
