"""Data-parallel tests on the 8-device virtual CPU mesh (the analogue of the
reference's Spark local[n] tests, SURVEY.md §4): sync DP convergence parity,
averaging-frequency emulation, ParallelInference batching."""
import threading

import jax
import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd
from deeplearning4j_tpu.parallel import (ParallelInference, ParallelWrapper,
                                         make_mesh)


def _net(seed=3, updater=None):
    conf = (NeuralNetConfiguration(seed=seed, updater=updater or Sgd(0.1))
            .list(DenseLayer(n_in=4, n_out=16, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=256, seed=0):
    r = np.random.default_rng(seed)
    x = r.normal(size=(n, 4)).astype(np.float32)
    yi = (x.sum(-1) > 0).astype(int) + (x[:, 0] > 1).astype(int)
    return x, np.eye(3, dtype=np.float32)[yi]


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    mesh = make_mesh()
    assert mesh.devices.size == 8


def test_sync_dp_matches_single_device_math():
    """Per-step all-reduce DP over sharded batch must equal the single-device
    step on the full batch (same global batch, SGD)."""
    x, y = _data(64)
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    net_a = _net(seed=11)
    net_b = _net(seed=11)
    assert np.allclose(np.asarray(net_a.params_flat()),
                       np.asarray(net_b.params_flat()))
    net_a.fit(x, y, epochs=3, batch_size=64)
    ParallelWrapper(net_b, training_mode="shared_gradients").fit(it, epochs=3)
    assert np.allclose(np.asarray(net_a.params_flat()),
                       np.asarray(net_b.params_flat()), atol=1e-5)


def test_averaging_frequency_mode_converges():
    x, y = _data(512)
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    net = _net(seed=5, updater=Adam(5e-3))
    pw = ParallelWrapper(net, averaging_frequency=4, training_mode="averaging")
    s0 = net.score(x, y)
    pw.fit(it, epochs=15)
    assert net.score(x, y) < s0
    ev = net.evaluate(x, y)
    assert ev.accuracy() > 0.8


def test_parallel_inference_batched():
    net = _net()
    x, _ = _data(64)
    expected = np.asarray(net.output(x))
    pi = ParallelInference(net, batch_limit=64)
    results = {}

    def worker(i):
        results[i] = pi.output(x[i * 8:(i + 1) * 8])

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    pi.shutdown()
    for i in range(8):
        assert np.allclose(results[i], expected[i * 8:(i + 1) * 8], atol=1e-6), i


def test_parallel_inference_sequential():
    net = _net()
    x, _ = _data(16)
    pi = ParallelInference(net, inference_mode="sequential")
    out = pi.output(x)
    assert np.allclose(out, np.asarray(net.output(x)), atol=1e-6)


# ------------------------------------------- ParallelInference regressions
class _RecordingNet:
    """Stub with the one method ParallelInference needs; records every
    merged batch size it is asked to serve."""

    def __init__(self, block_event=None):
        self.batch_sizes = []
        self._block = block_event

    def output(self, x):
        if self._block is not None:
            self._block.wait(10.0)
        self.batch_sizes.append(x.shape[0])
        return np.asarray(x) * 2.0


def test_parallel_inference_never_exceeds_batch_limit():
    """Regression: the dispatch loop checked `total < batch_limit` BEFORE
    popping but appended whatever it popped, so merged batches could
    overshoot the limit. Overflow requests must be deferred, not merged."""
    import time as _time
    gate = threading.Event()
    stub = _RecordingNet(block_event=gate)
    pi = ParallelInference(stub, batch_limit=8, max_wait_ms=50.0)
    xs = [np.full((5, 3), float(i), np.float32) for i in range(4)]
    results = {}

    def worker(i):
        results[i] = pi.output(xs[i])

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
        _time.sleep(0.02)        # deterministic arrival order
    gate.set()                   # release the first dispatch
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive()
    pi.shutdown()
    assert stub.batch_sizes, "nothing dispatched"
    assert max(stub.batch_sizes) <= 8, stub.batch_sizes
    assert sum(stub.batch_sizes) == 20  # every row served exactly once
    for i in range(4):
        assert np.allclose(results[i], xs[i] * 2.0), i


def test_parallel_inference_shutdown_contract():
    """Regression: output() after shutdown() used to enqueue a request no
    worker would ever serve (caller hung forever), and shutdown() never
    resolved queued requests. Now: post-shutdown submit raises, and every
    pending request is resolved (served or failed) — nobody hangs."""
    import time as _time
    gate = threading.Event()
    stub = _RecordingNet(block_event=gate)
    pi = ParallelInference(stub, batch_limit=4, max_wait_ms=1.0)
    outcomes = []

    def worker():
        try:
            outcomes.append(("ok", pi.output(np.ones((2, 3), np.float32))))
        except RuntimeError as e:
            outcomes.append(("err", e))

    threads = [threading.Thread(target=worker) for _ in range(3)]
    for t in threads:
        t.start()
    _time.sleep(0.05)            # first batch blocked on the gate, rest queued
    shut = threading.Thread(target=pi.shutdown)
    shut.start()
    _time.sleep(0.05)
    gate.set()                   # release the in-flight batch
    shut.join(timeout=10)
    assert not shut.is_alive(), "shutdown() hung"
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive(), "caller left hanging across shutdown()"
    assert len(outcomes) == 3    # every caller resolved, one way or another
    with pytest.raises(RuntimeError, match="shut down"):
        pi.output(np.ones((1, 3), np.float32))


def test_model_server_status_codes_and_drain_health():
    """Regression: do_POST collapsed every failure to 400. Malformed
    payloads are 400, model-side failures 500; /health reports queue depth
    and 503 while draining."""
    import json as _json
    import urllib.error
    import urllib.request
    from deeplearning4j_tpu.parallel.model_server import ModelServingServer

    class _FlakyNet:
        def __init__(self):
            self.fail = False

        def output(self, x):
            if self.fail:
                raise RuntimeError("device-side boom")
            return np.asarray(x) * 2.0

    net = _FlakyNet()
    srv = ModelServingServer(net, batched=False)
    port = srv.start()
    base = f"http://127.0.0.1:{port}"

    def post(payload_bytes):
        req = urllib.request.Request(f"{base}/predict", payload_bytes,
                                     {"Content-Type": "application/json"})
        return urllib.request.urlopen(req, timeout=10)

    try:
        # happy path
        body = _json.dumps({"features": [[1.0, 2.0]]}).encode()
        assert post(body).status == 200
        # malformed JSON -> 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(b"{nope")
        assert ei.value.code == 400
        # missing/bad features -> 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(_json.dumps({"features": [["a"]]}).encode())
        assert ei.value.code == 400
        # model-side failure -> 500
        net.fail = True
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(body)
        assert ei.value.code == 500
        net.fail = False
        # health: ok + queue depth
        with urllib.request.urlopen(f"{base}/health", timeout=10) as r:
            h = _json.loads(r.read())
        assert h["status"] == "ok" and h["queue_depth"] == 0
        # draining -> 503 on health AND predict
        srv._draining = True
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/health", timeout=10)
        assert ei.value.code == 503
        assert _json.loads(ei.value.read())["status"] == "draining"
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(body)
        assert ei.value.code == 503
        srv._draining = False
    finally:
        srv.stop()
