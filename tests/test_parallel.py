"""Data-parallel tests on the 8-device virtual CPU mesh (the analogue of the
reference's Spark local[n] tests, SURVEY.md §4): sync DP convergence parity,
averaging-frequency emulation, ParallelInference batching."""
import threading

import jax
import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd
from deeplearning4j_tpu.parallel import (ParallelInference, ParallelWrapper,
                                         make_mesh)


def _net(seed=3, updater=None):
    conf = (NeuralNetConfiguration(seed=seed, updater=updater or Sgd(0.1))
            .list(DenseLayer(n_in=4, n_out=16, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=256, seed=0):
    r = np.random.default_rng(seed)
    x = r.normal(size=(n, 4)).astype(np.float32)
    yi = (x.sum(-1) > 0).astype(int) + (x[:, 0] > 1).astype(int)
    return x, np.eye(3, dtype=np.float32)[yi]


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    mesh = make_mesh()
    assert mesh.devices.size == 8


def test_sync_dp_matches_single_device_math():
    """Per-step all-reduce DP over sharded batch must equal the single-device
    step on the full batch (same global batch, SGD)."""
    x, y = _data(64)
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    net_a = _net(seed=11)
    net_b = _net(seed=11)
    assert np.allclose(np.asarray(net_a.params_flat()),
                       np.asarray(net_b.params_flat()))
    net_a.fit(x, y, epochs=3, batch_size=64)
    ParallelWrapper(net_b, training_mode="shared_gradients").fit(it, epochs=3)
    assert np.allclose(np.asarray(net_a.params_flat()),
                       np.asarray(net_b.params_flat()), atol=1e-5)


def test_averaging_frequency_mode_converges():
    x, y = _data(512)
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    net = _net(seed=5, updater=Adam(5e-3))
    pw = ParallelWrapper(net, averaging_frequency=4, training_mode="averaging")
    s0 = net.score(x, y)
    pw.fit(it, epochs=15)
    assert net.score(x, y) < s0
    ev = net.evaluate(x, y)
    assert ev.accuracy() > 0.8


def test_parallel_inference_batched():
    net = _net()
    x, _ = _data(64)
    expected = np.asarray(net.output(x))
    pi = ParallelInference(net, batch_limit=64)
    results = {}

    def worker(i):
        results[i] = pi.output(x[i * 8:(i + 1) * 8])

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    pi.shutdown()
    for i in range(8):
        assert np.allclose(results[i], expected[i * 8:(i + 1) * 8], atol=1e-6), i


def test_parallel_inference_sequential():
    net = _net()
    x, _ = _data(16)
    pi = ParallelInference(net, inference_mode="sequential")
    out = pi.output(x)
    assert np.allclose(out, np.asarray(net.output(x)), atol=1e-6)
