"""util/retry: capped exponential backoff — deterministic mode, timeout
budget, give-up contract (the shared policy behind the elastic
coordinator and serving /reload checkpoint loads)."""
import zipfile

import pytest

from deeplearning4j_tpu.util.retry import RetryError, RetryPolicy, retry_call


def _flaky(n_failures, exc=OSError):
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        if calls["n"] <= n_failures:
            raise exc(f"flake #{calls['n']}")
        return calls["n"]
    fn.calls = calls
    return fn


def test_deterministic_delays_are_capped():
    p = RetryPolicy(max_attempts=6, base_delay_s=0.1, max_delay_s=0.5,
                    multiplier=2.0)
    assert list(p.delays()) == [0.1, 0.2, 0.4, 0.5, 0.5]


def test_jitter_is_seeded_and_reproducible():
    a = RetryPolicy(max_attempts=5, base_delay_s=0.1, jitter=0.5, seed=7)
    b = RetryPolicy(max_attempts=5, base_delay_s=0.1, jitter=0.5, seed=7)
    da, db = list(a.delays()), list(b.delays())
    assert da == db
    # jittered delays land in [1-jitter, 1] x nominal
    for d, nominal in zip(da, [0.1, 0.2, 0.4, 0.8]):
        assert 0.5 * nominal <= d <= nominal


def test_success_after_transient_failures():
    sleeps = []
    p = RetryPolicy(max_attempts=4, base_delay_s=0.1, sleep=sleeps.append)
    fn = _flaky(2)
    retries = []
    assert p.call(fn, on_retry=lambda i, e: retries.append(str(e))) == 3
    assert fn.calls["n"] == 3
    assert sleeps == [0.1, 0.2]          # no real sleeping, injected
    assert retries == ["flake #1", "flake #2"]


def test_give_up_raises_retry_error_with_chained_cause():
    p = RetryPolicy(max_attempts=3, base_delay_s=0.01, sleep=lambda s: None)
    fn = _flaky(99)
    with pytest.raises(RetryError) as ei:
        p.call(fn)
    assert ei.value.attempts == 3
    assert fn.calls["n"] == 3
    assert isinstance(ei.value.last, OSError)
    assert isinstance(ei.value.__cause__, OSError)


def test_timeout_budget_gives_up_without_terminal_sleep():
    """A retry whose sleep would cross timeout_s gives up immediately —
    no pointless sleep followed by a doomed attempt."""
    t = {"now": 0.0}
    sleeps = []

    def sleep(s):
        sleeps.append(s)
        t["now"] += s

    p = RetryPolicy(max_attempts=10, base_delay_s=1.0, max_delay_s=1.0,
                    timeout_s=2.5, sleep=sleep, clock=lambda: t["now"])
    fn = _flaky(99)
    with pytest.raises(RetryError, match="time budget"):
        p.call(fn)
    # attempt(t=0) -> sleep 1 -> attempt(t=1) -> sleep 1 -> attempt(t=2)
    # -> next sleep would end at t=3 > 2.5 -> give up NOW
    assert fn.calls["n"] == 3
    assert sleeps == [1.0, 1.0]


def test_non_retryable_propagates_untouched():
    p = RetryPolicy(max_attempts=5, base_delay_s=0.01,
                    sleep=lambda s: None,
                    retryable=lambda e: isinstance(e, OSError)
                    and not isinstance(e, FileNotFoundError))
    fn = _flaky(99, exc=FileNotFoundError)
    with pytest.raises(FileNotFoundError):
        p.call(fn)
    assert fn.calls["n"] == 1            # no retries burned


def test_retry_call_convenience():
    assert retry_call(_flaky(1), policy=RetryPolicy(
        max_attempts=2, sleep=lambda s: None)) == 2


def test_reload_policy_shape():
    """The serving /reload policy retries transient I/O but not a missing
    path (FileNotFoundError must stay a fast 400)."""
    from deeplearning4j_tpu.serving.http import _RELOAD_RETRY
    assert _RELOAD_RETRY.retryable(OSError("nfs hiccup"))
    assert _RELOAD_RETRY.retryable(zipfile.BadZipFile("landing"))
    assert not _RELOAD_RETRY.retryable(FileNotFoundError("gone"))
    assert not _RELOAD_RETRY.retryable(ValueError("not a model"))


def test_reload_retries_transient_load_failure(monkeypatch, tmp_path):
    """End-to-end: a load_net that flakes once succeeds on retry through
    the /reload path's policy (unit-level — the HTTP harness is covered
    by test_serving_engine)."""
    from deeplearning4j_tpu.serving import registry as sreg
    from deeplearning4j_tpu.serving.http import _RELOAD_RETRY

    calls = {"n": 0}

    def flaky_load(path):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("transient read error")
        return "net"

    monkeypatch.setattr(_RELOAD_RETRY, "_sleep", lambda s: None)
    monkeypatch.setattr(sreg, "load_net", flaky_load)
    assert _RELOAD_RETRY.call(sreg.load_net, str(tmp_path / "m.zip")) == "net"
    assert calls["n"] == 2
