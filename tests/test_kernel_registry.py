"""Kernel registry + autotune harness (ISSUE 17 tentpole).

Pins:
  - every registered kernel carries a parity pin — the parity test below
    is AUTO-GENERATED from the registry, so registering a kernel without
    a pin fails tier-1 by construction;
  - per-kernel interpret-mode CPU parity: fused (pallas interpreter) vs
    XLA fallback within the kernel's declared tolerance (0.0 = bitwise);
  - kill-switch/interpret env resolution is the ONE shared envutil
    implementation: canonical ``DL4J_TPU_KERNEL_<NAME>`` names win,
    legacy ``DL4J_TPU_FUSED_*`` names keep working as aliases
    (regression for every pre-registry script and runbook);
  - autotune decisions are measured once, cached per (kernel, shape-sig,
    backend), and REPLAYED without re-measurement; no-measurement
    backends record "defaults stand" with the reason; a cached decision
    actually changes ``pallas_attention._blocks`` while explicit env
    overrides still win;
  - ``kernels_snapshot()`` rides ``perf_snapshot()`` and
    ``record_kernel_timing`` publishes the roofline-vs-measured gauges.
"""
import json
import os

import numpy as np
import pytest

from deeplearning4j_tpu.ops import kernels
from deeplearning4j_tpu.ops.kernels import autotune, envutil

BUILTINS = ("attention", "lstm", "threshold_encode", "int8_matmul",
            "conv1x1_bias_relu")


# ----------------------------------------------------------------- registry
def test_builtin_kernels_registered():
    have = kernels.names()
    for name in BUILTINS:
        assert name in have, f"builtin kernel {name!r} missing"


def test_duplicate_registration_rejected():
    spec = kernels.get("attention")
    with pytest.raises(ValueError, match="already registered"):
        kernels.register(spec)


@pytest.mark.parametrize("name", kernels.names())
def test_every_kernel_has_parity_pin(name):
    """A kernel registered without a ParityPin fails tier-1 (the contract
    that makes the parity suite auto-generated rather than opt-in)."""
    spec = kernels.get(name)
    assert spec.parity is not None, \
        f"kernel {name!r} registered without a parity pin"
    assert spec.available() in (True, False)


@pytest.mark.parametrize("name", kernels.names())
def test_kernel_parity_interpret_mode(name, monkeypatch):
    """Auto-generated per-kernel pin: fused impl (CPU pallas interpreter)
    vs XLA fallback on identical inputs, within the declared tol."""
    spec = kernels.get(name)
    if not spec.available():
        pytest.skip("pallas unavailable on this install")
    monkeypatch.setenv(spec.interpret_env, "1")
    for alias in spec.interpret_aliases:
        monkeypatch.setenv(alias, "1")
    monkeypatch.delenv(spec.kill_env, raising=False)
    for alias in spec.kill_aliases:
        monkeypatch.delenv(alias, raising=False)
    fused, fallback = spec.parity.run(0)
    assert len(fused) == len(fallback) and fused
    for a, b in zip(fused, fallback):
        err = float(np.max(np.abs(np.asarray(a, np.float64)
                                  - np.asarray(b, np.float64))))
        assert err <= spec.parity.tol, \
            (name, err, spec.parity.tol, spec.parity.note)


# ------------------------------------------------------------ env plumbing
def test_env_names_canonical():
    assert envutil.kill_env_name("int8_matmul") == \
        "DL4J_TPU_KERNEL_INT8_MATMUL"
    assert envutil.interpret_env_name("conv1x1_bias_relu") == \
        "DL4J_TPU_KERNEL_CONV1X1_BIAS_RELU_INTERPRET"


@pytest.mark.parametrize("name,legacy", [
    ("attention", "DL4J_TPU_FUSED_ATTENTION"),
    ("lstm", "DL4J_TPU_FUSED_LSTM"),
    ("threshold_encode", "DL4J_TPU_FUSED_ENCODE"),
])
def test_legacy_kill_aliases_honored(name, legacy, monkeypatch):
    """Regression: the pre-registry DL4J_TPU_FUSED_* kill switches keep
    working through the registry dispatch."""
    spec = kernels.get(name)
    monkeypatch.delenv(spec.kill_env, raising=False)
    assert spec.enabled()
    for off in ("0", "false", "OFF"):
        monkeypatch.setenv(legacy, off)
        assert not spec.enabled(), (legacy, off)
        assert kernels.active_impl(name) == "fallback"
    # canonical name wins when both are set
    monkeypatch.setenv(spec.kill_env, "1")
    monkeypatch.setenv(legacy, "0")
    assert spec.enabled()


def test_canonical_kill_switch_new_kernels(monkeypatch):
    spec = kernels.get("int8_matmul")
    assert spec.kill_aliases == ()
    assert spec.enabled()
    monkeypatch.setenv("DL4J_TPU_KERNEL_INT8_MATMUL", "0")
    assert not spec.enabled()
    assert kernels.active_impl("int8_matmul") == "fallback"


def test_legacy_interpret_aliases_honored(monkeypatch):
    spec = kernels.get("attention")
    monkeypatch.delenv(spec.interpret_env, raising=False)
    monkeypatch.delenv("DL4J_TPU_FUSED_ATTN_INTERPRET", raising=False)
    assert not spec.interpret_opted_in()
    assert kernels.active_impl("attention") == "fallback"   # cpu, no opt-in
    monkeypatch.setenv("DL4J_TPU_FUSED_ATTN_INTERPRET", "1")
    assert spec.interpret_opted_in()
    if spec.available():
        assert kernels.active_impl("attention") == "interpret"


def test_backend_admits_rule(monkeypatch):
    monkeypatch.delenv("DL4J_TPU_KERNEL_LSTM_INTERPRET", raising=False)
    monkeypatch.delenv("DL4J_TPU_FUSED_LSTM_INTERPRET", raising=False)
    aliases = ("DL4J_TPU_FUSED_LSTM_INTERPRET",)
    assert envutil.backend_admits("lstm", "tpu", aliases)
    assert not envutil.backend_admits("lstm", "cpu", aliases)
    assert not envutil.backend_admits("lstm", "gpu", aliases)
    monkeypatch.setenv("DL4J_TPU_FUSED_LSTM_INTERPRET", "1")
    assert envutil.backend_admits("lstm", "cpu", aliases)
    assert not envutil.backend_admits("lstm", "gpu", aliases)


# ---------------------------------------------------------------- autotune
@pytest.fixture
def tuned_cache(tmp_path, monkeypatch):
    path = str(tmp_path / "autotune.json")
    monkeypatch.setenv("DL4J_TPU_AUTOTUNE_CACHE", path)
    return path


def test_autotune_measures_and_changes_default(tuned_cache):
    times = {(512, 1024): 3.0e-3, (256, 512): 1.0e-3, (128, 128): 2.0e-3}
    calls = []

    def measure(cand):
        calls.append(cand)
        return times[tuple(cand)]

    rec = autotune.decide("attention", "T9999", list(times), measure,
                          default=(512, 1024))
    assert rec["choice"] == [256, 512]
    assert rec["changed_default"] is True
    assert "argmin" in rec["why"]
    assert len(calls) == 3
    # persisted: a fresh load sees the decision
    with open(tuned_cache) as f:
        data = json.load(f)
    assert data["autotune_cache"] == 1
    key = autotune.AutotuneCache.key("attention", "T9999",
                                     autotune._backend())
    assert data["decisions"][key]["choice"] == [256, 512]


def test_autotune_replays_without_remeasuring(tuned_cache):
    def measure(cand):
        return 1.0e-3

    autotune.decide("attention", "T777", [(512, 1024)], measure,
                    default=(512, 1024))

    def boom(cand):
        raise AssertionError("replay must not re-measure")

    rec = autotune.decide("attention", "T777", [(512, 1024)], boom,
                          default=(512, 1024))
    assert rec["choice"] == [512, 1024]
    assert rec["replays"] == 1
    assert autotune.cached_decision("attention", "T777") == [512, 1024]
    with open(tuned_cache) as f:
        data = json.load(f)
    key = autotune.AutotuneCache.key("attention", "T777",
                                     autotune._backend())
    assert data["decisions"][key]["replays"] == 2


def test_autotune_defaults_stand_without_measurement(tuned_cache):
    """Off-TPU there is nothing trustworthy to measure — the harness must
    RECORD that defaults stand (auditable), not silently skip."""
    rec = autotune.decide("attention", "T555", [(512, 1024), (256, 256)],
                          None, default=(512, 1024))
    assert rec["choice"] == [512, 1024]
    assert rec["changed_default"] is False
    assert "defaults stand" in rec["why"]
    assert autotune.decisions_for("attention")


def test_autotune_corrupt_cache_is_empty(tuned_cache):
    with open(tuned_cache, "w") as f:
        f.write("{not json")
    assert autotune.cached_decision("attention", "T1024") is None
    rec = autotune.decide("attention", "T1024", [(512, 1024)], None,
                          default=(512, 1024))
    assert rec["choice"] == [512, 1024]


def test_attention_blocks_resolution_order(tuned_cache, monkeypatch):
    """env override -> cached autotune decision -> hand-tuned defaults."""
    from deeplearning4j_tpu.ops.pallas_attention import _blocks
    monkeypatch.delenv("DL4J_TPU_ATTN_BQ", raising=False)
    monkeypatch.delenv("DL4J_TPU_ATTN_BK", raising=False)
    # empty cache: the v5e-sweep defaults
    assert _blocks(1024) == (512, 1024)
    # a cached decision for this (T, backend) takes over
    autotune.get_cache().store(
        "attention", "T1024", autotune._backend(),
        {"choice": [256, 512], "default": [512, 1024],
         "changed_default": True, "replays": 0, "measured_ms": {},
         "why": "test"})
    assert _blocks(1024) == (256, 512)
    # a non-dividing cached choice is ignored, not an error
    autotune.get_cache().store(
        "attention", "T384", autotune._backend(),
        {"choice": [256, 512], "default": [512, 1024],
         "changed_default": True, "replays": 0, "measured_ms": {},
         "why": "test"})
    assert _blocks(384) == (128, 128)
    # explicit env override wins over the cache
    monkeypatch.setenv("DL4J_TPU_ATTN_BQ", "128")
    assert _blocks(1024) == (128, 1024)


# ------------------------------------------------- snapshot + perf gauges
def test_kernels_snapshot_shape(tuned_cache):
    snap = kernels.kernels_snapshot()
    for name in BUILTINS:
        row = snap[name]
        assert row["impl"] in ("fused", "interpret", "fallback")
        assert row["has_parity_pin"] is True
        assert row["kill_env"] == envutil.kill_env_name(name)
        assert row["interpret_env"] == envutil.interpret_env_name(name)
    assert snap["attention"]["kill_aliases"] == ["DL4J_TPU_FUSED_ATTENTION"]
    assert snap["attention"]["default_choice"] == [512, 1024]
    # an autotune decision shows up on the row
    autotune.decide("int8_matmul", "64x256x256", [(32, 128)], None,
                    default=(32, 128))
    snap = kernels.kernels_snapshot()
    assert snap["int8_matmul"]["autotune"]


def test_perf_snapshot_carries_kernels():
    from deeplearning4j_tpu.telemetry.perf import perf_snapshot
    out = perf_snapshot()
    assert "kernels" in out
    assert set(BUILTINS) <= set(out["kernels"])


def test_record_kernel_timing_publishes_roofline_gauges():
    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.telemetry import MetricsRegistry
    reg = MetricsRegistry(enabled=True)
    prev = telemetry.set_registry(reg)
    try:
        # run far above the bound: the below_roofline flag must trip
        row = kernels.record_kernel_timing("int8_matmul", "64x256x256",
                                           measured_s=10.0)
        assert row is not None
        assert row["vs_roofline"] > 2.0
        base = "perf.kernels.int8_matmul"
        assert reg.gauge(f"{base}.below_roofline").value == 1.0
        assert reg.gauge(f"{base}.measured_ms").value == \
            pytest.approx(10.0 * 1e3)
        assert reg.gauge(f"{base}.roofline_ms").value > 0
    finally:
        telemetry.set_registry(prev)
    assert kernels.record_kernel_timing("int8_matmul", "bogus", 1.0) is None
    assert kernels.record_kernel_timing("lstm", "4x8x128", 0.0) is None


# -------------------------------------------------------------------- bench
@pytest.mark.bench_smoke
def test_int8_matmul_bench_smoke():
    """Tier-1 guard for the int8_serving_matmul row: the paired windows
    run, the quantized logits stay within the bounded-error tier, and the
    timings are sane. (No speedup gate off-TPU: the int8 side runs the
    XLA fallback there, and an int8 CPU GEMM may legitimately lose to
    f32 — the row's ratio is rig information, not an acceptance.)"""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    row = bench.bench_int8_matmul(repeats=2, batch=64)
    assert row["max_rel_err"] < 0.05, row
    assert row["int8_ms"] > 0 and row["f32_ms"] > 0
    assert row["int8_vs_f32_speedup"] > 0
