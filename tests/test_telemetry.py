"""Unified telemetry (ISSUE 4 tentpole): MetricsRegistry + structured
spans + jax signal capture, wired through the training/prefetch/serving
hot paths WITHOUT adding device syncs.

Acceptance contracts pinned here:
- a short fused-window run produces a Chrome-trace whose spans nest
  fit -> epoch -> window (-> dispatch), with XLA compile events attributed
  to the span they happened under;
- RecompileDetector flags an intentionally shape-unstable loop (naming
  the offending span path) while the warmed serving path stays at zero;
- the instrumented fit path performs ZERO extra device->host transfers vs
  uninstrumented (score_to_float counting harness from test_scan_window +
  the HostSyncDetector tripwire), and a disabled registry is a near-no-op;
- the telemetry_overhead_pct bench row reports <5% on the dispatch-bound
  CPU loop (bench_smoke guard).
"""
import json
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (MultiLayerNetwork, NeuralNetConfiguration,
                                telemetry)
from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.listeners import (
    CollectScoresIterationListener, PerformanceListener,
    ScoreIterationListener)
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd
from deeplearning4j_tpu.telemetry import (HostSyncDetector, HostSyncError,
                                          MetricsRegistry, RecompileDetector,
                                          current_span_path, span)


@pytest.fixture
def fresh_registry():
    """Isolate each test in its own enabled registry (the built-in
    instrumentation resolves get_registry() live, so swapping works in
    any test order — the reversed-order harness included)."""
    reg = MetricsRegistry(enabled=True)
    prev = telemetry.set_registry(reg)
    try:
        yield reg
    finally:
        telemetry.set_registry(prev)


def _tiny_net(seed=12, updater=None):
    conf = (NeuralNetConfiguration(seed=seed, updater=updater or Sgd(0.1))
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _toy(rng, n=64):
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=n)]
    return x, y


def _it(x, y, bs=8):
    return ListDataSetIterator(features=x, labels=y, batch_size=bs)


# ------------------------------------------------------------- registry core
def test_registry_counters_gauges_histograms(fresh_registry):
    reg = fresh_registry
    reg.counter("c").inc()
    reg.counter("c").inc(4)
    reg.gauge("g").set(2.0)
    reg.gauge("g").set(1.0)
    for v in range(100):
        reg.histogram("h_ms").observe(float(v))
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 5
    assert snap["gauges"]["g"] == {"value": 1.0, "max": 2.0}
    h = snap["histograms"]["h_ms"]
    assert h["count"] == 100 and h["p50"] == 50.0
    # nearest-rank on 0..99: round(q * 99)
    assert h["p95"] == 94.0 and h["p99"] == 98.0
    # same-name accessors return the same object (cheap hot-path lookups)
    assert reg.counter("c") is reg.counter("c")


def test_registry_prometheus_dump(fresh_registry):
    reg = fresh_registry
    reg.counter("train.iterations").inc(7)
    reg.gauge("prefetch.queue_depth").set(3)
    reg.histogram("serving.default.latency_ms").observe(4.0)
    text = reg.to_prometheus_text()
    assert "# TYPE dl4j_tpu_train_iterations counter" in text
    assert "dl4j_tpu_train_iterations 7" in text
    assert "dl4j_tpu_prefetch_queue_depth 3" in text
    # ISSUE 13: conformant histogram exposition — _bucket with le labels
    assert "# TYPE dl4j_tpu_serving_default_latency_ms histogram" in text
    assert 'dl4j_tpu_serving_default_latency_ms_bucket{le="5"} 1' in text
    assert 'dl4j_tpu_serving_default_latency_ms_bucket{le="2.5"} 0' in text
    assert 'dl4j_tpu_serving_default_latency_ms_bucket{le="+Inf"} 1' in text
    assert "dl4j_tpu_serving_default_latency_ms_count 1" in text
    # the pre-ISSUE-13 ad-hoc quantile keys survive under the compat flag
    compat = reg.to_prometheus_text(compat_quantiles=True)
    assert 'dl4j_tpu_serving_default_latency_ms{quantile="0.99"} 4.0' \
        in compat
    assert "_bucket" not in compat


def test_trace_seq_cursoring(fresh_registry):
    """ISSUE 19: every recorded event carries a monotonic ``seq`` and
    ``trace_events_since`` returns only the delta — the incremental-pull
    contract the replica's /debug/trace route and the fleet collector's
    cursors are built on."""
    reg = fresh_registry
    assert reg.last_seq == 0
    with span("a"):
        pass
    with span("b"):
        pass
    events = reg.trace_events()
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    cursor = seqs[0]
    delta = reg.trace_events_since(cursor)
    assert [e["seq"] for e in delta] == [s for s in seqs if s > cursor]
    assert reg.trace_events_since(reg.last_seq) == []
    # a stale (pre-ring) cursor returns the whole ring, never raises
    assert len(reg.trace_events_since(-1)) == len(events)


def test_raw_metrics_round_trips_histogram_buckets(fresh_registry):
    """raw_metrics() is the mergeable wire format: cumulative buckets on
    the canonical ladder, counter values, gauge value+max."""
    reg = fresh_registry
    reg.counter("c").inc(3)
    reg.gauge("g").set(2.5)
    h = reg.histogram("lat_ms")
    for v in (1.0, 4.0, 900.0):
        h.observe(v)
    raw = reg.raw_metrics()
    assert raw["counters"]["c"] == 3
    assert raw["gauges"]["g"]["value"] == 2.5
    hr = raw["histograms"]["lat_ms"]
    assert hr["count"] == 3 and hr["cumulative"][-1] == 3
    assert hr["bounds"] == list(h.bounds)
    # cumulative is monotone non-decreasing
    assert all(a <= b for a, b in zip(hr["cumulative"],
                                      hr["cumulative"][1:]))


def test_trace_spool_round_trip_and_skip(fresh_registry, tmp_path):
    """The crash-durable black box: flush writes an atomic, parseable
    spill of ring tail + raw metrics; an unchanged ring skips the disk
    write; stop() force-flushes the final state."""
    from deeplearning4j_tpu.telemetry import TraceSpool, read_spool
    reg = fresh_registry
    path = str(tmp_path / "replica-r7.spool.json")
    spool = TraceSpool(path, replica_id="r7", registry=reg, capacity=4)
    with span("work"):
        reg.counter("done").inc()
    assert spool.flush() is True
    spill = read_spool(path)
    assert spill["replica"] == "r7" and spill["seq"] == reg.last_seq
    assert spill["metrics"]["counters"]["done"] == 1
    assert [e["name"] for e in spill["events"]] == ["work"]
    # no ring advance -> flush is a no-op (idle replicas cost zero I/O)
    assert spool.flush() is False and spool.skipped == 1
    for i in range(8):
        with span(f"s{i}"):
            pass
    assert spool.flush() is True
    spill = read_spool(path)
    assert len(spill["events"]) == 4         # capacity bounds the tail
    assert spill["events"][-1]["name"] == "s7"
    # absent / garbage files read as None, never raise
    assert read_spool(str(tmp_path / "nope.json")) is None
    (tmp_path / "junk.json").write_text("{not json")
    assert read_spool(str(tmp_path / "junk.json")) is None


def test_registry_stats_storage_bridge(fresh_registry):
    from deeplearning4j_tpu.ui import InMemoryStatsStorage
    reg = fresh_registry
    reg.counter("jax.compiles").inc(2)
    store = InMemoryStatsStorage()
    snap = reg.publish(store, session_id="telemetry", worker_id="runtime")
    assert snap["counters"]["jax.compiles"] == 2
    got = store.get_latest_update("telemetry", "runtime")
    assert got["counters"]["jax.compiles"] == 2


def test_disabled_registry_is_near_noop(fresh_registry):
    reg = fresh_registry
    reg.enabled = False
    reg.counter("c").inc()
    reg.gauge("g").set(1.0)
    reg.histogram("h").observe(1.0)
    with span("nothing", k=1):
        pass
    reg.enabled = True
    snap = reg.snapshot()
    assert snap["counters"] == {} and snap["gauges"] == {}
    assert snap["histograms"] == {}
    assert reg.trace_events() == []
    # disabled span() returns the shared no-op (no allocation per call)
    reg.enabled = False
    assert span("a") is span("b")
    reg.enabled = True


# ------------------------------------------------------------------- spans
def test_span_nesting_and_paths(fresh_registry):
    reg = fresh_registry
    with span("outer", a=1):
        assert current_span_path() == "outer"
        with span("inner"):
            assert current_span_path() == "outer/inner"
        assert current_span_path() == "outer"
    assert current_span_path() == ""
    paths = [e["args"]["path"] for e in reg.trace_events()]
    assert paths == ["outer/inner", "outer"]     # children close first
    # spans auto-feed duration histograms
    assert reg.histogram("span.outer_ms").count == 1


def test_span_manual_start_end_tolerates_interleaving(fresh_registry):
    reg = fresh_registry
    # a manually-opened span (ProfilerListener pattern) survives lexical
    # spans opening and closing around it
    s = span("capture").start()
    with span("step"):
        pass
    s.end()
    names = [e["name"] for e in reg.trace_events()]
    assert names == ["step", "capture"]
    ev = {e["name"]: e for e in reg.trace_events()}
    assert ev["capture"]["args"]["path"] == "capture"
    assert ev["step"]["args"]["path"] == "capture/step"


def test_chrome_trace_file_format(fresh_registry, tmp_path):
    reg = fresh_registry
    with span("a"):
        with span("b"):
            pass
    path = reg.write_chrome_trace(str(tmp_path / "t.trace.json"))
    text = open(path).read()
    events = json.loads(text)                    # valid JSON array
    assert [e["name"] for e in events] == ["b", "a"]
    # one event per line (JSONL-style body: Perfetto + line tools friendly)
    body = [ln for ln in text.splitlines() if ln not in ("[", "]")]
    assert len(body) == 2
    for ln in body:
        json.loads(ln.rstrip(","))
    for e in events:                             # Chrome-trace complete events
        assert e["ph"] == "X" and "ts" in e and "dur" in e


# ----------------------------------------------- fit -> trace (acceptance)
def test_fused_fit_trace_nests_and_attributes_compiles(fresh_registry,
                                                       tmp_path, rng):
    """A short fused-window run: spans nest fit -> epoch -> window ->
    dispatch, compile events carry the span path they happened under, and
    the registry counts iterations/windows."""
    reg = fresh_registry
    x, y = _toy(rng)
    net = _tiny_net(updater=Adam(1e-2))
    net.fit(iterator=_it(x, y), epochs=2, steps_per_dispatch=4)

    events = json.load(open(reg.write_chrome_trace(
        str(tmp_path / "fit.trace.json"))))
    spans_ = [e for e in events if e.get("cat") == "span"]
    paths = {e["args"]["path"] for e in spans_}
    assert {"fit", "fit/epoch", "fit/epoch/window",
            "fit/epoch/window/dispatch"} <= paths
    by_name = {}
    for e in spans_:
        by_name.setdefault(e["name"], []).append(e)
    assert len(by_name["fit"]) == 1
    assert len(by_name["epoch"]) == 2
    assert len(by_name["window"]) == 4           # 8 batches / K=4, 2 epochs
    # parent spans contain their children in time (ts/dur nesting)
    fit_ev = by_name["fit"][0]
    for e in by_name["window"]:
        assert fit_ev["ts"] <= e["ts"]
        assert e["ts"] + e["dur"] <= fit_ev["ts"] + fit_ev["dur"] + 1000
    # the first window traced + compiled: events attributed to fit spans
    compiles = [e for e in events if e.get("cat") == "compile"]
    assert compiles, "no backend-compile events captured"
    assert any(e["args"]["path"].startswith("fit/epoch/window")
               for e in compiles)
    snap = reg.snapshot()
    assert snap["counters"]["train.iterations"] == 16
    assert snap["counters"]["train.windows"] == 4
    assert snap["counters"]["jax.compiles"] >= 1
    assert reg.histogram("span.dispatch_ms").count == 4


def test_parallel_wrapper_fit_emits_spans(fresh_registry, rng):
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
    reg = fresh_registry
    x, y = _toy(rng)
    net = _tiny_net()
    ParallelWrapper(net, steps_per_dispatch=2).fit(_it(x, y, bs=16), epochs=1)
    paths = {e["args"]["path"] for e in reg.trace_events()
             if e.get("cat") == "span"}
    assert "fit/epoch/window/dispatch" in paths
    assert reg.snapshot()["counters"]["train.iterations"] == 4


def test_prefetch_reports_queue_and_stall(fresh_registry, rng):
    from deeplearning4j_tpu.datasets.prefetch import DevicePrefetchIterator
    reg = fresh_registry
    x, y = _toy(rng)
    it = DevicePrefetchIterator(_it(x, y), depth=2, dtype="float32")
    batches = list(it)
    assert len(batches) == 8
    snap = reg.snapshot()
    assert snap["counters"]["prefetch.batches"] == 8
    assert snap["histograms"]["prefetch.wait_ms"]["count"] == 8
    assert snap["histograms"]["prefetch.ship_ms"]["count"] == 8
    assert "prefetch.queue_depth" in snap["gauges"]


# -------------------------------------------------------- recompile detector
def test_recompile_detector_flags_shape_unstable_loop(fresh_registry,
                                                      caplog):
    """Acceptance: an intentionally shape-unstable loop is flagged, with
    the offending span path in the warning."""
    f = jax.jit(lambda a: (a * 2.0).sum())
    with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
        with RecompileDetector(allowed=0) as det:
            with span("unstable_loop"):
                for n in (3, 4, 5):          # new shape -> retrace, each call
                    f(jnp.ones((n,), jnp.float32))
    assert det.count >= 3
    assert det.recompiles == det.count
    assert {e["span_path"] for e in det.events} == {"unstable_loop"}
    assert any("unstable_loop" in r.message for r in caplog.records)
    assert fresh_registry.snapshot()["counters"]["jax.compiles"] >= 3


def test_recompile_detector_scoped_and_stable_loop_clean(fresh_registry):
    g = jax.jit(lambda a: a + 1.0)
    g(jnp.ones((4,), jnp.float32))               # compile OUTSIDE the scope
    with RecompileDetector(warn=False) as det:
        for _ in range(5):
            g(jnp.ones((4,), jnp.float32))       # steady state: no traces
    assert det.count == 0


@pytest.mark.bench_smoke
def test_serving_warm_path_zero_recompiles_under_detector(fresh_registry):
    """Steady-state serving through the warmed engine stays at ZERO
    compiles — now asserted via the first-class detector, not just the
    raw counter."""
    from deeplearning4j_tpu.serving import InferenceEngine
    net = _tiny_net(seed=31)
    rng = np.random.default_rng(5)
    sizes = [1, 3, 8, 5, 2, 8]
    for n in sizes:                              # warm net.output shapes
        net.output(rng.normal(size=(n, 4)).astype(np.float32))
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(4, 8),
                          batch_window_ms=0.5)
    try:
        eng.predict(rng.normal(size=(3, 4)).astype(np.float32))  # settle
        with RecompileDetector(allowed=0) as det:
            for n in sizes:
                out = eng.predict(rng.normal(size=(n, 4)).astype(np.float32))
                assert out.shape == (n, 3)
        assert det.count == 0, det.events
    finally:
        eng.stop()


# -------------------------------------------------------- host-sync detector
def test_host_sync_detector_flags_readback_with_span_path(fresh_registry):
    with HostSyncDetector(action="count") as det:
        with span("fused_window"):
            v = jax.jit(lambda a: a.sum())(jnp.arange(4.0))
            float(v)                              # the accidental sync
    assert det.count == 1
    assert det.events[0]["span_path"] == "fused_window"
    assert fresh_registry.snapshot()["counters"]["jax.host_syncs_flagged"] == 1


def test_host_sync_detector_raise_mode(fresh_registry):
    with pytest.raises(HostSyncError, match="device->host"):
        with HostSyncDetector(action="raise"):
            float(jax.jit(lambda a: a.sum())(jnp.arange(3.0)))


def test_host_sync_detector_scope_and_cached_reads(fresh_registry):
    v = jax.jit(lambda a: a * 2.0)(jnp.arange(4.0))
    float(v.sum())                                # outside: not flagged
    w = jax.jit(lambda a: a * 3.0)(jnp.arange(4.0))
    wsum = w.sum()
    float(wsum)                                   # materialized BEFORE scope
    with HostSyncDetector(action="count") as det:
        float(wsum)                               # cached: no device sync
    assert det.count == 0


# ------------------------------------------------- sync-freedom (acceptance)
def test_instrumented_fit_adds_zero_host_syncs(fresh_registry, rng,
                                               monkeypatch):
    """The tier-1 sync-freedom contract: the INSTRUMENTED fit path (spans +
    counters live) performs zero score readbacks inside the loop (the
    score_to_float harness from test_scan_window) and zero device->host
    materializations (HostSyncDetector tripwire) — identical to a
    disabled-registry run, in both fused and per-step modes."""
    import deeplearning4j_tpu.optimize.listeners as L
    x, y = _toy(rng, n=32)
    calls = {"n": 0}
    orig = L.score_to_float

    def counting(s):
        calls["n"] += 1
        return orig(s)

    logger = logging.getLogger("deeplearning4j_tpu")
    old = logger.level
    logger.setLevel(logging.WARNING)
    try:
        monkeypatch.setattr(L, "score_to_float", counting)
        for enabled in (True, False):
            fresh_registry.enabled = enabled
            for k in (1, 2):
                net = _tiny_net()
                collect = CollectScoresIterationListener()
                net.set_listeners(collect, ScoreIterationListener(2))
                # warm-up epoch first: jit tracing may legitimately touch
                # host values; the contract is about the steady-state loop
                net.fit(iterator=_it(x, y), epochs=1, steps_per_dispatch=k,
                        async_prefetch=False)
                calls["n"] = 0
                with HostSyncDetector(action="count") as det:
                    net.fit(iterator=_it(x, y), epochs=1,
                            steps_per_dispatch=k, async_prefetch=False)
                assert calls["n"] == 0, \
                    f"enabled={enabled} K={k}: {calls['n']} score readbacks"
                assert det.count == 0, \
                    f"enabled={enabled} K={k}: syncs at " \
                    f"{[e['span_path'] for e in det.events]}"
                assert len(collect.scores) == 8    # flush still works after
    finally:
        fresh_registry.enabled = True
        logger.setLevel(old)


# ----------------------------------------------- PerformanceListener fusion
def test_performance_listener_window_aligned_reports(fresh_registry):
    """K-fused accounting: a report falling due mid-window defers to the
    window's last step, every fused step is counted, and the record
    carries windowed_steps_per_sec + steps_per_dispatch. Log format is
    unchanged."""
    lst = PerformanceListener(frequency=2)
    it = 0
    for _ in range(2):                       # two windows of K=4
        lst.note_window(4)
        for _ in range(4):
            lst.note_batch(8, etl_wait_ms=0.5, device_ms=1.0)
            lst.iteration_done(None, it, 0.25)
            it += 1
    # iteration 2 was report-due mid-window -> deferred to window end (3);
    # iterations 4 and 6 due mid second window -> deferred to 7
    assert [r["iteration"] for r in lst.history] == [3, 7]
    r = lst.history[0]
    assert r["steps_per_dispatch"] == 4.0
    assert r["windowed_steps_per_sec"] == r["batches_per_sec"] > 0
    assert r["samples_per_sec"] > 0
    assert r["score"] == 0.25
    # shared-registry mirror
    snap = fresh_registry.snapshot()
    assert snap["gauges"]["train.steps_per_dispatch"]["value"] == 4.0
    assert snap["histograms"]["train.etl_wait_ms"]["count"] == 2


def test_performance_listener_per_step_reports_unchanged(fresh_registry):
    lst = PerformanceListener(frequency=2)
    for it in range(7):
        lst.note_batch(8, etl_wait_ms=0.1, device_ms=0.2)
        lst.iteration_done(None, it, 1.0)
    assert [r["iteration"] for r in lst.history] == [2, 4, 6]
    r = lst.history[-1]
    assert r["steps_per_dispatch"] == 1.0
    assert r["etl_wait_ms_per_iteration"] == pytest.approx(0.1)
    assert r["etl_ms_per_iteration"] == r["etl_wait_ms_per_iteration"]


def test_performance_listener_fused_fit_history(fresh_registry, rng):
    """End to end through the fused Solver path: history rows carry the
    fused-dispatch fields and samples/sec counts every fused step."""
    x, y = _toy(rng)
    net = _tiny_net()
    perf = PerformanceListener(frequency=4)
    net.set_listeners(perf)
    net.fit(iterator=_it(x, y), epochs=3, steps_per_dispatch=4,
            async_prefetch=False)
    assert perf.history, "no reports"
    for r in perf.history:
        assert r["steps_per_dispatch"] == 4.0
        assert r["windowed_steps_per_sec"] > 0


# ------------------------------------------------------ serving integration
def test_serving_metrics_mirror_into_registry(fresh_registry):
    from deeplearning4j_tpu.serving.metrics import ServingMetrics
    m = ServingMetrics(name="digits")
    m.record_request(4.2, rows=3)
    m.record_queue_wait(1.1)
    m.record_batch(bucket=8, rows=6)
    m.record_rejection("full")
    m.record_swap()
    snap = m.snapshot()                      # GET /metrics payload: stable
    assert snap["requests"] == 1 and snap["rows"] == 3
    assert set(snap) == {"requests", "rows", "batches", "latency_ms",
                         "queue_wait_ms", "batch_occupancy", "padding_waste",
                         "per_bucket", "rejected", "hot_swaps", "uptime_s"}
    reg = fresh_registry.snapshot()
    assert reg["counters"]["serving.digits.requests"] == 1
    assert reg["counters"]["serving.digits.rejected.full"] == 1
    assert reg["counters"]["serving.digits.hot_swaps"] == 1
    assert reg["histograms"]["serving.digits.latency_ms"]["count"] == 1
    assert reg["gauges"]["serving.digits.batch_occupancy"]["value"] == \
        pytest.approx(0.75)


def test_engine_metrics_reach_shared_registry(fresh_registry):
    from deeplearning4j_tpu.serving import InferenceEngine
    net = _tiny_net(seed=77)
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(4,),
                          batch_window_ms=0.5)
    try:
        x = np.random.default_rng(1).normal(size=(2, 4)).astype(np.float32)
        eng.predict(x)
    finally:
        eng.stop()
    snap = fresh_registry.snapshot()
    assert snap["counters"]["serving.default.requests"] == 1
    assert snap["histograms"]["serving.default.latency_ms"]["count"] == 1
    # one surface: training-style prometheus dump carries serving p99
    assert "dl4j_tpu_serving_default_latency_ms" in \
        fresh_registry.to_prometheus_text()


# ------------------------------------------------------------ dashboard card
def test_dashboard_renders_telemetry_card(fresh_registry, rng):
    from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener
    from deeplearning4j_tpu.ui.dashboard import render_dashboard_html
    reg = fresh_registry
    reg.counter("jax.compiles").inc(3)
    reg.histogram("prefetch.wait_ms").observe(1.5)
    reg.histogram("serving.default.latency_ms").observe(9.0)
    store = InMemoryStatsStorage()
    net = _tiny_net()
    net.set_listeners(StatsListener(store, session_id="s"))
    x, y = _toy(rng, n=16)
    net.fit(x, y, epochs=1, batch_size=16)
    page = render_dashboard_html(store)
    assert "Runtime telemetry" in page
    assert "XLA compiles" in page
    assert "prefetch stall p95 (ms)" in page
    assert "serving p99 [default] (ms)" in page
    assert "train.iterations" in page            # fit's own counters render


def test_dashboard_without_telemetry_omits_card(fresh_registry):
    from deeplearning4j_tpu.ui import InMemoryStatsStorage
    from deeplearning4j_tpu.ui.dashboard import render_dashboard_html
    fresh_registry.enabled = False
    store = InMemoryStatsStorage()
    store.put_static_info("s", "w", {"a": 1})
    store.put_update("s", "w", {"iteration": 0, "score": 1.0})
    page = render_dashboard_html(store)
    assert "Runtime telemetry" not in page
    fresh_registry.enabled = True


# ----------------------------------------------------------- trace2summary
def test_trace2summary_folds_trace(fresh_registry, tmp_path, rng, capsys):
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.trace2summary import format_table, load_events, main, summarize
    x, y = _toy(rng, n=32)
    net = _tiny_net()
    net.fit(iterator=_it(x, y), epochs=1, steps_per_dispatch=4,
            async_prefetch=False)
    path = fresh_registry.write_chrome_trace(str(tmp_path / "t.json"))
    rows = summarize(load_events(path))
    phases = {r["phase"]: r for r in rows}
    assert phases["fit/epoch/window"]["count"] == 1
    # share = phase total / trace wall window. A backend_compile event's
    # REPORTED duration can exceed its wall footprint (XLA compiles on
    # multiple threads), stretching the window past the fit span — so pin
    # the invariant, not an exact 1.0: fit dominates and never exceeds it.
    assert 0.3 < phases["fit"]["share"] <= 1.0
    # compile events fold into their own [backend_compile] bucket
    assert any("[backend_compile]" in p for p in phases)
    assert "fit/epoch/window" in format_table(rows)
    assert main([path, "--top", "3"]) == 0
    assert "phase" in capsys.readouterr().out
    # bare JSONL (no array brackets) loads too
    jsonl = tmp_path / "t.jsonl"
    jsonl.write_text("\n".join(json.dumps(e)
                               for e in fresh_registry.trace_events()))
    assert len(load_events(str(jsonl))) == len(fresh_registry.trace_events())


# ------------------------------------------------------- ProfilerListener
def test_profiler_listener_tolerates_active_trace(fresh_registry, tmp_path):
    """Regression (ISSUE 4 satellite): start_trace raising (another trace
    already active — jax allows one per process) must not propagate out of
    iteration_done or leave the listener half-armed."""
    from deeplearning4j_tpu.util.checkpointing import ProfilerListener
    jax.profiler.start_trace(str(tmp_path / "outer"))
    try:
        lst = ProfilerListener(str(tmp_path / "inner"), start_iteration=0,
                               n_iterations=2)
        lst.iteration_done(None, 0, 0.0)        # start_trace raises inside
        assert lst._done and not lst._active    # retired cleanly
        lst.iteration_done(None, 1, 0.0)        # inert afterwards
        lst.on_epoch_end(None)                  # must NOT stop the outer trace
    finally:
        jax.profiler.stop_trace()


def test_profiler_listener_capture_emits_span(fresh_registry, tmp_path):
    from deeplearning4j_tpu.util.checkpointing import ProfilerListener
    lst = ProfilerListener(str(tmp_path / "prof"), start_iteration=1,
                           n_iterations=2)
    for it in range(5):
        lst.iteration_done(None, it, 0.0)
    assert lst._done and not lst._active
    spans_ = [e for e in fresh_registry.trace_events()
              if e["name"] == "profiler_capture"]
    assert len(spans_) == 1
    assert spans_[0]["args"]["start_iteration"] == 1


def test_device_memory_gauges_smoke(fresh_registry):
    from deeplearning4j_tpu.telemetry import device_memory_gauges
    out = device_memory_gauges(fresh_registry)
    # CPU backend exposes no memory_stats; on real devices gauges appear
    for name, val in out.items():
        assert val >= 0
        assert fresh_registry.gauge(name).value == val


# ------------------------------------------------------------- bench guard
@pytest.mark.bench_smoke
def test_telemetry_overhead_bench_smoke():
    """Tier-1 guard for the telemetry_overhead bench row: the enabled
    registry must cost <5% on the dispatch-bound loop. Host wall-clock on
    a shared CI box swings a few percent either way (the row itself uses
    interleaved medians), so the guard retries: it fails only if three
    consecutive measurements all exceed the bound."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    last = None
    for _ in range(3):
        # base variant only: the traced fit + serving variants have their
        # own guard (tests/test_tracing.py) — no double payment here
        row = bench.bench_telemetry_overhead(steps=128, repeats=5,
                                             variants=("base",))
        assert row["instrumented_steps_per_sec"] > 0
        assert row["bare_steps_per_sec"] > 0
        last = row
        # guard on the paired-ratio FLOOR: the median pct (still the
        # reported row) absorbs co-tenant load bursts asymmetrically on
        # this rig and can flake >=5% for minutes at a stretch, while a
        # real regression lifts every adjacent on/off pair
        if row["telemetry_overhead_floor_pct"] < 5.0:
            return
    pytest.fail(f"telemetry overhead >=5% in 3 consecutive runs: {last}")
