"""Incident flight recorder (ISSUE 13 tentpole): dump-on-trigger black
boxes from the telemetry trace ring.

Acceptance pinned here: an injected KillWorker fault and an injected
NaN-loss batch each produce a flight-recorder dump containing the
preceding spans/events; unhandled scheduler/batcher exceptions, elastic
preemption and POST /debug/flightrec leave dumps too; dumps are atomic,
rate-limited for repeat-fire triggers, pruned to keep_last, and readable
by the trace tools.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.telemetry import (FlightRecorder, MetricsRegistry,
                                          set_flight_recorder, span)


@pytest.fixture
def fresh_registry():
    reg = MetricsRegistry(enabled=True)
    prev = telemetry.set_registry(reg)
    try:
        yield reg
    finally:
        telemetry.set_registry(prev)


@pytest.fixture
def recorder(fresh_registry, tmp_path):
    rec = FlightRecorder(directory=str(tmp_path / "fr"), capacity=64,
                         min_interval_s=10.0, keep_last=3)
    prev = set_flight_recorder(rec)
    try:
        yield rec
    finally:
        set_flight_recorder(prev)


def _tiny_net(seed=12):
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd
    conf = (NeuralNetConfiguration(seed=seed, updater=Sgd(0.1))
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


# ------------------------------------------------------------------- core
def test_dump_captures_ring_tail_metrics_and_deltas(recorder,
                                                    fresh_registry):
    reg = fresh_registry
    reg.counter("work.items").inc(5)
    with span("phase1"):
        pass
    p1 = recorder.dump("manual_one", context="first")
    assert p1 and os.path.exists(p1)
    rec1 = json.load(open(p1))
    assert rec1["trigger"] == "manual_one"
    assert rec1["info"]["context"] == "first"
    assert [e["name"] for e in rec1["events"]] == ["phase1"]
    assert rec1["metrics"]["counters"]["work.items"] == 5
    assert rec1["counter_deltas_since_last_dump"]["work.items"] == 5
    reg.counter("work.items").inc(2)
    p2 = recorder.dump("manual_two")
    rec2 = json.load(open(p2))
    assert rec2["counter_deltas_since_last_dump"]["work.items"] == 2
    assert rec2["seq"] == rec1["seq"] + 1
    # no torn tmp files left behind
    assert not [f for f in os.listdir(recorder.directory)
                if f.endswith(".tmp")]


def test_dump_capacity_bounds_events(recorder, fresh_registry):
    for i in range(200):
        fresh_registry.record_event({"name": f"e{i}", "ph": "i", "ts": i,
                                     "pid": 1, "tid": 1, "args": {}})
    p = recorder.dump("bounded")
    rec = json.load(open(p))
    assert len(rec["events"]) == 64               # capacity, most recent
    assert rec["events"][-1]["name"] == "e199"


def test_rate_limit_and_force(recorder):
    assert recorder.dump("auto", force=False) is not None
    assert recorder.dump("auto", force=False) is None     # suppressed
    assert recorder.suppressed == 1
    assert recorder.dump("explicit", force=True) is not None


def test_keep_last_prunes_old_dumps(recorder):
    paths = [recorder.dump(f"t{i}") for i in range(5)]
    assert all(paths)
    kept = sorted(os.listdir(recorder.directory))
    assert len(kept) == 3                          # keep_last
    assert os.path.basename(paths[-1]) in kept
    assert os.path.basename(paths[0]) not in kept


def test_disabled_registry_no_dump(recorder, fresh_registry):
    fresh_registry.enabled = False
    assert recorder.dump("nope") is None
    fresh_registry.enabled = True


def test_note_breadcrumbs_land_in_dump(recorder, fresh_registry):
    recorder.note("drain_started", queued=7)
    p = recorder.dump("with_note")
    rec = json.load(open(p))
    notes = [e for e in rec["events"] if e.get("cat") == "note"]
    assert notes and notes[0]["name"] == "drain_started"
    assert notes[0]["args"]["queued"] == 7


def test_dump_readable_by_trace_tools(recorder, fresh_registry):
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.trace2summary import load_events, summarize
    with span("incident_phase"):
        pass
    p = recorder.dump("tool_check")
    events = load_events(p)
    assert [e["name"] for e in events] == ["incident_phase"]
    assert summarize(events)[0]["phase"] == "incident_phase"


def test_dump_never_raises_into_failing_path(recorder, monkeypatch):
    monkeypatch.setattr(recorder, "directory", "/dev/null/cannot/exist")
    assert recorder.dump("doomed") is None         # logged, not raised


# -------------------------------------------------- trigger: NaN-loss batch
def test_nan_loss_batch_leaves_black_box(recorder, fresh_registry, rng):
    """Acceptance: an injected NaN-loss batch produces a dump containing
    the preceding spans/events."""
    from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
    from deeplearning4j_tpu.telemetry import (TrainingWatch,
                                              set_training_watch)
    net = _tiny_net()
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=32)]
    x[18] = np.nan                                 # the poisoned batch
    watch = TrainingWatch(window=4, flight_recorder=recorder,
                          registry=fresh_registry)
    prev = set_training_watch(watch)
    try:
        net.fit(iterator=ListDataSetIterator(features=x, labels=y,
                                             batch_size=4),
                epochs=1, async_prefetch=False)
        assert watch.drain()
    finally:
        set_training_watch(prev)
    assert not watch.healthy
    assert watch.unhealthy[0]["reason"] == "nonfinite"
    dump = json.load(open(recorder.last_dump_path))
    assert dump["trigger"] == "training_nonfinite"
    assert dump["info"]["iteration"] == watch.unhealthy[0]["iteration"]
    # the black box holds the spans that led up to the blow-up: the
    # already-CLOSED step spans (whether fit/epoch appear depends on
    # whether the watch worker ran mid-fit or after — they close last)
    span_names = {e["name"] for e in dump["events"]
                  if e.get("cat") == "span"}
    assert "step" in span_names


# ----------------------------- triggers: KillWorker fault + preemption
def test_killworker_and_preemption_leave_black_boxes(recorder,
                                                     fresh_registry,
                                                     tmp_path, rng):
    """Acceptance: an injected KillWorker produces a dump with the
    preceding spans/events; recovery and the (later-injected) preemption
    each leave their own — one elastic run exercises all three
    triggers."""
    from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
    from deeplearning4j_tpu.parallel.elastic import ElasticTrainer
    from deeplearning4j_tpu.parallel.faults import (FaultInjector, FaultPlan,
                                                    KillWorker, PreemptAt)
    net = _tiny_net(seed=5)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=64)]
    it = ListDataSetIterator(features=x, labels=y, batch_size=8)
    trainer = ElasticTrainer(
        net, checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every_n_steps=4,
        fault_injector=FaultInjector(FaultPlan(
            KillWorker(step=5, worker=2, rejoin=True),
            PreemptAt(step=8))))
    trainer.fit(it, num_steps=20)
    assert trainer.recoveries == 1
    assert trainer.preempted
    triggers = [json.load(open(p))["trigger"] for p in recorder.dumps]
    assert "fault_killworker" in triggers
    assert "elastic_recovery" in triggers
    assert "preemption" in triggers
    kill = json.load(open(recorder.dumps[triggers.index(
        "fault_killworker")]))
    assert kill["info"]["step"] == 5
    # preceding spans: the steps already CLOSED before the kill (the
    # enclosing fit/elastic.fit spans are still open mid-run — a black
    # box holds what finished happening, which for a step loop is steps)
    spans_ = [e for e in kill["events"] if e.get("cat") == "span"]
    assert any(e["name"] == "step" for e in spans_)
    # every STEP span carries the elastic run's single trace id. Only the
    # step spans: the background checkpoint writer's checkpoint_write
    # span has no request context (worker thread, no handoff) and races
    # the step-5 dump — under a slow fit (cold compile, co-tenant load)
    # it lands inside the ring window, under a fast one it closes after;
    # asserting over ALL spans made the pin depend on that timing
    ids = {e["args"].get("trace_id") for e in spans_
           if e["name"] == "step"}
    assert len(ids) == 1 and None not in ids


# ------------------------------------- trigger: generation scheduler error
def test_generation_dispatch_failure_leaves_black_box(recorder,
                                                      fresh_registry):
    from deeplearning4j_tpu.models.zoo_extra import transformer_lm
    from deeplearning4j_tpu.serving import GenerationEngine
    net = transformer_lm(vocab_size=23, d_model=16, n_heads=2, n_blocks=1,
                         max_length=32, seed=9, dtype="float32",
                         token_input=True).init()
    eng = GenerationEngine(net, model_name="lm", block_len=8,
                           max_seq_len=32, decode_slots=2,
                           prefill_batches=(1,), prompt_rungs=(32,))
    try:
        rt = eng._get("lm")

        def exploding(*a, **k):
            raise RuntimeError("device meltdown")

        rt.active_ps.run_prefill = exploding
        ts = eng.generate([1, 2, 3], max_tokens=4, stream=True)
        tokens, reason = ts.result(raise_on_error=False)
        assert reason == "error"
    finally:
        eng.stop(drain=False, timeout=2.0)
    deadline = time.monotonic() + 5.0
    while recorder.last_dump_path is None and time.monotonic() < deadline:
        time.sleep(0.01)
    dump = json.load(open(recorder.last_dump_path))
    assert dump["trigger"] == "generation_error"
    assert dump["info"]["error_type"] == "RuntimeError"
    assert dump["info"]["model"] == "lm"


# -------------------------------------------- trigger: batcher model error
def test_serving_dispatch_failure_leaves_black_box(recorder,
                                                   fresh_registry):
    from deeplearning4j_tpu.serving.batcher import ShapeBucketedBatcher
    from deeplearning4j_tpu.serving.buckets import BucketLadder

    def bad_runner(padded):
        raise RuntimeError("XLA imploded")

    b = ShapeBucketedBatcher(bad_runner, BucketLadder((4,)), (4,),
                             batch_window_ms=0.5, name="doomed")
    try:
        with pytest.raises(RuntimeError, match="XLA imploded"):
            b.submit(np.zeros((2, 4), np.float32), timeout=5.0)
    finally:
        b.stop(drain=False)
    dump = json.load(open(recorder.last_dump_path))
    assert dump["trigger"] == "serving_dispatch_error"
    assert dump["info"]["model"] == "doomed"


# --------------------------------------------- trigger: POST /debug/flightrec
def test_http_debug_flightrec_route(recorder, fresh_registry):
    import urllib.request
    from deeplearning4j_tpu.serving import InferenceEngine, ServingHTTPServer
    net = _tiny_net(seed=8)
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(4,),
                          batch_window_ms=0.5)
    srv = ServingHTTPServer(engine=eng)
    base = f"http://127.0.0.1:{srv.start()}"
    try:
        req = urllib.request.Request(
            base + "/debug/flightrec",
            json.dumps({"operator": "why-slow"}).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            body = json.loads(r.read())
        assert os.path.exists(body["dumped"])
        rec = json.load(open(body["dumped"]))
        assert rec["trigger"] == "http_debug"
        assert rec["info"]["operator"] == "why-slow"
        # body keys colliding with dump()'s own parameters are prefixed,
        # not bound (a {"trigger": ...} body used to TypeError mid-handler
        # and {"force": false} silently rate-limited the explicit dump)
        req = urllib.request.Request(
            base + "/debug/flightrec",
            json.dumps({"trigger": "spoof", "force": False}).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            body = json.loads(r.read())
        rec = json.load(open(body["dumped"]))
        assert rec["trigger"] == "http_debug"
        assert rec["info"]["body_trigger"] == "spoof"
        assert rec["info"]["body_force"] is False
    finally:
        srv.stop()
