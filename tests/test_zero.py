"""ZeRO-style sharded weight update (parallel/zero.py): group/bucket
layout, replicated-update parity (per-step, fused windows, remainder
batches, stage 1 vs 2, heterogeneous lr groups), sharded-state
checkpointing with manifest layout metadata + re-shard restore onto a
different mesh size, elastic kill->resume with sharded updater state, the
zero.* telemetry, and the zero_sharded_update bench row smoke."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd
from deeplearning4j_tpu.parallel import (ElasticTrainer, FaultInjector,
                                         FaultPlan, KillWorker,
                                         ParallelWrapper, ZeroUpdateEngine,
                                         is_zero_state, make_zero_resharder)
from deeplearning4j_tpu.parallel.faults import truncate_newest_sharded
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.util.distributed_checkpoint import (
    read_manifest, restore_latest_sharded_checkpoint,
    restore_sharded_checkpoint, save_sharded_checkpoint)

R = np.random.default_rng(47)


def _net(seed=7, updater=None, bias_lr=None):
    layers = [DenseLayer(n_in=6, n_out=24, activation="tanh"),
              DenseLayer(n_in=24, n_out=16, activation="tanh",
                         **({"bias_learning_rate": bias_lr}
                            if bias_lr else {})),
              OutputLayer(n_out=3, activation="softmax", loss="mcxent")]
    conf = (NeuralNetConfiguration(seed=seed, updater=updater or Adam(5e-3),
                                   dtype="float32")
            .list(*layers).build())
    return MultiLayerNetwork(conf).init()


def _data(n=128):
    x = R.normal(size=(n, 6)).astype(np.float32)
    yi = (x.sum(-1) > 0).astype(int) + (x[:, 0] > 1).astype(int)
    return x, np.eye(3, dtype=np.float32)[yi]


def _flat(net):
    return np.asarray(net.params_flat())


# ------------------------------------------------------------------ layout
def test_groups_partition_every_unfrozen_leaf_once():
    net = _net()
    eng = ZeroUpdateEngine.from_net(net, make_mesh(), stage=2,
                                    bucket_bytes=256)
    seen = sorted(i for g in eng.groups for b in g.buckets
                  for i in b.indices)
    assert seen == list(range(len(jax.tree.leaves(net.params))))
    for g in eng.groups:
        for b in g.buckets:
            assert b.lb == -(-b.nb // eng.n)        # ceil padding
        assert g.length == sum(b.lb for b in g.buckets)


def test_layout_splits_heterogeneous_lr_into_groups():
    """A bias_learning_rate override changes that leaf's lr multiplier —
    it must land in its OWN group (each group's flat update runs with a
    single traced-scalar lr, the bit-identity precondition)."""
    uniform = ZeroUpdateEngine.from_net(_net(), make_mesh(), stage=1)
    assert len(uniform.groups) == 1
    split = ZeroUpdateEngine.from_net(_net(bias_lr=0.5), make_mesh(),
                                      stage=1)
    assert len(split.groups) == 2
    mults = sorted(g.lr_mult for g in split.groups)
    assert mults[0] == 1.0 and mults[1] != 1.0


def test_engine_rejects_grad_norm_and_bad_stage():
    conf = (NeuralNetConfiguration(seed=1, updater=Sgd(0.1),
                                   gradient_normalization="clipl2perlayer")
            .list(DenseLayer(n_in=4, n_out=4, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    with pytest.raises(ValueError, match="normalization"):
        ZeroUpdateEngine.from_net(net, make_mesh())
    with pytest.raises(ValueError, match="stage"):
        ZeroUpdateEngine.from_net(_net(), make_mesh(), stage=3)


def test_wrapper_rejects_bad_combinations():
    from deeplearning4j_tpu.parallel.accumulation import PsumAccumulator
    with pytest.raises(ValueError, match="zero_stage"):
        ParallelWrapper(_net(), zero_stage=1,
                        gradient_accumulator=PsumAccumulator())
    with pytest.raises(ValueError, match="averaging"):
        ParallelWrapper(_net(), zero_stage=1, training_mode="averaging",
                        averaging_frequency=4)
    with pytest.raises(ValueError, match="zero_stage"):
        ParallelWrapper(_net(), zero_stage=7)
    # averaging_frequency=1 IS the sync path: allowed
    ParallelWrapper(_net(), zero_stage=2, training_mode="averaging",
                    averaging_frequency=1)


def test_elastic_rejects_zero_plus_degraded_mode():
    with pytest.raises(ValueError, match="degraded"):
        ElasticTrainer(_net(), zero_stage=1, sync_latency_budget_ms=5.0)


def test_wrapper_rejects_overlap_sync_plus_zero():
    """Regression: overlap_sync=True with zero_stage was silently
    ignored (zero takes the dispatch) — it must refuse like the other
    non-composing flag pairs do."""
    with pytest.raises(ValueError, match="overlap_sync"):
        ParallelWrapper(_net(), zero_stage=2, overlap_sync=True)


def test_zero_handles_parameterless_layers():
    """Regression: a net containing a layer with NO params (activation/
    dropout/pooling — an empty param dict) crashed the opt-state
    alignment (the empty dict was mistaken for a stateless leaf). The
    sharded update must match the replicated one on such nets."""
    from deeplearning4j_tpu.nn.layers import ActivationLayer
    x, y = _data()

    def mk():
        conf = (NeuralNetConfiguration(seed=9, updater=Adam(5e-3),
                                       dtype="float32")
                .list(DenseLayer(n_in=6, n_out=16, activation="identity"),
                      ActivationLayer(activation="tanh"),
                      OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    ref = mk()
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    ParallelWrapper(ref).fit(it, epochs=2)
    it.reset()
    net = mk()
    pw = ParallelWrapper(net, zero_stage=2)
    pw.fit(it, epochs=2)
    np.testing.assert_array_equal(_flat(ref), _flat(net))
    # round-trips through the replicated format too
    pw.gather_opt_state()
    ref_state = net.updater.init(net.params)
    assert jax.tree.structure(net.opt_state) == \
        jax.tree.structure(ref_state)


def test_zero_frozen_layer_state_round_trips():
    """Regression: a frozen layer's leaves are excluded from the sharded
    update, but its (init, never-updated) state must come back from
    gather_opt_state() in the updater.init shape so model zips keep
    loading — and NONZERO frozen state is refused loudly instead of
    being silently zeroed."""
    from deeplearning4j_tpu.util.serialization import (
        restore_multilayer_network, write_model)
    x, y = _data()

    def mk():
        conf = (NeuralNetConfiguration(seed=9, updater=Adam(5e-3),
                                       dtype="float32")
                .list(DenseLayer(n_in=6, n_out=16, activation="tanh",
                                 frozen=True),
                      OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    ref = mk()
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    ParallelWrapper(ref).fit(it, epochs=2)
    it.reset()
    net = mk()
    pw = ParallelWrapper(net, zero_stage=2)
    pw.fit(it, epochs=2)
    np.testing.assert_array_equal(_flat(ref), _flat(net))
    pw.gather_opt_state()
    assert jax.tree.structure(net.opt_state) == \
        jax.tree.structure(net.updater.init(net.params))
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "m.zip")
        write_model(net, path)
        back = restore_multilayer_network(path)
        np.testing.assert_allclose(_flat(back), _flat(net), atol=1e-7)
    # nonzero frozen state cannot enter the sharded format silently
    poisoned = mk()
    poisoned.opt_state = jax.tree.map(lambda a: a + 1.0,
                                      poisoned.opt_state)
    eng = ZeroUpdateEngine.from_net(poisoned, make_mesh(), stage=2)
    with pytest.raises(ValueError, match="frozen"):
        eng.shard_opt_state(poisoned.opt_state)


def test_state_shard_roundtrip_and_bytes():
    """shard -> unshard -> shard must be bitwise lossless (pure
    redistribution), and the per-replica state allocation must shrink
    ~mesh-size-x (padding costs a few %)."""
    net = _net()
    eng = ZeroUpdateEngine.from_net(net, make_mesh(), stage=2,
                                    bucket_bytes=512)
    sharded = eng.shard_opt_state(net.opt_state)
    assert is_zero_state(sharded)
    rep = eng.unshard_opt_state(sharded)
    back = eng.shard_opt_state(rep)
    for a, b in zip(jax.tree.leaves(sharded), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ratio = eng.replicated_state_bytes / eng.shard_state_bytes
    assert ratio >= 0.75 * eng.n, ratio
    # a state sharded for a different mesh size must be refused loudly
    eng2 = ZeroUpdateEngine.from_net(net, make_mesh((4,), ("data",),
                                                    jax.devices()[:4]),
                                     stage=2, bucket_bytes=512)
    with pytest.raises(ValueError, match="re-shard"):
        eng2.check_state(sharded)


# ------------------------------------------------------------------ parity
def test_zero_parity_default_bucket_bit_identical():
    """THE acceptance pin: stage 1 and stage 2 at the default bucket
    size match the replicated (overlap) update bit-for-bit after N
    steps on the 8-device mesh, Adam state and all."""
    x, y = _data()
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    ref = _net()
    ParallelWrapper(ref, overlap_sync=True).fit(it, epochs=2)
    for stage in (1, 2):
        it.reset()
        net = _net()
        ParallelWrapper(net, zero_stage=stage).fit(it, epochs=2)
        np.testing.assert_array_equal(_flat(ref), _flat(net))


@pytest.mark.slow
def test_zero_stage1_equals_stage2_every_bucket_size():
    """Stages differ ONLY in the collective op (all-reduce+slice vs
    psum_scatter) over one shared packing graph — bitwise equal at every
    bucket size, and within float tolerance of the replicated path (the
    flat Adam chain may fuse with different rounding than the per-leaf
    chain at some packings — <= 1 ulp/step, same caveat as the scan
    window's)."""
    x, y = _data()
    ref = _net()
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    ParallelWrapper(ref).fit(it, epochs=2)
    for bb in (256, 1 << 30):
        flats = []
        for stage in (1, 2):
            it.reset()
            net = _net()
            ParallelWrapper(net, zero_stage=stage, bucket_bytes=bb).fit(
                it, epochs=2)
            flats.append(_flat(net))
        np.testing.assert_array_equal(flats[0], flats[1])
        np.testing.assert_allclose(flats[0], _flat(ref), atol=1e-6)


@pytest.mark.slow
def test_zero_sgd_bit_identical_every_bucket_size():
    """With a stateless elementwise rule the flat update has no fusable
    multi-op chain: SGD pins bitwise against the replicated path at
    every bucket size, multi-bucket groups included."""
    x, y = _data()
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    ref = _net(updater=Sgd(0.1))
    ParallelWrapper(ref).fit(it, epochs=2)
    for bb in (256, 1 << 30):
        it.reset()
        net = _net(updater=Sgd(0.1))
        ParallelWrapper(net, zero_stage=2, bucket_bytes=bb).fit(it, epochs=2)
        np.testing.assert_array_equal(_flat(ref), _flat(net))


def test_zero_window_bit_identical_to_per_step():
    """K fused zero steps (steps_per_dispatch) == K per-step zero
    dispatches, bitwise — the grad_sync/update_fn seams ride
    train_step_math into the scan body structurally."""
    x, y = _data(128)
    a, b = _net(), _net()
    b.set_params_flat(a.params_flat())
    it = ListDataSetIterator(features=x, labels=y, batch_size=32)
    ParallelWrapper(a, zero_stage=2).fit(it, epochs=2)
    it.reset()
    ParallelWrapper(b, zero_stage=2, steps_per_dispatch=2).fit(it, epochs=2)
    np.testing.assert_array_equal(_flat(a), _flat(b))


def test_zero_remainder_batch_dispatches_replicated_feed():
    """A batch that does not tile the mesh takes the replicated-feed
    zero program — sharded update and collectives intact — and tracks
    the single-net fit."""
    x, y = _data(100)            # batch 64 -> remainder 36 (36 % 8 != 0)
    single = _net()
    single.fit(iterator=ListDataSetIterator(features=x, labels=y,
                                            batch_size=64),
               epochs=2, async_prefetch=False)
    # stage 2 only: the remainder path differs from stage 1 solely in
    # the grad collective, and stage1==stage2 is pinned separately
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    net = _net()
    pw = ParallelWrapper(net, zero_stage=2)
    pw.fit(it, epochs=2)
    assert pw._remainder_step is not None         # the remainder took it
    np.testing.assert_allclose(_flat(net), _flat(single),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.slow
def test_zero_bias_lr_override_parity():
    """Heterogeneous lr multipliers (bias_learning_rate) split the
    layout into groups; the multi-group sharded update must still match
    the replicated path at the default bucket size."""
    x, y = _data()
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    ref = _net(bias_lr=0.5)
    ParallelWrapper(ref).fit(it, epochs=2)
    it.reset()
    net = _net(bias_lr=0.5)
    pw = ParallelWrapper(net, zero_stage=2)
    pw.fit(it, epochs=2)
    assert len(pw._zero().groups) == 2
    np.testing.assert_allclose(_flat(ref), _flat(net), atol=1e-6)


@pytest.mark.slow
def test_zero_converges():
    x, y = _data(256)
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    net = _net()
    s0 = net.score(x, y)
    ParallelWrapper(net, zero_stage=2).fit(it, epochs=12)
    assert net.score(x, y) < s0
    assert net.evaluate(x, y).accuracy() > 0.8


def test_gather_opt_state_restores_replicated_format():
    x, y = _data()
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    net = _net()
    pw = ParallelWrapper(net, zero_stage=2)
    pw.fit(it, epochs=1)
    assert is_zero_state(net.opt_state)
    pw.gather_opt_state()
    assert not is_zero_state(net.opt_state)
    # structure matches a fresh updater.init
    ref = net.updater.init(net.params)
    assert jax.tree.structure(net.opt_state) == jax.tree.structure(ref)


def test_write_model_refuses_sharded_state(tmp_path):
    from deeplearning4j_tpu.util.serialization import write_model
    x, y = _data()
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    net = _net()
    pw = ParallelWrapper(net, zero_stage=1)
    pw.fit(it, epochs=1)
    with pytest.raises(ValueError, match="gather_opt_state"):
        write_model(net, str(tmp_path / "m.zip"))
    pw.gather_opt_state()
    write_model(net, str(tmp_path / "m.zip"))     # now fine


# ------------------------------------------------------------- checkpoints
def _ckpt_tree(net, eng):
    return {"params": net.params, "state": net.state,
            "opt": eng.shard_opt_state(net.opt_state)
            if not is_zero_state(net.opt_state) else net.opt_state}


def test_manifest_sharding_block_and_same_mesh_restore(tmp_path):
    x, y = _data()
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    net = _net()
    pw = ParallelWrapper(net, zero_stage=2)
    pw.fit(it, epochs=1)
    eng = pw._zero()
    save_sharded_checkpoint(str(tmp_path), 3, _ckpt_tree(net, eng),
                            extra={"step_in_epoch": 1},
                            sharding=eng.sharding_meta())
    man = read_manifest(str(tmp_path), 3)
    assert man["sharding"]["format"] == "zero-flat"
    assert man["sharding"]["num_shards"] == 8
    assert man["sharding"]["groups"][0]["bucket_elems"]
    # same mesh: direct restore, bitwise
    like = {"params": net.params, "state": net.state,
            "opt": eng.init_opt_state()}
    got = restore_sharded_checkpoint(str(tmp_path), 3, like)
    for a, b in zip(jax.tree.leaves(got["opt"]),
                    jax.tree.leaves(net.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_reshard_restore_onto_smaller_mesh(tmp_path):
    """State saved on the 8-shard layout restores onto a 4-device mesh
    via the resharder (all-gather -> re-slice): unsharding both must
    give the SAME per-leaf state values (redistribution, not math)."""
    x, y = _data()
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    net = _net()
    pw = ParallelWrapper(net, zero_stage=2)
    pw.fit(it, epochs=1)
    eng8 = pw._zero()
    save_sharded_checkpoint(str(tmp_path), 5, _ckpt_tree(net, eng8),
                            sharding=eng8.sharding_meta())
    mesh4 = make_mesh((4,), ("data",), jax.devices()[:4])
    eng4 = ZeroUpdateEngine.from_net(net, mesh4, stage=2)
    rep = NamedSharding(mesh4, P())
    like = {"params": jax.tree.map(
                lambda a: jax.device_put(jnp.asarray(a), rep), net.params),
            "state": jax.tree.map(
                lambda a: jax.device_put(jnp.asarray(a), rep), net.state),
            "opt": eng4.init_opt_state()}
    step, got, _ = restore_latest_sharded_checkpoint(
        str(tmp_path), like, resharder=make_zero_resharder(eng4))
    assert step == 5
    eng4.check_state(got["opt"])          # shaped for the 4-shard layout
    rep8 = eng8.unshard_opt_state(net.opt_state)
    rep4 = eng4.unshard_opt_state(got["opt"])
    for a, b in zip(jax.tree.leaves(rep8), jax.tree.leaves(rep4)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # params rode along untouched
    for a, b in zip(jax.tree.leaves(net.params),
                    jax.tree.leaves(got["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_reshard_restore_falls_back_past_truncated_newest(tmp_path):
    """Regression (satellite): the re-shard path must compose with the
    damaged-save fallback — a truncated newest checkpoint is skipped and
    the older valid save re-shards instead of the restore aborting."""
    x, y = _data()
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    net = _net()
    pw = ParallelWrapper(net, zero_stage=2)
    pw.fit(it, epochs=1)
    eng8 = pw._zero()
    save_sharded_checkpoint(str(tmp_path), 5, _ckpt_tree(net, eng8),
                            sharding=eng8.sharding_meta())
    pw.fit(it, epochs=1)
    save_sharded_checkpoint(str(tmp_path), 9, _ckpt_tree(net, eng8),
                            sharding=eng8.sharding_meta())
    truncate_newest_sharded(str(tmp_path))
    mesh4 = make_mesh((4,), ("data",), jax.devices()[:4])
    eng4 = ZeroUpdateEngine.from_net(net, mesh4, stage=2)
    rep = NamedSharding(mesh4, P())
    like = {"params": jax.tree.map(
                lambda a: jax.device_put(jnp.asarray(a), rep), net.params),
            "state": jax.tree.map(
                lambda a: jax.device_put(jnp.asarray(a), rep), net.state),
            "opt": eng4.init_opt_state()}
    step, got, _ = restore_latest_sharded_checkpoint(
        str(tmp_path), like, resharder=make_zero_resharder(eng4))
    assert step == 5                      # walked past the truncated 9
    eng4.check_state(got["opt"])


# ----------------------------------------------------------------- elastic
_EX = R.normal(size=(64, 6)).astype(np.float32)
_EY = np.eye(3, dtype=np.float32)[R.integers(0, 3, 64)]


def _eit(bs=8):
    return ListDataSetIterator(features=_EX, labels=_EY, batch_size=bs)


def _enet(seed=7):
    conf = (NeuralNetConfiguration(seed=seed, updater=Adam(1e-2),
                                   dtype="float32")
            .list(DenseLayer(n_in=6, n_out=16, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _devs(n=4):
    return jax.devices()[:n]


_ZB_FLAT = {}


def _zero_baseline_flat(num_steps=16):
    """Unfaulted elastic-zero reference params, computed once per process
    (fixed seeds + module-level data: identical in any test order)."""
    if num_steps not in _ZB_FLAT:
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            a = _enet()
            ElasticTrainer(a, checkpoint_dir=os.path.join(td, "zbase"),
                           devices=_devs(), checkpoint_every_n_steps=4,
                           keep_last=4, zero_stage=2).fit(
                _eit(), num_steps=num_steps)
            _ZB_FLAT[num_steps] = _flat(a)
    return _ZB_FLAT[num_steps]


def test_elastic_zero_matches_plain_zero_wrapper(tmp_path):
    """Supervision + async sharded-state checkpointing must add NOTHING
    to the zero math: an unfaulted elastic zero run is bit-identical to
    a plain ParallelWrapper(zero_stage) fit over the same steps."""
    a = _enet()
    ParallelWrapper(a, mesh=make_mesh((4,), ("data",), _devs()),
                    zero_stage=2, prefetch_buffer=0).fit(_eit(), epochs=2)
    b = _enet()
    tr = ElasticTrainer(b, checkpoint_dir=str(tmp_path), devices=_devs(),
                        checkpoint_every_n_steps=4, zero_stage=2)
    tr.fit(_eit(), num_steps=16)
    assert tr.steps_done == 16 and tr.recoveries == 0
    np.testing.assert_array_equal(_flat(a), _flat(b))
    # the on-disk manifests carry the shard-layout block
    from deeplearning4j_tpu.util.distributed_checkpoint import \
        latest_sharded_step
    st = latest_sharded_step(str(tmp_path))
    assert read_manifest(str(tmp_path), st)["sharding"]["num_shards"] == 4


def test_elastic_zero_kill_rejoin_bit_identical(tmp_path):
    """Worker kill with rejoin -> same-shape mesh re-form: the sharded
    updater state restores from the async checkpoints and the run lands
    bit-identical to the unfaulted elastic zero run, resuming mid-grid
    through K=2 fused windows."""
    base = _zero_baseline_flat()
    # K=2 is the stronger pin (fused windows + recovery); the K=1 zero
    # elastic loop is covered by the no-fault and shrunk-mesh tests
    b = _enet()
    inj = FaultInjector(FaultPlan(KillWorker(step=13, worker=1,
                                             rejoin=True)))
    tr = ElasticTrainer(b, checkpoint_dir=str(tmp_path / "zf"),
                        devices=_devs(), checkpoint_every_n_steps=4,
                        keep_last=4, zero_stage=2,
                        steps_per_dispatch=2, fault_injector=inj)
    tr.fit(_eit(), num_steps=16)
    assert tr.recoveries == 1 and tr.steps_done == 16
    np.testing.assert_array_equal(base, _flat(b))


def test_elastic_zero_shrunk_mesh_reshards_state(tmp_path):
    """THE re-shard acceptance scenario: a permanently lost worker
    re-forms a 3-device mesh; the 4-shard updater state re-shards on
    restore (all-gather -> re-slice) instead of aborting, and the run
    converges to the baseline within float tolerance."""
    base = _zero_baseline_flat()
    b = _enet()
    inj = FaultInjector(FaultPlan(KillWorker(step=11, worker=2,
                                             rejoin=False)))
    tr = ElasticTrainer(b, checkpoint_dir=str(tmp_path / "shrink"),
                        devices=_devs(), checkpoint_every_n_steps=4,
                        zero_stage=2, fault_injector=inj)
    tr.fit(_eit(), num_steps=16)
    assert tr.recoveries == 1 and len(tr._devices) == 3
    assert tr.steps_done == 16
    np.testing.assert_allclose(base, _flat(b), rtol=1e-4, atol=1e-5)


# --------------------------------------------------------------- telemetry
def test_zero_gauges_and_collective_launch_accounting():
    reg = telemetry.get_registry()
    telemetry.reset()
    x, y = _data(128)
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    net = _net()
    pw = ParallelWrapper(net, zero_stage=2, bucket_bytes=512)
    pw.fit(it, epochs=1)                              # 2 steps
    eng = pw._zero()
    assert reg.gauge("zero.shard_bytes").value == eng.shard_state_bytes
    assert reg.gauge("zero.gathered_bytes").value == eng.gathered_bytes
    snap = reg.snapshot()
    # per step: reduce launches + group all-gathers + fused state/loss
    assert snap["counters"]["parallel.collective_launches"] == \
        2 * (eng.collectives_per_step + 1)


def test_zero_profile_emits_collective_trace_phases(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import trace2summary

    reg = telemetry.get_registry()
    telemetry.reset()
    net = _net()
    eng = ZeroUpdateEngine.from_net(net, make_mesh(), stage=2,
                                    bucket_bytes=512)
    with telemetry.span("fit"):
        out = eng.profile(make_mesh())
    assert out["reduce_scatter"] and out["all_gather"]
    assert reg.gauge("zero.shard_bytes").value == eng.shard_state_bytes
    trace = tmp_path / "trace.json"
    reg.write_chrome_trace(str(trace))
    rows = trace2summary.summarize(trace2summary.load_events(str(trace)))
    phases = {r["phase"] for r in rows}
    # the all-gather launches fold under the zero.allgather span; every
    # reduce-scatter bucket gets its own [reduce_scatter:g.b] phase
    assert "fit/zero.allgather" in phases, phases
    for r in out["reduce_scatter"]:
        assert f"fit/[reduce_scatter:{r['group']}.{r['bucket']}]" \
            in phases, phases
    for r in out["all_gather"]:
        assert f"fit/zero.allgather/[all_gather:{r['group']}]" in phases, \
            phases


# ------------------------------------------------------------- bench smoke
@pytest.mark.bench_smoke
def test_zero_sharded_update_bench_smoke():
    """Tier-1 guard: the zero_sharded_update row must run end to end,
    report the ~mesh-size-x per-replica state reduction, and the sharded
    update must not be catastrophically slower than the replicated one
    (shared-CI CPU timings swing, so three consecutive failing attempts
    are required to fail)."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    row = None
    for _ in range(3):
        row = bench.bench_zero_sharded_update(meshes=(4,),
                                              total_elems=80_000,
                                              bucket_bytes=128 * 1024,
                                              timeout=240, repeats=3)
        sub = row["4"]
        assert sub["state_bytes_zero"] < sub["state_bytes_replicated"]
        assert sub["state_reduction"] >= 0.75 * 4
        assert sub["replicated_update_ms"] > 0
        assert sub["zero1_update_ms"] > 0 and sub["zero2_update_ms"] > 0
        if sub["zero2_update_ms"] < 3 * sub["replicated_update_ms"]:
            return
    pytest.fail(f"sharded update catastrophically slow in 3 attempts: "
                f"{row}")
