"""NLP tests (mirror reference deeplearning4j-nlp tests: Word2Vec end-to-end
on a synthetic corpus with similarity assertions, serde round-trips,
tokenizers, vocab/Huffman)."""
import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (CommonPreprocessor,
                                    DefaultTokenizerFactory, Glove,
                                    NGramTokenizerFactory, ParagraphVectors,
                                    VocabCache, Word2Vec, read_word_vectors,
                                    read_binary_word_vectors,
                                    write_binary_word_vectors,
                                    write_word_vectors)


def _corpus(n=300, seed=0):
    """Synthetic corpus with clear topical structure: 'day/sun/light' vs
    'night/moon/dark' (stands in for the reference's raw_sentences.txt
    sim('day','night') assertions)."""
    r = np.random.default_rng(seed)
    day_words = ["day", "sun", "light", "morning", "bright"]
    night_words = ["night", "moon", "dark", "evening", "stars"]
    other = ["the", "a", "is", "was", "and"]
    out = []
    for _ in range(n):
        topic = day_words if r.random() < 0.5 else night_words
        sent = []
        for _ in range(r.integers(5, 12)):
            sent.append(topic[r.integers(len(topic))] if r.random() < 0.7
                        else other[r.integers(len(other))])
        out.append(" ".join(sent))
    return out


def test_tokenizers():
    tf = DefaultTokenizerFactory(CommonPreprocessor())
    toks = tf.create("Hello, World! 123 foo").get_tokens()
    assert toks == ["hello", "world", "foo"]
    ng = NGramTokenizerFactory(1, 2)
    toks = ng.create("a b c").get_tokens()
    assert "a b" in toks and "b c" in toks and "a" in toks


def test_vocab_and_huffman():
    vc = VocabCache.build([["a", "a", "a", "b", "b", "c"]])
    assert vc.index_of("a") == 0  # most frequent first
    assert vc.word_frequency("b") == 2
    vc.build_huffman()
    codes = {w: vc.word_for(w).code for w in ("a", "b", "c")}
    assert len(codes["a"]) <= len(codes["c"])  # frequent => shorter code
    # prefix-free
    for w1, c1 in codes.items():
        for w2, c2 in codes.items():
            if w1 != w2:
                assert c1 != c2[:len(c1)] or len(c1) > len(c2)


def test_word2vec_similarity_structure():
    w2v = Word2Vec(layer_size=32, window=4, min_word_frequency=2, epochs=10,
                   negative=5, learning_rate=0.05, seed=3)
    w2v.fit(_corpus())
    assert w2v.has_word("day") and w2v.has_word("night")
    same_topic = w2v.similarity("day", "sun")
    cross_topic = w2v.similarity("day", "moon")
    assert same_topic > cross_topic, (same_topic, cross_topic)
    nearest = w2v.words_nearest("sun", 4)
    assert any(w in ("day", "light", "morning", "bright") for w in nearest), nearest


def test_word_vector_serde_round_trip(tmp_path):
    w2v = Word2Vec(layer_size=16, min_word_frequency=1, epochs=2, seed=1)
    w2v.fit(["one two three", "one two", "three four one"])
    txt = str(tmp_path / "vecs.txt")
    write_word_vectors(w2v, txt)
    loaded = read_word_vectors(txt)
    assert np.allclose(loaded.get_word_vector("one"),
                       w2v.get_word_vector("one"), atol=1e-5)
    binp = str(tmp_path / "vecs.bin")
    write_binary_word_vectors(w2v, binp)
    loaded_b = read_binary_word_vectors(binp)
    assert np.allclose(loaded_b.get_word_vector("three"),
                       w2v.get_word_vector("three"), atol=1e-6)


def test_paragraph_vectors():
    docs = [("doc_day", " ".join(["sun day light bright"] * 5)),
            ("doc_night", " ".join(["moon night dark stars"] * 5))]
    pv = ParagraphVectors(layer_size=24, min_word_frequency=1, epochs=15,
                          negative=4, learning_rate=0.05, seed=2)
    pv.fit(docs)
    assert pv.get_doc_vector("doc_day") is not None
    v = pv.infer_vector("sun light day")
    assert v.shape == (24,)
    sim_day = pv.similarity_to_label("sun light bright day", "doc_day")
    sim_night = pv.similarity_to_label("sun light bright day", "doc_night")
    assert sim_day > sim_night, (sim_day, sim_night)


def test_glove_trains():
    g = Glove(layer_size=16, window=4, min_word_frequency=2, epochs=20,
              seed=5, batch_size=4096)
    g.fit([s.split() for s in _corpus(200)])
    assert g.similarity("day", "sun") > g.similarity("day", "moon")


def test_sgns_scatter_update_matches_dense_autodiff():
    """The analytic scatter-add SGNS step must equal SGD on jax.grad of the
    dense loss (VERDICT r1 weak #7: no dense [V,D] gradient materialized)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors

    R = np.random.default_rng(3)
    V, D, B, k = 50, 16, 64, 5
    syn0 = jnp.asarray(R.normal(size=(V, D)).astype(np.float32) * 0.1)
    syn1 = jnp.asarray(R.normal(size=(V, D)).astype(np.float32) * 0.1)
    centers = jnp.asarray(R.integers(0, V, B))
    contexts = jnp.asarray(R.integers(0, V, B))
    negs = jnp.asarray(R.integers(0, V, (B, k)))
    lr = 0.05

    def dense_loss(s0, s1):
        v = s0[centers]
        pos = jnp.sum(v * s1[contexts], -1)
        neg = jnp.einsum("bd,bkd->bk", v, s1[negs])
        return jnp.sum(jax.nn.softplus(-pos)) + jnp.sum(jax.nn.softplus(neg))

    g0, g1 = jax.grad(dense_loss, argnums=(0, 1))(syn0, syn1)
    want0, want1 = syn0 - lr * g0, syn1 - lr * g1

    sv = SequenceVectors(layer_size=D, negative=k)
    step = sv._build_step()
    got0, got1, _ = step(syn0, syn1, centers, contexts, negs, lr)
    np.testing.assert_allclose(np.asarray(got0), np.asarray(want0), atol=1e-6)
    np.testing.assert_allclose(np.asarray(got1), np.asarray(want1), atol=1e-6)


@pytest.mark.slow
def test_paragraph_vectors_pv_dm():
    """PV-DM mode (reference learning/impl/sequence/DM.java): doc vectors of
    same-topic docs end up closer than cross-topic, and infer_vector works.
    Slow lane (ISSUE 14 tier-1 budget reclaim): ~12s algorithm-mode variant
    — PV-DBOW (test_paragraph_vectors) and the hierarchical-softmax PV
    variant keep the tier-1 coverage of the PV training/inference path."""
    from deeplearning4j_tpu.nlp import ParagraphVectors

    cats = ["the cat sat on the mat and purred softly today",
            "a cat chased the small mouse around the mat",
            "my cat naps on a warm mat every afternoon"]
    cars = ["the car drove down the long road very fast",
            "a fast car raced along the road at night",
            "my car needs fuel before the long road trip"]
    docs = [(f"cat_{i}", t) for i, t in enumerate(cats)] + \
           [(f"car_{i}", t) for i, t in enumerate(cars)]
    pv = ParagraphVectors(layer_size=24, window=3, epochs=40, negative=4,
                          seed=11, dm=True, learning_rate=0.05)
    pv.fit(docs)
    assert pv.doc_vectors.shape == (6, 24)

    def sim(a, b):
        va, vb = pv.get_doc_vector(a), pv.get_doc_vector(b)
        return float(va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb)))

    same = np.mean([sim("cat_0", "cat_1"), sim("cat_0", "cat_2"),
                    sim("car_0", "car_1"), sim("car_0", "car_2")])
    cross = np.mean([sim("cat_0", "car_0"), sim("cat_1", "car_1"),
                     sim("cat_2", "car_2")])
    assert same > cross
    v = pv.infer_vector("the cat sat on a mat")
    assert v.shape == (24,) and np.isfinite(v).all()


def test_bag_of_words_and_tfidf_vectorizers():
    from deeplearning4j_tpu.nlp.vectorizers import (BagOfWordsVectorizer,
                                                    CollectionDocumentIterator,
                                                    FileDocumentIterator,
                                                    TfidfVectorizer)
    docs = ["apple banana apple", "banana cherry", "apple cherry cherry date"]
    bow = BagOfWordsVectorizer().fit(docs)
    assert bow.vocab == ["apple", "banana", "cherry", "date"]
    np.testing.assert_array_equal(bow.transform("apple apple banana"),
                                  [2, 1, 0, 0])
    m = bow.transform_documents(docs)
    assert m.shape == (3, 4)

    tfidf = TfidfVectorizer().fit(docs)
    v = tfidf.transform("apple date")
    # 'date' appears in 1 doc, 'apple' in 2 -> idf(date) > idf(apple)
    assert v[3] > v[0] > 0
    assert tfidf.tfidf_word("banana", "apple date") == 0.0

    ds = bow.vectorize("apple banana", "fruit", ["fruit", "other"])
    assert ds.features.shape == (1, 4) and ds.labels[0, 0] == 1.0

    it = CollectionDocumentIterator(docs)
    assert len(list(it)) == 3
    import tempfile, os
    with tempfile.TemporaryDirectory() as td:
        for i, d in enumerate(docs):
            with open(os.path.join(td, f"d{i}.txt"), "w") as f:
                f.write(d)
        fit2 = BagOfWordsVectorizer().fit(FileDocumentIterator(td))
        assert fit2.vocab == bow.vocab


def test_hs_scatter_update_matches_dense_autodiff():
    """The analytic hierarchical-softmax step must equal SGD on jax.grad of
    the dense HS loss (reference SkipGram.java:238ff HS branch, batched over
    padded Huffman paths)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors

    R = np.random.default_rng(4)
    V, D, B, L = 50, 16, 64, 7
    syn0 = jnp.asarray(R.normal(size=(V, D)).astype(np.float32) * 0.1)
    syn1 = jnp.asarray(R.normal(size=(V - 1, D)).astype(np.float32) * 0.1)
    centers = jnp.asarray(R.integers(0, V, B))
    pts = jnp.asarray(R.integers(0, V - 1, (B, L)))
    cds = jnp.asarray(R.integers(0, 2, (B, L)).astype(np.float32))
    lens = R.integers(1, L + 1, B)
    msk = jnp.asarray((np.arange(L)[None, :] < lens[:, None]).astype(np.float32))
    lr = 0.05

    def dense_loss(s0, s1):
        v = s0[centers]
        logits = jnp.einsum("bd,bld->bl", v, s1[pts])
        return jnp.sum(jax.nn.softplus((2.0 * cds - 1.0) * logits) * msk)

    g0, g1 = jax.grad(dense_loss, argnums=(0, 1))(syn0, syn1)
    want0, want1 = syn0 - lr * g0, syn1 - lr * g1

    sv = SequenceVectors(layer_size=D, use_hierarchical_softmax=True)
    step = sv._build_step()
    got0, got1, _ = step(syn0, syn1, centers, pts, cds, msk, lr)
    np.testing.assert_allclose(np.asarray(got0), np.asarray(want0), atol=1e-6)
    np.testing.assert_allclose(np.asarray(got1), np.asarray(want1), atol=1e-6)


@pytest.mark.slow
def test_word2vec_hierarchical_softmax_similarity_structure():
    """Similarity parity with HS enabled (reference useHierarchicSoftmax;
    VERDICT r2 missing #3). Slow lane (ISSUE 19 tier-1 budget reclaim):
    ~9s duplicate of the similarity-structure contract —
    test_word2vec_similarity_structure (negative sampling) keeps it
    tier-1 and test_word2vec_hs_cbow_trains keeps the HS training
    path."""
    # HS shares the root path across every word, so without frequent-word
    # subsampling the filler words ('the','a',...) drag all vectors onto one
    # direction on this tiny corpus — sample>0 is the canonical word2vec-HS
    # configuration (reference sampling in SkipGram.java HS branch).
    w2v = Word2Vec(layer_size=32, window=4, min_word_frequency=2, epochs=20,
                   learning_rate=0.05, sample=1e-3, seed=3,
                   use_hierarchical_softmax=True)
    w2v.fit(_corpus())
    assert w2v.syn1 is not None and w2v.syn1.shape[0] == len(w2v.vocab) - 1
    same_topic = w2v.similarity("day", "sun")
    cross_topic = w2v.similarity("day", "moon")
    assert same_topic > cross_topic, (same_topic, cross_topic)
    nearest = w2v.words_nearest("sun", 4)
    assert any(w in ("day", "light", "morning", "bright") for w in nearest), nearest


def test_word2vec_hs_cbow_trains():
    w2v = Word2Vec(layer_size=24, window=4, min_word_frequency=2, epochs=10,
                   learning_rate=0.05, seed=5, learning_algorithm="cbow",
                   use_hierarchical_softmax=True)
    w2v.fit(_corpus(200))
    assert w2v.similarity("night", "moon") > w2v.similarity("night", "sun")


def test_huffman_arrays_rectangular():
    vc = VocabCache.build([["a"] * 5 + ["b"] * 3 + ["c"] * 2 + ["d"]])
    codes, points, mask = vc.huffman_arrays()
    V = len(vc)
    assert codes.shape == points.shape == mask.shape
    assert codes.shape[0] == V
    for i in range(V):
        vw = vc.word_for(vc.word_at(i))
        n = int(mask[i].sum())
        assert n == len(vw.code)
        assert list(codes[i, :n].astype(int)) == vw.code
        assert list(points[i, :n]) == vw.points
        assert (points[i] < V - 1).all()  # inner-node table bounds


def test_paragraph_vectors_hierarchical_softmax():
    """PV-DBOW + infer_vector with the HS objective (reference
    ParagraphVectors useHierarchicSoftmax path)."""
    docs = [("doc_day", " ".join(["sun day light bright"] * 5)),
            ("doc_night", " ".join(["moon night dark stars"] * 5))]
    pv = ParagraphVectors(layer_size=24, min_word_frequency=1, epochs=15,
                          learning_rate=0.05, seed=2,
                          use_hierarchical_softmax=True)
    pv.fit(docs)
    assert pv.syn1 is not None
    sim_day = pv.similarity_to_label("sun light bright day", "doc_day")
    sim_night = pv.similarity_to_label("sun light bright day", "doc_night")
    assert sim_day > sim_night, (sim_day, sim_night)


# ------------------------------------------------- CJK segmentation (r3)
def _seg_f1(pred, gold):
    """Boundary-span F1: segments as (start, end) spans."""
    def spans(toks):
        out, i = set(), 0
        for t in toks:
            out.add((i, i + len(t)))
            i += len(t)
        return out
    p, g = spans(pred), spans(gold)
    tp = len(p & g)
    if not tp:
        return 0.0
    prec, rec = tp / len(p), tp / len(g)
    return 2 * prec * rec / (prec + rec)


ZH_GOLD = [
    ("我们在北京大学学习机器学习", ["我们", "在", "北京大学", "学习", "机器学习"]),
    ("今天天气很好", ["今天", "天气", "很", "好"]),
    ("我喜欢吃苹果", ["我", "喜欢", "吃", "苹果"]),
    ("他们的老师现在在中国工作", ["他们", "的", "老师", "现在", "在", "中国", "工作"]),
    ("因为这个问题很难所以我们要学习", ["因为", "这个", "问题", "很", "难", "所以", "我们", "要", "学习"]),
]

JA_GOLD = [
    ("私は東京大学の学生です", ["私", "は", "東京大学", "の", "学生", "です"]),
    ("今日はとてもいい天気です", ["今日", "は", "とても", "いい", "天気", "です"]),
    ("機械学習を勉強します", ["機械学習", "を", "勉強", "します"]),
    ("彼女は毎日日本語を勉強しています", ["彼女", "は", "毎日", "日本語", "を", "勉強", "しています"]),
    ("この本はとても面白いです", ["この", "本", "は", "とても", "面白い", "です"]),
]


@pytest.mark.parametrize("lang,gold", [("zh", ZH_GOLD), ("ja", JA_GOLD)])
def test_lattice_segmenter_beats_bigram_fallback(lang, gold):
    """Dictionary+Viterbi segmentation (reference ansj/kuromoji capability,
    VERDICT r2 missing #5): span-F1 on a small gold set clearly beats the
    character-bigram fallback, and is the CJKTokenizerFactory default for
    the language."""
    from deeplearning4j_tpu.nlp import CJKTokenizerFactory

    seg_tf = CJKTokenizerFactory(language=lang)
    assert seg_tf.segmenter is not None
    fallback_tf = CJKTokenizerFactory()       # bigram fallback

    f1_seg, f1_fb = [], []
    for text, want in gold:
        f1_seg.append(_seg_f1(seg_tf.create(text).get_tokens(), want))
        f1_fb.append(_seg_f1(fallback_tf.create(text).get_tokens(), want))
    mean_seg = sum(f1_seg) / len(f1_seg)
    mean_fb = sum(f1_fb) / len(f1_fb)
    assert mean_seg >= 0.9, (lang, f1_seg)
    assert mean_seg > mean_fb + 0.3, (lang, mean_seg, mean_fb)


def test_lattice_segmenter_unknown_handling_and_user_dict(tmp_path):
    from deeplearning4j_tpu.nlp import JapaneseSegmenter, LatticeSegmenter

    ja = JapaneseSegmenter()
    # unknown katakana run groups into ONE token (kuromoji character-class
    # grouping); unknown kanji stays per-character
    toks = ja.segment("コンピュータは面白いです")
    assert toks[0] == "コンピュータ"
    # user dictionary seam: unknown compound becomes one token after adding
    assert "量子計算" not in ja
    before = ja.segment("量子計算を勉強します")
    ja.add_word("量子計算", 100)
    after = ja.segment("量子計算を勉強します")
    assert "量子計算" in after and "量子計算" not in before
    # load_tsv
    p = tmp_path / "dict.tsv"
    p.write_text("深宇宙\t50\n", encoding="utf-8")
    seg = LatticeSegmenter().load_tsv(str(p))
    assert "深宇宙" in seg


def test_word2vec_with_chinese_segmenter():
    """End-to-end: Word2Vec over segmented Chinese text (the reference's
    ChineseTokenizer + Word2Vec use case)."""
    from deeplearning4j_tpu.nlp import CJKTokenizerFactory, Word2Vec
    corpus = (["我们 学习 机器学习", "学生 在 大学 学习", "老师 教 学生 机器学习",
               "今天 天气 很 好", "明天 天气 不 好", "天气 好 我们 高兴"] * 10)
    # strip the spaces: the segmenter must recover the words itself
    corpus = ["".join(s.split()) for s in corpus]
    w2v = Word2Vec(layer_size=16, window=3, min_word_frequency=1, epochs=5,
                   negative=3, seed=4,
                   tokenizer_factory=CJKTokenizerFactory(language="zh"))
    w2v.fit(corpus)
    assert w2v.has_word("机器学习") and w2v.has_word("天气")


def test_cjk_segmenter_drops_punctuation():
    from deeplearning4j_tpu.nlp import CJKTokenizerFactory
    toks = CJKTokenizerFactory(language="zh").create(
        "今天天气很好。我喜欢吃苹果！").get_tokens()
    assert "。" not in toks and "！" not in toks
    assert "今天" in toks and "苹果" in toks


# ------------------------------------------------- POS tagging (UIMA analogue)
def test_rule_based_pos_tagger():
    from deeplearning4j_tpu.nlp import RuleBasedPosTagger
    t = RuleBasedPosTagger()
    toks = "the quick dog quickly ate 42 sandwiches in London".split()
    tags = t.tag(toks)
    assert tags[0] == "DT"
    assert tags[3] == "RB"          # quickly
    assert tags[4] == "VBD"         # ate (lexicon)
    assert tags[5] == "CD"          # 42
    assert tags[6] == "NNS"         # sandwiches
    assert tags[7] == "IN"
    assert tags[8] == "NNP"         # London (mid-sentence capital)
    # sentence-initial capital is NOT auto-NNP
    assert t.tag(["Running", "works"])[0] == "VBG"


def test_pos_filter_tokenizer_factory():
    """Reference PosUimaTokenizerFactory(allowedPosTags): noun-only
    tokenization for embedding corpora."""
    from deeplearning4j_tpu.nlp import PosFilterTokenizerFactory
    tf = PosFilterTokenizerFactory(["NN*"])
    toks = tf.create("the hungry dog quickly ate two big sandwiches "
                     "in the kitchen").get_tokens()
    assert "dog" in toks and "sandwiches" in toks and "kitchen" in toks
    assert "quickly" not in toks and "ate" not in toks and "the" not in toks
    # exact-tag filtering + preprocessor seam
    from deeplearning4j_tpu.nlp import CommonPreprocessor
    tf2 = PosFilterTokenizerFactory(["VBD", "VBG"],
                                    pre_processor=CommonPreprocessor())
    toks2 = tf2.create("She was running and ate quickly").get_tokens()
    assert "running" in toks2 and "ate" in toks2 and "quickly" not in toks2


def test_pos_filtered_word2vec():
    from deeplearning4j_tpu.nlp import PosFilterTokenizerFactory, Word2Vec
    corpus = ["the dog quickly ate the food in the house",
              "a cat slowly drank the water in the kitchen"] * 20
    w2v = Word2Vec(layer_size=16, window=3, min_word_frequency=1, epochs=3,
                   negative=3, seed=4,
                   tokenizer_factory=PosFilterTokenizerFactory(["NN*"]))
    w2v.fit(corpus)
    assert w2v.has_word("dog") and w2v.has_word("kitchen")
    assert not w2v.has_word("quickly") and not w2v.has_word("the")
