"""XLA-vs-reference-numpy parity tests.

Reference test strategy (SURVEY.md §4): deeplearning4j-cuda's
CuDNNGradientChecks + TestConvolution assert the ACCELERATED path equals the
builtin path. The TPU analogue: each accelerated layer's XLA lowering is
checked against an independent straight-loop numpy implementation — the
"helper-with-fallback parity" discipline (SURVEY.md §2.1 L1) without
shipping a slow fallback in the product.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers import (BatchNormalization, ConvolutionLayer,
                                          LocalResponseNormalization, LSTM,
                                          SubsamplingLayer)

R = np.random.default_rng(77)


def _np_conv2d_same(x, w, b, stride):
    """Straight-loop NHWC conv, SAME padding (independent of lax.conv)."""
    B, H, W_, C = x.shape
    kh, kw, _, F = w.shape
    sh, sw = stride
    oh, ow = -(-H // sh), -(-W_ // sw)
    pad_h = max((oh - 1) * sh + kh - H, 0)
    pad_w = max((ow - 1) * sw + kw - W_, 0)
    xp = np.pad(x, ((0, 0), (pad_h // 2, pad_h - pad_h // 2),
                    (pad_w // 2, pad_w - pad_w // 2), (0, 0)))
    out = np.zeros((B, oh, ow, F), np.float64)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, i * sh:i * sh + kh, j * sw:j * sw + kw, :]
            out[:, i, j, :] = np.tensordot(patch, w, axes=([1, 2, 3],
                                                           [0, 1, 2]))
    return out + (b if b is not None else 0.0)


def test_conv2d_matches_numpy():
    layer = ConvolutionLayer(n_in=3, n_out=5, kernel_size=(3, 3),
                             stride=(2, 2), convolution_mode="same",
                             activation="identity", weight_init="xavier")
    params, _ = layer.init(jax.random.PRNGKey(0), None, jnp.float64)
    x = R.normal(size=(2, 9, 9, 3))
    got, _ = layer.apply(params, {}, jnp.asarray(x))
    want = _np_conv2d_same(x, np.asarray(params["W"]),
                           np.asarray(params["b"]), (2, 2))
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-10)


def test_conv2d_no_bias_matches_numpy():
    layer = ConvolutionLayer(n_in=2, n_out=4, kernel_size=(3, 3),
                             convolution_mode="same", has_bias=False,
                             activation="identity", weight_init="xavier")
    params, _ = layer.init(jax.random.PRNGKey(1), None, jnp.float64)
    assert "b" not in params
    x = R.normal(size=(2, 6, 6, 2))
    got, _ = layer.apply(params, {}, jnp.asarray(x))
    want = _np_conv2d_same(x, np.asarray(params["W"]), None, (1, 1))
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-10)


@pytest.mark.parametrize("pool", ["max", "avg"])
def test_subsampling_matches_numpy(pool):
    layer = SubsamplingLayer(pooling_type=pool, kernel_size=(2, 2),
                             stride=(2, 2))
    x = R.normal(size=(2, 8, 8, 3))
    got, _ = layer.apply({}, {}, jnp.asarray(x))
    B, H, W_, C = x.shape
    want = np.zeros((B, H // 2, W_ // 2, C))
    for i in range(H // 2):
        for j in range(W_ // 2):
            win = x[:, 2 * i:2 * i + 2, 2 * j:2 * j + 2, :]
            want[:, i, j, :] = (win.max((1, 2)) if pool == "max"
                                else win.mean((1, 2)))
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-12)


def test_avg_pool_same_divisor_semantics():
    """SAME-mode avg pool, odd length: the reference (SubsamplingLayer.java
    activate — mean over the full zero-padded im2col window) divides by
    kernel-size everywhere; TF/Keras divides by the valid-cell count. The
    flag selects; reference semantics is the default."""
    x = np.abs(R.normal(size=(1, 5, 5, 1))).astype(np.float64) + 1.0
    ref = SubsamplingLayer(pooling_type="avg", kernel_size=(2, 2),
                           stride=(2, 2), convolution_mode="same")
    tf_ = SubsamplingLayer(pooling_type="avg", kernel_size=(2, 2),
                           stride=(2, 2), convolution_mode="same",
                           avg_pool_include_pad_in_divisor=False)
    got_ref, _ = ref.apply({}, {}, jnp.asarray(x))
    got_tf, _ = tf_.apply({}, {}, jnp.asarray(x))
    # interior windows agree ...
    np.testing.assert_allclose(np.asarray(got_ref)[:, :2, :2],
                               np.asarray(got_tf)[:, :2, :2], atol=1e-12)
    # ... the corner window (1 valid cell of 4) differs by exactly 4x
    np.testing.assert_allclose(np.asarray(got_tf)[0, 2, 2, 0],
                               4.0 * np.asarray(got_ref)[0, 2, 2, 0],
                               atol=1e-12)
    # and the reference path equals sum/ (kh*kw) computed by hand
    np.testing.assert_allclose(np.asarray(got_ref)[0, 2, 2, 0],
                               x[0, 4, 4, 0] / 4.0, atol=1e-12)


def test_batchnorm_matches_numpy():
    layer = BatchNormalization(n_out=4, activation="identity")
    params, state = layer.init(jax.random.PRNGKey(2), None, jnp.float64)
    params = {"gamma": jnp.asarray(R.normal(size=4) + 1.0),
              "beta": jnp.asarray(R.normal(size=4))}
    x = R.normal(size=(6, 5, 5, 4)) * 3.0 + 1.0
    got, new_state = layer.apply(params, state, jnp.asarray(x), train=True)
    mean = x.mean((0, 1, 2))
    var = x.var((0, 1, 2))
    want = ((x - mean) / np.sqrt(var + layer.eps)) * np.asarray(params["gamma"]) \
        + np.asarray(params["beta"])
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-8)
    # running stats moved toward the batch stats
    np.testing.assert_allclose(np.asarray(new_state["mean"]),
                               (1 - layer.decay) * mean, atol=1e-5)


def test_lrn_matches_numpy():
    layer = LocalResponseNormalization(k=2.0, n=5, alpha=1e-4, beta=0.75)
    x = R.normal(size=(2, 4, 4, 8))
    got, _ = layer.apply({}, {}, jnp.asarray(x))
    want = np.zeros_like(x)
    half = 5 // 2
    for c in range(8):
        lo, hi = max(0, c - half), min(8, c + half + 1)
        denom = (2.0 + 1e-4 * (x[..., lo:hi] ** 2).sum(-1)) ** 0.75
        want[..., c] = x[..., c] / denom
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-10)


def test_lstm_matches_numpy():
    """Straight-loop LSTM recurrence vs the scan-hoisted implementation."""
    layer = LSTM(n_in=3, n_out=4, activation="tanh", weight_init="xavier")
    params, _ = layer.init(jax.random.PRNGKey(3), None, jnp.float64)
    x = R.normal(size=(2, 6, 3))
    got, _ = layer.apply(params, {}, jnp.asarray(x))

    W = np.asarray(params["W"])     # [n_in, 4H]
    Rm = np.asarray(params["R"])    # [H, 4H]
    b = np.asarray(params["b"])     # [4H]
    H = 4

    def sigmoid(v):
        return 1.0 / (1.0 + np.exp(-v))

    h = np.zeros((2, H))
    c = np.zeros((2, H))
    outs = []
    for t in range(x.shape[1]):
        z = x[:, t] @ W + h @ Rm + b
        # gate order must match the implementation: i, f, o, g
        i = sigmoid(z[:, 0 * H:1 * H])
        f = sigmoid(z[:, 1 * H:2 * H])
        o = sigmoid(z[:, 2 * H:3 * H])
        g = np.tanh(z[:, 3 * H:4 * H])
        c = f * c + i * g
        h = o * np.tanh(c)
        outs.append(h.copy())
    want = np.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-9)
