"""serving/fleet/affinity.py: the routing math, no processes involved.

Pins the property the whole affinity design hangs on — the router's
chain hashes are THE SAME hashes the replica prefix caches key blocks by
(imported, not re-implemented) — plus rendezvous determinism/minimal
disruption, the learned LRU map, and the candidate-ordering policy
(affinity preferred, overload spills, unseen prefixes rendezvous).
"""
from types import SimpleNamespace

import numpy as np

from deeplearning4j_tpu.serving.fleet.affinity import (
    AffinityMap, AffinityPolicy, prompt_chain, rendezvous_order)
from deeplearning4j_tpu.serving.generation.prefix import _block_hashes


def _view(rid, ready=True, queue=0, free=1.0):
    return SimpleNamespace(id=rid, ready=ready,
                           steering={"queue_depth": queue,
                                     "block_pool_free_frac": free})


# ------------------------------------------------------------- chain hash
def test_prompt_chain_is_the_prefix_cache_hash():
    prompt = list(range(3, 45))
    for blk in (8, 16):
        chain = prompt_chain(prompt, blk)
        want = _block_hashes(np.asarray(prompt, dtype=np.int32), blk)
        assert chain == want
        # only FULL blocks hash — the cache can only share full blocks
        assert len(chain) == len(prompt) // blk


def test_prompt_chain_is_a_rolling_chain():
    """chain(prefix) is a prefix of chain(extension): shared prompt heads
    share hashes, diverging tails diverge from the divergence block on."""
    head = list(range(32))
    a = prompt_chain(head + [1, 2, 3, 4, 5, 6, 7, 8], 8)
    b = prompt_chain(head + [9, 9, 9, 9, 9, 9, 9, 9], 8)
    assert a[:4] == b[:4] == prompt_chain(head, 8)
    assert a[4] != b[4]


def test_short_prompt_has_empty_chain():
    assert prompt_chain([1, 2, 3], 8) == []


# ------------------------------------------------------------- rendezvous
def test_rendezvous_is_deterministic_and_total():
    ids = [f"r{i}" for i in range(5)]
    key = b"some-chain-head"
    order = rendezvous_order(key, ids)
    assert sorted(order) == sorted(ids)
    assert order == rendezvous_order(key, list(reversed(ids)))


def test_rendezvous_minimal_disruption_on_member_loss():
    """Removing one replica must not remap keys that did not score
    highest on it — the surviving ids keep their relative order."""
    ids = [f"r{i}" for i in range(6)]
    keys = [f"key-{k}".encode() for k in range(40)]
    for key in keys:
        before = rendezvous_order(key, ids)
        lost = before[0]
        after = rendezvous_order(key, [r for r in ids if r != lost])
        assert after == [r for r in before if r != lost]


def test_rendezvous_spreads_distinct_keys():
    ids = ["a", "b", "c"]
    firsts = {rendezvous_order(f"k{i}".encode(), ids)[0]
              for i in range(60)}
    assert firsts == set(ids)   # every replica wins some keyspace


# ----------------------------------------------------------- affinity map
def test_affinity_map_longest_is_deepest_first():
    chain = prompt_chain(list(range(40)), 8)    # 5 blocks
    m = AffinityMap()
    m.record(chain[:2], "shallow")
    m.record(chain[:4], "deep")     # overwrites blocks 0-1 too
    rid, depth = m.longest(chain)
    assert (rid, depth) == ("deep", 4)
    # a diverging prompt still matches its shared head
    other = prompt_chain(list(range(16)) + [99] * 24, 8)
    rid, depth = m.longest(other)
    assert (rid, depth) == ("deep", 2)
    assert m.longest([]) == (None, 0)


def test_affinity_map_lru_capacity_and_forget():
    m = AffinityMap(capacity=4)
    chains = [prompt_chain([i] * 8, 8) for i in range(6)]
    for i, c in enumerate(chains):
        m.record(c, f"r{i % 2}")
    assert len(m) == 4              # two oldest evicted
    assert m.longest(chains[0]) == (None, 0)
    assert m.longest(chains[5])[0] == "r1"
    dropped = m.forget_replica("r1")
    assert dropped > 0
    assert m.longest(chains[5]) == (None, 0)
    stats = m.stats()
    assert "r1" not in stats["entries_per_replica"]


# ----------------------------------------------------------------- policy
def test_policy_prefers_learned_affinity_target():
    p = AffinityPolicy()
    chain = prompt_chain(list(range(32)), 8)
    views = [_view("a"), _view("b"), _view("c")]
    p.record(chain, "c")
    order, reason = p.candidates(chain, views)
    assert order[0] == "c" and reason == "affinity"
    assert sorted(order) == ["a", "b", "c"]


def test_policy_unseen_prefix_falls_back_to_rendezvous():
    p = AffinityPolicy()
    chain = prompt_chain(list(range(32)), 8)
    order, reason = p.candidates(chain, [_view("a"), _view("b")])
    assert reason == "rendezvous"
    assert order == rendezvous_order(chain[0], ["a", "b"])


def test_policy_spills_off_overloaded_target():
    p = AffinityPolicy(queue_hi=4)
    chain = prompt_chain(list(range(32)), 8)
    p.record(chain, "hot")
    views = [_view("hot", queue=9), _view("cool")]
    order, reason = p.candidates(chain, views)
    assert reason == "spill"
    assert order[0] == "cool"       # overloaded target demoted, not gone
    assert order[-1] == "hot"


def test_policy_starved_block_pool_counts_as_overload():
    p = AffinityPolicy(min_free_frac=0.05)
    chain = prompt_chain(list(range(32)), 8)
    p.record(chain, "starved")
    order, _ = p.candidates(chain, [_view("starved", free=0.01),
                                    _view("ok")])
    assert order[0] == "ok"


def test_policy_skips_not_ready_and_handles_empty():
    p = AffinityPolicy()
    chain = prompt_chain(list(range(32)), 8)
    p.record(chain, "dead")
    order, reason = p.candidates(
        chain, [_view("dead", ready=False), _view("live")])
    assert order == ["live"]
    assert p.candidates(chain, [_view("dead", ready=False)]) == ([], "none")
    # short prompt: no chain, rendezvous on the sentinel key still works
    order, reason = p.candidates([], [_view("a"), _view("b")])
    assert sorted(order) == ["a", "b"] and reason == "rendezvous"
