"""serving/ production inference engine: bucket ladder, AOT warm-up with
zero steady-state recompiles, admission control + deadlines, drain-then-stop,
multi-model registry + zero-downtime hot-swap, HTTP surface, metrics.

Heavy soak/hammer variants are marked ``slow``; the tier-1 versions keep
the same assertions at a handful-of-requests scale."""
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd
from deeplearning4j_tpu.serving import (BucketLadder, DeadlineExceededError,
                                        DrainingError, InferenceEngine,
                                        QueueFullError, ServingHTTPServer,
                                        ServingMetrics, ShapeMismatchError,
                                        UnknownModelError, xla_compile_count)

R = np.random.default_rng(77)


def _net(seed=3, n_in=4, n_out=3):
    conf = (NeuralNetConfiguration(seed=seed, updater=Sgd(0.1),
                                   dtype="float32")
            .list(DenseLayer(n_in=n_in, n_out=16, activation="tanh"),
                  OutputLayer(n_out=n_out, activation="softmax",
                              loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _post(url, payload, timeout=30):
    req = urllib.request.Request(url, json.dumps(payload).encode(),
                                 {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


# ------------------------------------------------------------ bucket ladder
def test_bucket_ladder():
    lad = BucketLadder((32, 1, 8, 8))
    assert lad.rungs == (1, 8, 32)
    assert lad.bucket_for(1) == 1
    assert lad.bucket_for(2) == 8
    assert lad.bucket_for(8) == 8
    assert lad.bucket_for(9) == 32
    assert lad.padding_waste(24) == pytest.approx(8 / 32)
    with pytest.raises(ValueError):
        lad.bucket_for(33)
    with pytest.raises(ValueError):
        BucketLadder(())
    with pytest.raises(ValueError):
        BucketLadder((0, 4))


# ------------------------------------------------- parity + zero recompiles
def test_bucketed_output_bit_identical_to_net_output():
    """Padded-bucket forward sliced back to the caller's rows must be
    BIT-identical to the unbatched net.output — padding must not leak."""
    net = _net()
    sizes = [1, 2, 5, 8, 17, 32]
    xs = [R.normal(size=(n, 4)).astype(np.float32) for n in sizes]
    expected = [np.asarray(net.output(x)) for x in xs]
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(1, 8, 32),
                          batch_window_ms=0.5)
    try:
        for x, want in zip(xs, expected):
            got = eng.predict(x)
            assert got.dtype == want.dtype
            assert np.array_equal(got, want)
    finally:
        eng.stop()


@pytest.mark.bench_smoke
def test_zero_recompiles_after_warmup():
    """Tier-1 guard (ISSUE acceptance): after warm-up, mixed-size concurrent
    traffic through two buckets triggers ZERO new XLA compilations — checked
    against the process-wide jax.monitoring backend-compile counter AND the
    engine's own trace hook."""
    net = _net(seed=9)
    sizes = [1, 3, 4, 8, 6, 2, 7, 5]
    # build every jit program the test itself needs BEFORE snapshotting
    expected = {n: np.asarray(net.output(R.normal(size=(n, 4))
                                         .astype(np.float32)))
                for n in sizes}  # warms net.output's per-shape cache
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(4, 8),
                          batch_window_ms=1.0)
    assert eng.trace_count == 2            # one trace per bucket at warm-up
    compiles0 = xla_compile_count()
    traces0 = eng.trace_count

    results = {}

    def worker(i, n):
        x = R.normal(size=(n, 4)).astype(np.float32)
        results[i] = (x, eng.predict(x))

    threads = [threading.Thread(target=worker, args=(i, n))
               for i, n in enumerate(sizes)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    eng.stop()
    for x, out in results.values():
        assert out.shape == (x.shape[0], 3)
    assert eng.trace_count == traces0, "serving path re-traced a program"
    assert xla_compile_count() == compiles0, \
        "steady-state serving triggered an XLA compilation"
    snap = eng.metrics()["default"]
    assert snap["requests"] == len(sizes)
    assert set(snap["per_bucket"]) <= {4, 8}


def test_mesh_sharded_serving_matches_single_host():
    """Merged batch lands on the 'data' axis (same mapping as
    parallel/inference.py); results must match the unsharded forward."""
    from deeplearning4j_tpu.parallel import make_mesh
    net = _net(seed=21)
    x = R.normal(size=(5, 4)).astype(np.float32)
    want = np.asarray(net.output(x))
    mesh = make_mesh()     # 8 virtual CPU devices on 'data'
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(8, 16),
                          mesh=mesh, batch_window_ms=0.5)
    try:
        got = eng.predict(x)
        np.testing.assert_allclose(got, want, atol=1e-6)
    finally:
        eng.stop()
    with pytest.raises(ValueError, match="not divisible"):
        InferenceEngine(net, feature_shape=(4,), buckets=(1, 8), mesh=mesh)


# ----------------------------------------------- admission control + deadlines
def test_queue_full_fast_fails():
    """With the dispatcher gated on a slow batch, the bounded queue fills
    and the next submit fast-fails with QueueFullError (HTTP 429)."""
    net = _net()
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(1,),
                          queue_limit=2, batch_window_ms=0.1)
    entry = eng.registry.get()
    real_runner = entry.batcher._runner
    gate = threading.Event()

    def gated_runner(padded):
        gate.wait(10.0)
        return real_runner(padded)

    entry.batcher._runner = gated_runner
    x = R.normal(size=(1, 4)).astype(np.float32)
    done = []
    threads = [threading.Thread(
        target=lambda: done.append(eng.predict(x, timeout=20)))
        for _ in range(3)]           # 1 in flight (gated) + 2 queued
    try:
        for t in threads:
            t.start()
            time.sleep(0.05)
        assert entry.batcher.queue_depth == 2
        with pytest.raises(QueueFullError):
            eng.predict(x, timeout=5)
        assert eng.metrics()["default"]["rejected"]["full"] == 1
    finally:
        gate.set()
        for t in threads:
            t.join(timeout=10)
            assert not t.is_alive()
        eng.stop()
    assert len(done) == 3            # the gated requests all completed


def test_deadline_expires_instead_of_blocking():
    """A request whose deadline lapses while queued raises
    DeadlineExceededError promptly — callers can never hang."""
    net = _net()
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(1, 8),
                          batch_window_ms=500.0)   # long collect window
    try:
        x = R.normal(size=(1, 4)).astype(np.float32)
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceededError):
            eng.predict(x, timeout=0.05)
        assert time.monotonic() - t0 < 2.0
        assert eng.metrics()["default"]["rejected"]["deadline"] == 1
    finally:
        eng.stop(drain=False)


def test_shape_mismatch_rejected():
    net = _net()
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(1, 8),
                          batch_window_ms=0.5)
    try:
        with pytest.raises(ShapeMismatchError):
            eng.predict(np.zeros((2, 5), np.float32))
        with pytest.raises(ShapeMismatchError):
            eng.predict(np.zeros((0, 4), np.float32))
    finally:
        eng.stop()


# --------------------------------------------------------------- lifecycle
def test_drain_then_stop_resolves_everything():
    """stop(drain=True): queued work flushes; new work gets DrainingError;
    stop(drain=False): queued work is failed, not hung."""
    net = _net()
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(1, 8),
                          batch_window_ms=50.0)
    x = R.normal(size=(2, 4)).astype(np.float32)
    want = np.asarray(net.output(x))
    results, errors = [], []

    def client():
        try:
            results.append(eng.predict(x, timeout=10))
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=client) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.02)              # let them enqueue inside the window
    eng.stop(drain=True)          # must flush all four
    for t in threads:
        t.join(timeout=5)
        assert not t.is_alive(), "caller left hanging across stop()"
    assert not errors, errors
    assert len(results) == 4
    for out in results:
        assert np.allclose(out, want, atol=1e-6)
    with pytest.raises(DrainingError):
        eng.predict(x)


def test_stop_without_drain_fails_pending():
    net = _net()
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(1,),
                          batch_window_ms=300.0)
    x = R.normal(size=(1, 4)).astype(np.float32)
    errors, results = [], []

    def client():
        try:
            results.append(eng.predict(x, timeout=10))
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=client) for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    eng.stop(drain=False)
    for t in threads:
        t.join(timeout=5)
        assert not t.is_alive()
    # every caller resolved: served (the one already collected) or failed
    assert len(errors) + len(results) == 3
    assert all(isinstance(e, DrainingError) for e in errors)


# ------------------------------------------------------- registry + hot-swap
def test_multi_model_routing_and_unknown_model():
    net_a, net_b = _net(seed=1), _net(seed=2)
    eng = InferenceEngine(net_a, feature_shape=(4,), buckets=(8,),
                          batch_window_ms=0.5)
    eng.add_model("b", net_b, feature_shape=(4,), buckets=(8,))
    try:
        x = R.normal(size=(3, 4)).astype(np.float32)
        np.testing.assert_allclose(eng.predict(x),
                                   np.asarray(net_a.output(x)), atol=1e-6)
        np.testing.assert_allclose(eng.predict(x, model="b"),
                                   np.asarray(net_b.output(x)), atol=1e-6)
        with pytest.raises(UnknownModelError):
            eng.predict(x, model="nope")
        info = eng.models()
        assert set(info) == {"default", "b"}
        assert info["default"]["version"] == 1
    finally:
        eng.stop()


def _hot_swap_under_load(n_clients, min_requests, post_swap_requests):
    """Shared body for the tier-1 and slow hot-swap tests: hammer the
    engine while swapping mid-load; ZERO failures allowed, every result
    must match the old or the new model bit-for-bit, and any request
    SUBMITTED after the cutover must see the new model."""
    net_old, net_new = _net(seed=5), _net(seed=6)
    x = R.normal(size=(3, 4)).astype(np.float32)
    want_old = np.asarray(net_old.output(x))
    want_new = np.asarray(net_new.output(x))
    assert not np.allclose(want_old, want_new)   # swap must be observable
    eng = InferenceEngine(net_old, feature_shape=(4,), buckets=(4, 8),
                          batch_window_ms=0.5)
    compiles0 = xla_compile_count()
    failures, outputs = [], []
    out_lock = threading.Lock()
    swapped = threading.Event()

    def client():
        k = post_swap = 0
        # run at least min_requests, and keep going until this client has
        # made post_swap_requests submissions entirely after the cutover
        while k < min_requests or post_swap < post_swap_requests:
            k += 1
            submitted_after_swap = swapped.is_set()
            try:
                out = eng.predict(x, timeout=10)
            except Exception as e:       # pragma: no cover - must not happen
                failures.append(e)
                return
            post_swap += submitted_after_swap
            with out_lock:
                outputs.append((submitted_after_swap, out))

    threads = [threading.Thread(target=client) for _ in range(n_clients)]
    for t in threads:
        t.start()
    time.sleep(0.02)
    version = eng.hot_swap("default", net_new)
    swapped.set()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive()
    eng.stop()
    assert not failures, f"hot-swap failed requests: {failures[:3]}"
    assert version == 2
    # same architecture: the swap must not have compiled anything
    assert xla_compile_count() == compiles0
    n_old = n_new = 0
    for submitted_after_swap, out in outputs:
        if np.array_equal(out, want_old):
            n_old += 1
            assert not submitted_after_swap, \
                "request submitted after the cutover served by the old model"
        elif np.array_equal(out, want_new):
            n_new += 1
        else:                            # pragma: no cover
            raise AssertionError("output matches neither model")
    assert n_old + n_new == len(outputs)
    assert n_new >= n_clients * post_swap_requests


def test_hot_swap_zero_downtime():
    _hot_swap_under_load(n_clients=4, min_requests=8, post_swap_requests=2)


@pytest.mark.slow
def test_hot_swap_soak():
    _hot_swap_under_load(n_clients=8, min_requests=200,
                         post_swap_requests=10)


def test_hot_swap_changed_architecture_warms_before_cutover(tmp_path):
    """A swap to a DIFFERENT architecture compiles the new ladder before
    the cutover; serving keeps answering throughout."""
    conf_big = (NeuralNetConfiguration(seed=8, updater=Sgd(0.1),
                                       dtype="float32")
                .list(DenseLayer(n_in=4, n_out=32, activation="relu"),
                      OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
                .build())
    net_big = MultiLayerNetwork(conf_big).init()
    eng = InferenceEngine(_net(seed=5), feature_shape=(4,), buckets=(4,),
                          batch_window_ms=0.5)
    try:
        x = R.normal(size=(2, 4)).astype(np.float32)
        eng.predict(x)
        traces0 = eng.trace_count
        version = eng.hot_swap("default", net_big)
        assert version == 2
        assert eng.trace_count == traces0 + 1    # re-warmed the one bucket
        np.testing.assert_allclose(eng.predict(x),
                                   np.asarray(net_big.output(x)), atol=1e-6)
    finally:
        eng.stop()


def test_reload_from_checkpoint_zip(tmp_path):
    from deeplearning4j_tpu.util.serialization import write_model
    net_a, net_b = _net(seed=30), _net(seed=31)
    path = str(tmp_path / "model_b.zip")
    write_model(net_b, path)
    eng = InferenceEngine(net_a, feature_shape=(4,), buckets=(4,),
                          batch_window_ms=0.5)
    try:
        x = R.normal(size=(2, 4)).astype(np.float32)
        assert np.allclose(eng.predict(x), np.asarray(net_a.output(x)),
                           atol=1e-6)
        eng.reload_from_checkpoint("default", path)
        np.testing.assert_allclose(eng.predict(x),
                                   np.asarray(net_b.output(x)), atol=1e-5)
    finally:
        eng.stop()


# -------------------------------------------------------------------- HTTP
def test_http_surface_status_codes(tmp_path):
    from deeplearning4j_tpu.util.serialization import write_model
    net = _net(seed=40)
    net2 = _net(seed=41)
    zip_path = str(tmp_path / "v2.zip")
    write_model(net2, zip_path)
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(1, 8),
                          batch_window_ms=0.5)
    srv = ServingHTTPServer(eng)
    port = srv.start()
    base = f"http://127.0.0.1:{port}"
    try:
        x = R.normal(size=(3, 4)).astype(np.float32)
        # predict 200 + parity
        code, body = _post(f"{base}/predict", {"features": x.tolist()})
        assert code == 200
        np.testing.assert_allclose(np.asarray(body["output"]),
                                   np.asarray(net.output(x)), atol=1e-5)
        # health 200 with queue depths
        with urllib.request.urlopen(f"{base}/health", timeout=10) as r:
            h = json.loads(r.read())
        assert h["status"] == "ok" and "default" in h["queue_depth"]
        # models + metrics
        with urllib.request.urlopen(f"{base}/models", timeout=10) as r:
            m = json.loads(r.read())
        assert m["default"]["buckets"] == [1, 8]
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            snap = json.loads(r.read())["default"]
        assert snap["requests"] >= 1 and "p99" in snap["latency_ms"]
        # malformed JSON -> 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            req = urllib.request.Request(f"{base}/predict", b"{not json",
                                         {"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
        # bad feature payload -> 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{base}/predict", {"features": [["a", "b"]]})
        assert ei.value.code == 400
        # wrong trailing shape -> 400 (ShapeMismatch taxonomy)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{base}/predict", {"features": [[1.0, 2.0]]})
        assert ei.value.code == 400
        # unknown model -> 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{base}/predict/ghost", {"features": x.tolist()})
        assert ei.value.code == 404
        # reload -> hot swap through the wire
        code, body = _post(f"{base}/reload",
                           {"model": "default", "path": zip_path})
        assert code == 200 and body["version"] == 2
        code, body = _post(f"{base}/predict", {"features": x.tolist()})
        np.testing.assert_allclose(np.asarray(body["output"]),
                                   np.asarray(net2.output(x)), atol=1e-5)
        # reload unknown model -> 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{base}/reload", {"model": "ghost", "path": zip_path})
        assert ei.value.code == 404
    finally:
        srv.stop()
    # draining after stop: engine rejects
    with pytest.raises(DrainingError):
        eng.predict(np.zeros((1, 4), np.float32))


def test_http_draining_health_503():
    net = _net(seed=50)
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(1,),
                          batch_window_ms=0.5)
    srv = ServingHTTPServer(eng)
    port = srv.start()
    base = f"http://127.0.0.1:{port}"
    try:
        eng.stop(drain=True)       # engine drains; listener still up
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/health", timeout=10)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["status"] == "draining"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{base}/predict", {"features": [[0, 0, 0, 0]]})
        assert ei.value.code == 503
    finally:
        srv.stop()


# ------------------------------------------------------------------ metrics
def test_metrics_snapshot_and_stats_storage_bridge():
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
    net = _net(seed=60)
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(8,),
                          batch_window_ms=0.5)
    try:
        for n in (2, 6, 8):
            eng.predict(R.normal(size=(n, 4)).astype(np.float32))
        snap = eng.metrics()["default"]
        assert snap["requests"] == 3 and snap["rows"] == 16
        assert snap["batches"] >= 1
        assert 0.0 < snap["batch_occupancy"] <= 1.0
        assert snap["padding_waste"] == pytest.approx(
            1.0 - snap["batch_occupancy"])
        assert snap["latency_ms"]["p99"] >= snap["latency_ms"]["p50"] >= 0
        store = InMemoryStatsStorage()
        eng.publish_metrics(store)
        ups = store.get_updates("serving", "default")
        assert ups and ups[-1]["requests"] == 3
    finally:
        eng.stop()


def test_oversized_request_chunks_across_max_bucket():
    net = _net(seed=70)
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(8,),
                          batch_window_ms=0.5)
    try:
        x = R.normal(size=(21, 4)).astype(np.float32)
        np.testing.assert_array_equal(eng.predict(x),
                                      np.asarray(net.output(x)))
    finally:
        eng.stop()


# ------------------------------------------------------------ hammer (soak)
def _hammer(eng, net, n_threads, n_requests, sizes):
    """Every caller must get exactly its own rows back, bit-identical."""
    failures = []

    def client(tid):
        rng = np.random.default_rng(1000 + tid)
        for k in range(n_requests):
            n = sizes[(tid + k) % len(sizes)]
            x = rng.normal(size=(n, 4)).astype(np.float32)
            # salt row 0 with an id so cross-request row mixups can't
            # accidentally produce the right answer
            x[0, 0] = tid * 1000 + k
            try:
                out = eng.predict(x, timeout=30)
                want = np.asarray(net.output(x))
                if not np.array_equal(out, want):
                    failures.append((tid, k, "mismatch"))
            except Exception as e:
                failures.append((tid, k, repr(e)))

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive()
    assert not failures, failures[:5]


def test_concurrent_hammer_result_integrity():
    net = _net(seed=80)
    sizes = [1, 2, 3, 5, 8]
    for n in sizes:                       # pre-warm net.output's jit cache
        net.output(np.zeros((n, 4), np.float32))
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(4, 8),
                          batch_window_ms=1.0, queue_limit=512)
    try:
        _hammer(eng, net, n_threads=6, n_requests=6, sizes=sizes)
    finally:
        eng.stop()


@pytest.mark.slow
def test_concurrent_hammer_soak():
    net = _net(seed=81)
    sizes = [1, 2, 3, 5, 8, 13, 21, 32]
    for n in sizes:
        net.output(np.zeros((n, 4), np.float32))
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(8, 32, 64),
                          batch_window_ms=1.0, queue_limit=2048)
    try:
        _hammer(eng, net, n_threads=16, n_requests=100, sizes=sizes)
        snap = eng.metrics()["default"]
        assert snap["requests"] == 16 * 100
        assert snap["rejected"]["deadline"] == 0
    finally:
        eng.stop()


# ------------------------------------------------------------- bench smoke
@pytest.mark.bench_smoke
def test_serving_bench_smoke():
    """Tier-1 guard for the serving_throughput row: both columns run end
    to end and produce sane numbers. The bucketed-beats-unbucketed
    acceptance ratio is measured by bench.py on the real rig at full
    duration; CI pins 'not broken'."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    row = bench.bench_serving(duration=1.0, clients=4,
                              sizes=(1, 3, 5, 8))
    assert row["bucketed_req_per_sec"] > 0
    assert row["unbucketed_req_per_sec"] > 0
    assert row["bucketed_p99_ms"] > 0


def test_unwarmed_engine_raises_clear_error():
    from deeplearning4j_tpu.serving import ServingError
    net = _net(seed=90)
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(8,),
                          batch_window_ms=0.5, warm=False)
    try:
        with pytest.raises(ServingError, match="no warmed program"):
            eng.predict(np.zeros((2, 4), np.float32), timeout=5)
        eng.warm_up()
        assert eng.predict(np.zeros((2, 4), np.float32)).shape == (2, 3)
    finally:
        eng.stop()


def test_hot_swap_changed_arch_keeps_custom_forward_fn():
    """A changed-architecture swap must re-warm with the model's custom
    forward_fn, not silently fall back to the default forward."""
    net_a, net_b = _net(seed=91), _net(seed=92, n_in=4)
    net_b.conf.layers = net_b.conf.layers  # same conf class, new params

    def fwd_a(params, state, x):
        return net_a._output_pure(params, state, x) + 1.0

    def check(eng, net, x):
        return np.allclose(eng.predict(x),
                           np.asarray(net.output(x)) + 1.0, atol=1e-6)

    eng = InferenceEngine(net_a, feature_shape=(4,), buckets=(4,),
                          batch_window_ms=0.5, forward_fn=fwd_a)
    try:
        x = R.normal(size=(2, 4)).astype(np.float32)
        assert check(eng, net_a, x)
        # force the changed-shape path: a wider hidden layer
        conf_big = (NeuralNetConfiguration(seed=93, updater=Sgd(0.1),
                                           dtype="float32")
                    .list(DenseLayer(n_in=4, n_out=24, activation="tanh"),
                          OutputLayer(n_out=3, activation="softmax",
                                      loss="mcxent"))
                    .build())
        net_big = MultiLayerNetwork(conf_big).init()
        eng.hot_swap("default", net_big)
        # the custom fwd closes over net_a's ARCHITECTURE but runs the
        # swapped params; with the default-forward bug this returned
        # net_big.output(x) WITHOUT the +1.0 marker
        np.testing.assert_allclose(
            eng.predict(x), np.asarray(net_big.output(x)) + 1.0, atol=1e-6)
    finally:
        eng.stop()


def test_hot_swap_same_shapes_different_arch_rewarms():
    """Regression: the fast-path signature must catch same-SHAPED nets with
    a different architecture (tanh vs relu) — reusing the old executables
    would silently serve the old activation with the new params."""
    def build(act):
        conf = (NeuralNetConfiguration(seed=94, updater=Sgd(0.1),
                                       dtype="float32")
                .list(DenseLayer(n_in=4, n_out=16, activation=act),
                      OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    net_tanh, net_relu = build("tanh"), build("relu")
    eng = InferenceEngine(net_tanh, feature_shape=(4,), buckets=(4,),
                          batch_window_ms=0.5)
    try:
        x = R.normal(size=(2, 4)).astype(np.float32)
        traces0 = eng.trace_count
        eng.hot_swap("default", net_relu)
        assert eng.trace_count == traces0 + 1   # forced full re-warm
        np.testing.assert_array_equal(eng.predict(x),
                                      np.asarray(net_relu.output(x)))
        # seed-only difference stays on the free fast path
        net_relu2 = build("relu")
        net_relu2.init(seed=12345)
        traces1 = eng.trace_count
        eng.hot_swap("default", net_relu2)
        assert eng.trace_count == traces1       # no re-warm
        np.testing.assert_array_equal(eng.predict(x),
                                      np.asarray(net_relu2.output(x)))
    finally:
        eng.stop()
