"""serving/fleet/autoscale.py: the pure decision function + the actuator.

decide() is a pure function of fleet state and the clock, so the whole
policy truth table runs without processes; the Autoscaler tests drive
tick() by hand against a duck-typed fake router and a manual clock.
"""
import threading
import time
from types import SimpleNamespace

from deeplearning4j_tpu.serving.fleet.autoscale import (
    Autoscaler, AutoscalePolicy, decide)

P = AutoscalePolicy(min_replicas=1, max_replicas=4, queue_hi=4,
                    occupancy_lo=0.25, scale_out_cooldown_s=5.0,
                    scale_in_cooldown_s=30.0)


def _decide(**kw):
    base = dict(ready=2, starting=0, queue_depth=0, slot_occupancy=0.5,
                slo_breached=False, now_s=1000.0)
    base.update(kw)
    return decide(P, **base)


# ------------------------------------------------------------ truth table
def test_below_min_always_scales_out():
    assert _decide(ready=0, starting=0) == (1, "below_min")
    # even inside the cooldown window — a fleet below min is an outage
    assert _decide(ready=0, last_out_s=999.0) == (1, "below_min")


def test_slo_burn_scales_out():
    assert _decide(slo_breached=True) == (1, "slo_burn")


def test_queue_depth_scales_out_per_ready_replica():
    # threshold is queue_hi * ready: 2 ready -> backlog must exceed 8
    assert _decide(queue_depth=8) == (0, "steady")
    assert _decide(queue_depth=9) == (1, "queue_depth")


def test_scale_out_respects_cooldown_max_and_starting():
    assert _decide(slo_breached=True, last_out_s=996.0) == (0, "steady")
    assert _decide(slo_breached=True, ready=4) == (0, "steady")
    # a replica already starting absorbs the signal — one step per tick
    assert _decide(slo_breached=True, starting=1) == (0, "steady")


def test_idle_scale_in_requires_everything():
    idle = dict(queue_depth=0, slot_occupancy=0.1)
    assert _decide(**idle) == (-1, "idle")
    assert _decide(**idle, ready=1) == (0, "steady")        # at min
    assert _decide(**idle, last_in_s=990.0) == (0, "steady")  # cooldown
    assert _decide(queue_depth=1, slot_occupancy=0.1) == (0, "steady")
    assert _decide(queue_depth=0, slot_occupancy=0.5) == (0, "steady")
    assert _decide(**idle, slo_breached=True) == (1, "slo_burn")
    assert _decide(**idle, starting=1) == (0, "steady")


# --------------------------------------------------------------- actuator
class _FakeRouter:
    def __init__(self, rows):
        self.rows = {r["id"]: r for r in rows}
        self.added = []
        self.drained = []
        self.drain_event = threading.Event()

    def metrics(self):
        return {"replicas": dict(self.rows)}

    def add_process(self, proc, wait_ready=True):
        self.added.append(proc)

    def drain_replica(self, rid):
        self.drained.append(rid)
        self.drain_event.set()
        return True


def _row(rid, state="ready", queue=0, occ=0.5, in_flight=0, forwarded=0):
    return {"id": rid, "state": state, "forwarded": forwarded,
            "steering": {"queue_depth": queue, "slot_occupancy": occ,
                         "in_flight": in_flight}}


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_tick_scales_out_on_queue_and_respects_cooldown():
    router = _FakeRouter([_row("a", queue=6, occ=0.9),
                          _row("b", queue=6, occ=0.9),
                          _row("x", state="dead", queue=99)])
    clock = _Clock()
    scaler = Autoscaler(router,
                        lambda i: SimpleNamespace(id=f"auto{i}"),
                        policy=P, clock=clock)
    clock.t = 100.0
    assert scaler.tick() == (1, "queue_depth")      # 12 > 4*2
    assert [p.id for p in router.added] == ["auto0"]
    assert scaler.launched == 1
    clock.t = 101.0                                 # inside cooldown
    assert scaler.tick() == (0, "steady")
    clock.t = 106.0
    assert scaler.tick() == (1, "queue_depth")
    assert [p.id for p in router.added] == ["auto0", "auto1"]
    assert [h["reason"] for h in scaler.history] == ["queue_depth",
                                                     "queue_depth"]


def test_tick_scales_out_on_watchdog_breach():
    router = _FakeRouter([_row("a", queue=0, occ=0.3)])
    watchdog = SimpleNamespace(
        check=lambda: {"breached": [{"slo": "ttft_p99"}]})
    scaler = Autoscaler(router, lambda i: SimpleNamespace(id=f"a{i}"),
                        policy=P, watchdog=watchdog, clock=_Clock())
    delta, reason = scaler.tick()
    assert (delta, reason) == (1, "slo_burn")
    assert scaler.history[0]["breached"] == [{"slo": "ttft_p99"}]


def test_watchdog_failure_never_stalls_scaling():
    router = _FakeRouter([_row("a", queue=20, occ=0.9)])
    watchdog = SimpleNamespace(
        check=lambda: (_ for _ in ()).throw(RuntimeError("flake")))
    scaler = Autoscaler(router, lambda i: SimpleNamespace(id=f"a{i}"),
                        policy=P, watchdog=watchdog, clock=_Clock())
    assert scaler.tick() == (1, "queue_depth")


def test_tick_drains_least_loaded_on_idle():
    router = _FakeRouter([_row("busy", occ=0.1, in_flight=2, forwarded=9),
                          _row("lazy", occ=0.1, in_flight=0, forwarded=1)])
    scaler = Autoscaler(router, lambda i: SimpleNamespace(id=f"a{i}"),
                        policy=P, clock=_Clock())
    assert scaler.tick() == (-1, "idle")
    assert router.drain_event.wait(timeout=5.0)     # background drain
    assert router.drained == ["lazy"]
    # immediately after: the scale-in cooldown holds the next move
    assert scaler.tick() == (0, "steady")


def test_observe_folds_ready_rows_only():
    router = _FakeRouter([_row("a", queue=3, occ=0.2),
                          _row("b", queue=5, occ=0.6),
                          _row("s", state="starting", queue=99, occ=1.0),
                          _row("d", state="dead", queue=99)])
    scaler = Autoscaler(router, lambda i: None, policy=P, clock=_Clock())
    obs = scaler.observe()
    assert obs["ready"] == 2 and obs["starting"] == 1
    assert obs["queue_depth"] == 8
    assert abs(obs["slot_occupancy"] - 0.4) < 1e-9
    assert obs["slo_breached"] is False


def test_actuator_thread_start_stop():
    router = _FakeRouter([_row("a")])
    scaler = Autoscaler(router, lambda i: None, policy=P, period_s=0.01,
                        clock=time.monotonic)
    scaler.start()
    try:
        deadline = time.monotonic() + 5.0
        while not scaler._thread.is_alive() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert scaler._thread.is_alive()
    finally:
        scaler.stop()
    assert scaler._thread is None
