"""Report-component DSL (reference deeplearning4j-ui-components): JSON
round-trip, server-side SVG/HTML rendering, and stats->report assembly."""
import numpy as np
import pytest

from deeplearning4j_tpu.ui import components as C


def _full_tree():
    return C.ComponentDiv(components=[
        C.ComponentText("Report", size=18, bold=True),
        C.ChartLine(title="loss", x=[[0, 1, 2], [0, 1, 2]],
                    y=[[3.0, 2.0, 1.0], [2.5, 2.4, 2.2]],
                    series_names=["train", "val"]),
        C.ChartScatter(title="emb", x=[[0.0, 1.0]], y=[[1.0, 0.0]],
                       series_names=["pts"]),
        C.ChartHistogram(title="w", lower_bounds=[0.0, 0.5],
                         upper_bounds=[0.5, 1.0], y=[3.0, 7.0]),
        C.ChartHorizontalBar(title="f1", labels=["class0", "class1"],
                             values=[0.9, 0.7]),
        C.ChartStackedArea(title="mem", x=[0, 1, 2],
                           y=[[1, 1, 1], [2, 1, 0]],
                           series_names=["activations", "params"]),
        C.ChartTimeline(title="steps", lane_names=["device"],
                        lane_entries=[[[0, 5, "fwd"], [5, 9, "bwd"]]]),
        C.ComponentTable(header=["metric", "value"],
                         content=[["acc", "0.97"], ["f1", "0.95"]]),
        C.DecoratorAccordion(title="details", default_collapsed=False,
                             components=[C.ComponentText("inner <txt>")]),
    ])


def test_json_round_trip_all_types():
    page = _full_tree()
    j = page.to_json()
    back = C.from_json(j)
    assert back.to_json() == j
    # every registered type appears in the payload
    for name in ("ChartLine", "ChartScatter", "ChartHistogram",
                 "ChartHorizontalBar", "ChartStackedArea", "ChartTimeline",
                 "ComponentTable", "ComponentText", "ComponentDiv",
                 "DecoratorAccordion"):
        assert name in j


def test_render_html_is_self_contained_and_escaped():
    html = C.render_html(_full_tree())
    assert html.startswith("<!DOCTYPE html>")
    assert "<svg" in html and "<table>" in html and "<details open>" in html
    assert "&lt;txt&gt;" in html          # text content is escaped
    assert "<script" not in html          # no JS dependency


def test_unknown_type_raises():
    with pytest.raises(ValueError, match="Unknown component"):
        C.from_json('{"component_type": "ChartBogus"}')


def test_training_report_from_stats():
    """End-to-end: train with a StatsListener (histograms on), assemble the
    component report, render it."""
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.ui import (InMemoryStatsStorage, StatsListener,
                                       StatsUpdateConfiguration)

    r = np.random.default_rng(0)
    x = r.normal(size=(64, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(-1) > 0).astype(int)]
    conf = (NeuralNetConfiguration(seed=1, updater=Sgd(0.1))
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(
        storage, config=StatsUpdateConfiguration(collect_histograms=True)))
    net.fit(x, y, epochs=4, batch_size=32)

    report = C.training_report(storage)
    j = report.to_json()
    assert "score vs iteration" in j and "ChartHistogram" in j
    html = C.render_html(C.from_json(j))
    assert "<svg" in html and "Training report" in html


def test_attribute_injection_is_escaped():
    html = C.render_html(C.ComponentText(
        "hi", color="#111' onmouseover='alert(1)"))
    assert "onmouseover='alert" not in html
    assert "&#39;" in html


def test_non_finite_points_do_not_poison_chart():
    chart = C.ChartLine(title="s", x=[[0, 1, 2, 3]],
                        y=[[1.0, float("nan"), 2.0, float("inf")]],
                        series_names=["loss"])
    svg = chart.render()
    assert "nan" not in svg and "inf" not in svg
    assert "polyline" in svg


def test_dashboard_delegates_to_dsl():
    from deeplearning4j_tpu.ui.dashboard import (_svg_histogram,
                                                 _svg_line_chart)
    out = _svg_line_chart([("a", [(0, 1.0), (1, float("nan")), (2, 2.0)])])
    assert "<svg" in out and "nan" not in out
    assert _svg_line_chart([("a", [])]) == "<p class='meta'>no data yet</p>"
    h = _svg_histogram({"counts": [1, 3, 2], "lo": -1.0, "hi": 1.0})
    assert "<svg" in h and h.count("<rect") == 3


def test_non_finite_filtering_stacked_area_and_histogram():
    sa = C.ChartStackedArea(x=[0, 1, 2], y=[[1.0, float("nan"), 1.0],
                                            [2.0, 1.0, float("inf")]],
                            series_names=["a", "b"])
    svg = sa.render()
    assert "nan" not in svg and "inf" not in svg and "polygon" in svg
    h = C.ChartHistogram(lower_bounds=[0.0, 1.0, 2.0],
                         upper_bounds=[1.0, 2.0, 3.0],
                         y=[3.0, float("nan"), 2.0])
    svg = h.render()
    assert "nan" not in svg
    assert svg.count("<rect") >= 2   # the two finite bins still draw


def test_stacked_area_ragged_bands_truncate():
    """Ragged band lengths (a mid-update dashboard feed) truncate to the
    shortest instead of crashing."""
    sa = C.ChartStackedArea(x=[0, 1, 2], y=[[1.0, 2.0]], series_names=["a"])
    svg = sa.render()
    assert "polygon" in svg
