"""Checkpoint-restart orchestration + profiler hookup (SURVEY.md §5.1/§5.3:
periodic checkpoints, resume-after-preemption, XProf trace capture) —
incl. corrupt-checkpoint fallback, mid-epoch resume, and the prune
last-completed-write contract."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.util.checkpointing import (CheckpointListener,
                                                   ProfilerListener,
                                                   fit_with_checkpointing,
                                                   is_valid_checkpoint,
                                                   latest_checkpoint,
                                                   list_checkpoints,
                                                   read_checkpoint_manifest)

R = np.random.default_rng(29)


def _net(seed=3):
    conf = (NeuralNetConfiguration(seed=seed, updater=Adam(5e-3), dtype="float32")
            .list(DenseLayer(n_in=5, n_out=12, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _it(n=128, bs=32):
    x = R.normal(size=(n, 5)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(-1) > 0).astype(int)]
    return ListDataSetIterator(features=x, labels=y, batch_size=bs), x, y


def test_checkpoint_listener_writes_and_prunes(tmp_path):
    net = _net()
    it, _, _ = _it()
    net.set_listeners(CheckpointListener(str(tmp_path), every_n_epochs=1,
                                         keep_last=2))
    net.fit(iterator=it, epochs=5)
    ckpts = list_checkpoints(str(tmp_path))
    assert [e for _, e in ckpts] == [4, 5]     # pruned to last 2
    assert latest_checkpoint(str(tmp_path)).endswith("checkpoint_epoch5.zip")


def test_fit_with_checkpointing_resumes(tmp_path):
    d = str(tmp_path / "ck")
    it, x, y = _it()

    # run 1: 3 of 6 epochs, then "preemption"
    a = _net()
    fit_with_checkpointing(a, it, epochs=3, checkpoint_dir=d)
    assert latest_checkpoint(d).endswith("epoch3.zip")
    it.reset()

    # run 2 in a FRESH process-equivalent: resumes at epoch 3, runs 3 more
    b = _net()
    b2, ran = fit_with_checkpointing(b, it, epochs=6, checkpoint_dir=d)
    assert ran == 3
    assert latest_checkpoint(d).endswith("epoch6.zip")

    # a fully-complete run is a no-op
    c = _net()
    _, ran2 = fit_with_checkpointing(c, it, epochs=6, checkpoint_dir=d)
    assert ran2 == 0
    # restored params match the checkpointed ones
    from deeplearning4j_tpu.util.serialization import restore_model
    saved = restore_model(latest_checkpoint(d))
    np.testing.assert_allclose(np.asarray(c.params_flat()),
                               np.asarray(saved.params_flat()), atol=1e-6)


def _params(net):
    return np.asarray(net.params_flat())


def test_latest_checkpoint_skips_truncated_newest(tmp_path):
    """A truncated newest checkpoint (preemption mid-copy) must fall back
    to the previous one instead of being handed to restore_model."""
    net = _net()
    it, _, _ = _it()
    net.set_listeners(CheckpointListener(str(tmp_path), keep_last=5))
    net.fit(iterator=it, epochs=3)
    newest = os.path.join(str(tmp_path), "checkpoint_epoch3.zip")
    with open(newest, "r+b") as f:
        f.truncate(40)
    assert not is_valid_checkpoint(newest)
    assert latest_checkpoint(str(tmp_path)).endswith("epoch2.zip")
    # trust-the-newest escape hatch preserved
    assert latest_checkpoint(str(tmp_path), validate=False).endswith(
        "epoch3.zip")


def test_fit_with_checkpointing_falls_back_on_corrupt_newest(tmp_path):
    d = str(tmp_path / "ck")
    it, x, y = _it()
    a = _net()
    fit_with_checkpointing(a, it, epochs=3, checkpoint_dir=d, keep_last=5)
    it.reset()
    with open(os.path.join(d, "checkpoint_epoch3.zip"), "r+b") as f:
        f.truncate(40)
    # resume: epoch-3 save is damaged -> restart from epoch 2, rerun 4
    b = _net()
    b2, ran = fit_with_checkpointing(b, it, epochs=6, checkpoint_dir=d,
                                     keep_last=5)
    assert ran == 4
    assert latest_checkpoint(d).endswith("epoch6.zip")


class _RaiseAt(TrainingListener):
    """Simulated hard crash at a global iteration index."""

    class Boom(RuntimeError):
        pass

    def __init__(self, at):
        self.at = at

    def iteration_done(self, model, iteration, score):
        if iteration == self.at:
            raise self.Boom(f"crash at iteration {iteration}")


def test_mid_epoch_resume_does_not_replay_epoch(tmp_path):
    """every_n_iterations checkpoints + step_within_epoch in the manifest:
    a crash mid-epoch resumes at the exact step — bit-identical to an
    uninterrupted run, not a whole-epoch replay."""
    d = str(tmp_path / "ck")
    # ONE dataset, a fresh iterator object per run (a crashed run's
    # abandoned prefetcher must not share iterator state with the resume)
    _, x, y = _it()

    def fresh_it():
        return ListDataSetIterator(features=x, labels=y, batch_size=32)

    # uninterrupted baseline: 3 epochs of 4 batches (128/32)
    a = _net()
    a.fit(iterator=fresh_it(), epochs=3, async_prefetch=False)

    # crashed run: dies at global iteration 6 (step 3 of epoch 2)
    b = _net()
    b.set_listeners(_RaiseAt(6))
    with pytest.raises(_RaiseAt.Boom):
        fit_with_checkpointing(b, fresh_it(), epochs=3, checkpoint_dir=d,
                               every_n_iterations=2, keep_last=10)
    # newest checkpoint: 1 epoch done + 2 steps into epoch 2
    newest = latest_checkpoint(d)
    assert newest.endswith("epoch1_step2.zip")
    m = read_checkpoint_manifest(newest)
    assert (m["epochs_done"], m["step_within_epoch"]) == (1, 2)
    assert m["iterations_done"] == 6

    # fresh "process" resumes: must NOT replay epoch 2's first 2 steps
    c = _net()
    c.set_listeners()
    _, ran = fit_with_checkpointing(c, fresh_it(), epochs=3,
                                    checkpoint_dir=d,
                                    every_n_iterations=2, keep_last=10)
    assert ran == 2                      # partial epoch 2 + epoch 3
    assert c.iteration_count == 12       # 3 epochs x 4 batches, no replay
    np.testing.assert_array_equal(_params(a), _params(c))


def test_old_boundary_checkpoints_still_load(tmp_path):
    """A checkpoint without the new manifest keys (pre-mid-epoch format)
    is treated as an epoch-boundary save."""
    from deeplearning4j_tpu.util.serialization import write_model
    d = str(tmp_path)
    net = _net()
    net.iteration_count = 8              # 2 epochs x 4 batches
    write_model(net, os.path.join(d, "checkpoint_epoch2.zip"))
    it, _, _ = _it()
    b = _net()
    _, ran = fit_with_checkpointing(b, it, epochs=3, checkpoint_dir=d)
    assert ran == 1                      # resumes at the epoch boundary
    assert latest_checkpoint(d).endswith("epoch3.zip")


def test_prune_only_touches_checkpoints_older_than_last_completed(tmp_path):
    """Bugfix regression: pruning must only delete checkpoints strictly
    older than the last write THIS listener completed — a newer file
    (another process / an async writer mid-sequence) is neither counted
    against keep_last nor deleted."""
    d = str(tmp_path)
    for name in ("checkpoint_epoch1.zip", "checkpoint_epoch2.zip",
                 "checkpoint_epoch3.zip", "checkpoint_epoch3_step2.zip"):
        with open(os.path.join(d, name), "wb") as f:
            f.write(b"x")
    lst = CheckpointListener(d, keep_last=1)
    # before any completed write, prune is a no-op (it used to count the
    # foreign files and delete all but one)
    lst._prune()
    assert len(list_checkpoints(d)) == 4
    # we completed epoch 2: epoch 1 goes, epoch 2 is kept (keep_last=1),
    # the NEWER epoch-3 files (another writer's) are untouched
    lst._last_completed = (2, 0)
    lst._prune()
    names = sorted(os.path.basename(p) for p, _ in list_checkpoints(d))
    assert names == ["checkpoint_epoch2.zip", "checkpoint_epoch3.zip",
                     "checkpoint_epoch3_step2.zip"]


def test_mid_epoch_checkpoints_prune_with_boundaries(tmp_path):
    """Mixed boundary + mid-epoch saves order by (epoch, step) and prune
    oldest-first under keep_last."""
    net = _net()
    it, _, _ = _it()
    net.set_listeners(CheckpointListener(str(tmp_path), keep_last=3,
                                         every_n_iterations=2))
    # 2 epochs x 4 batches -> writes (0,2) (0,4) (1,0) (1,2) (1,4) (2,0);
    # keep_last=3 leaves the newest three in (epoch, step) order
    net.fit(iterator=it, epochs=2)
    names = sorted(os.path.basename(p) for p, _ in
                   list_checkpoints(str(tmp_path)))
    assert names == ["checkpoint_epoch1_step2.zip",
                     "checkpoint_epoch1_step4.zip",
                     "checkpoint_epoch2.zip"]


def test_profiler_listener_writes_trace(tmp_path):
    net = _net()
    it, _, _ = _it(64, 16)
    log_dir = str(tmp_path / "xprof")
    net.set_listeners(ProfilerListener(log_dir, start_iteration=1,
                                       n_iterations=2))
    net.fit(iterator=it, epochs=2)
    # a plugins/profile/<ts>/ dir with trace artifacts appears
    found = []
    for root, _, files in os.walk(log_dir):
        found.extend(files)
    assert found, "no profiler trace files written"
