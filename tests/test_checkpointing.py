"""Checkpoint-restart orchestration + profiler hookup (SURVEY.md §5.1/§5.3:
periodic checkpoints, resume-after-preemption, XProf trace capture)."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.util.checkpointing import (CheckpointListener,
                                                   ProfilerListener,
                                                   fit_with_checkpointing,
                                                   latest_checkpoint,
                                                   list_checkpoints)

R = np.random.default_rng(29)


def _net(seed=3):
    conf = (NeuralNetConfiguration(seed=seed, updater=Adam(5e-3), dtype="float32")
            .list(DenseLayer(n_in=5, n_out=12, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _it(n=128, bs=32):
    x = R.normal(size=(n, 5)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(-1) > 0).astype(int)]
    return ListDataSetIterator(features=x, labels=y, batch_size=bs), x, y


def test_checkpoint_listener_writes_and_prunes(tmp_path):
    net = _net()
    it, _, _ = _it()
    net.set_listeners(CheckpointListener(str(tmp_path), every_n_epochs=1,
                                         keep_last=2))
    net.fit(iterator=it, epochs=5)
    ckpts = list_checkpoints(str(tmp_path))
    assert [e for _, e in ckpts] == [4, 5]     # pruned to last 2
    assert latest_checkpoint(str(tmp_path)).endswith("checkpoint_epoch5.zip")


def test_fit_with_checkpointing_resumes(tmp_path):
    d = str(tmp_path / "ck")
    it, x, y = _it()

    # run 1: 3 of 6 epochs, then "preemption"
    a = _net()
    fit_with_checkpointing(a, it, epochs=3, checkpoint_dir=d)
    assert latest_checkpoint(d).endswith("epoch3.zip")
    it.reset()

    # run 2 in a FRESH process-equivalent: resumes at epoch 3, runs 3 more
    b = _net()
    b2, ran = fit_with_checkpointing(b, it, epochs=6, checkpoint_dir=d)
    assert ran == 3
    assert latest_checkpoint(d).endswith("epoch6.zip")

    # a fully-complete run is a no-op
    c = _net()
    _, ran2 = fit_with_checkpointing(c, it, epochs=6, checkpoint_dir=d)
    assert ran2 == 0
    # restored params match the checkpointed ones
    from deeplearning4j_tpu.util.serialization import restore_model
    saved = restore_model(latest_checkpoint(d))
    np.testing.assert_allclose(np.asarray(c.params_flat()),
                               np.asarray(saved.params_flat()), atol=1e-6)


def test_profiler_listener_writes_trace(tmp_path):
    net = _net()
    it, _, _ = _it(64, 16)
    log_dir = str(tmp_path / "xprof")
    net.set_listeners(ProfilerListener(log_dir, start_iteration=1,
                                       n_iterations=2))
    net.fit(iterator=it, epochs=2)
    # a plugins/profile/<ts>/ dir with trace artifacts appears
    found = []
    for root, _, files in os.walk(log_dir):
        found.extend(files)
    assert found, "no profiler trace files written"
