"""EvaluationBinary + EvaluationCalibration + EvaluationTools HTML export
(reference eval/EvaluationBinary.java, eval/EvaluationCalibration.java,
evaluation/EvaluationTools.java)."""
import numpy as np
import pytest

from deeplearning4j_tpu.eval import (EvaluationBinary, EvaluationCalibration,
                                     ROC, ROCBinary, calibration_chart_html,
                                     export_roc_charts, roc_chart_html)

R = np.random.default_rng(17)


def test_evaluation_binary_counts_and_metrics():
    e = EvaluationBinary()
    labels = np.array([[1, 0], [1, 1], [0, 0], [0, 1]])
    preds = np.array([[0.9, 0.2], [0.8, 0.4], [0.3, 0.6], [0.1, 0.7]])
    e.eval(labels, preds)
    # label 0: tp=2 tn=2 -> perfect
    assert e.accuracy(0) == 1.0 and e.f1(0) == 1.0
    assert e.matthews_correlation(0) == 1.0
    # label 1: preds>0.5 -> [0,0,1,1]; labels [0,1,0,1] -> tp=1 fp=1 tn=1 fn=1
    assert e.accuracy(1) == 0.5
    assert e.precision(1) == 0.5 and e.recall(1) == 0.5
    assert e.total_count(1) == 4
    assert "label_0" in e.stats()


def test_evaluation_binary_custom_threshold_and_mask():
    e = EvaluationBinary(decision_threshold=np.array([0.9, 0.1]))
    labels = np.array([[1, 1], [0, 0]])
    preds = np.array([[0.95, 0.2], [0.5, 0.05]])
    mask = np.array([[1, 1], [1, 0]])   # last entry of label 1 excluded
    e.eval(labels, preds, mask=mask)
    assert e.total_count(0) == 2
    assert e.total_count(1) == 1
    assert e.accuracy(0) == 1.0 and e.accuracy(1) == 1.0


def test_evaluation_binary_merge_and_timeseries():
    a, b = EvaluationBinary(), EvaluationBinary()
    l1 = (R.random((6, 3)) > 0.5).astype(float)
    p1 = R.random((6, 3))
    l2 = (R.random((4, 3)) > 0.5).astype(float)
    p2 = R.random((4, 3))
    a.eval(l1, p1)
    b.eval(l2, p2)
    a.merge(b)
    both = EvaluationBinary()
    both.eval(np.concatenate([l1, l2]), np.concatenate([p1, p2]))
    np.testing.assert_array_equal(a.tp, both.tp)
    np.testing.assert_array_equal(a.fn, both.fn)
    # [B,T,L] time series path
    ts = EvaluationBinary()
    ts.eval(l1.reshape(2, 3, 3), p1.reshape(2, 3, 3))
    flat = EvaluationBinary()
    flat.eval(l1, p1)
    np.testing.assert_array_equal(ts.tp, flat.tp)


def test_calibration_perfectly_calibrated():
    """Predictions drawn so P(label=1|p) == p: ECE should be near 0."""
    n = 20000
    p = R.random(n)
    y = (R.random(n) < p).astype(float)
    cal = EvaluationCalibration(reliability_bins=10)
    cal.eval(np.stack([1 - y, y], 1), np.stack([1 - p, p], 1))
    ece = cal.expected_calibration_error(1)
    assert ece < 0.02, ece
    mean_pred, frac_pos, counts = cal.reliability_diagram(1)
    assert counts.sum() == n
    np.testing.assert_allclose(mean_pred[counts > 100], frac_pos[counts > 100],
                               atol=0.05)


def test_calibration_overconfident_model_detected():
    n = 5000
    y = (R.random(n) < 0.5).astype(float)
    p = np.where(y > 0, 0.99, 0.01)           # overconfident but...
    wrong = R.random(n) < 0.3                 # ...wrong 30% of the time
    p = np.where(wrong, 1 - p, p)
    cal = EvaluationCalibration()
    cal.eval(np.stack([1 - y, y], 1), np.stack([1 - p, p], 1))
    assert cal.expected_calibration_error(1) > 0.2
    edges, counts = cal.residual_plot()
    assert counts.sum() == 2 * n


def test_html_exports(tmp_path):
    roc = ROC()
    y = (R.random(500) > 0.5).astype(float)
    s = np.clip(y * 0.6 + R.random(500) * 0.4, 0, 1)
    roc.eval(np.stack([1 - y, y], 1), np.stack([1 - s, s], 1))
    html = roc_chart_html(roc)
    assert "<svg" in html and "AUC=" in html
    path = str(tmp_path / "roc.html")
    export_roc_charts(path, roc)
    assert "<svg" in open(path).read()

    rb = ROCBinary()
    rb.eval((R.random((100, 3)) > 0.5).astype(float), R.random((100, 3)))
    assert "class 2" in roc_chart_html(rb, "per-label ROC")

    cal = EvaluationCalibration()
    cal.eval(np.stack([1 - y, y], 1), np.stack([1 - s, s], 1))
    chtml = calibration_chart_html(cal)
    assert "Reliability" in chtml and "Residual" in chtml


def test_roc_and_regression_merge():
    """Worker-side evals merge into the driver's (the Spark treeAggregate
    eval-merging capability; reference ROC.merge / RegressionEvaluation.merge)."""
    from deeplearning4j_tpu.eval import RegressionEvaluation, ROCMultiClass

    y1 = (R.random(300) > 0.5).astype(float)
    s1 = np.clip(y1 * 0.6 + R.random(300) * 0.4, 0, 1)
    y2 = (R.random(200) > 0.5).astype(float)
    s2 = np.clip(y2 * 0.6 + R.random(200) * 0.4, 0, 1)

    a, b, both = ROC(), ROC(), ROC()
    a.eval(np.stack([1 - y1, y1], 1), np.stack([1 - s1, s1], 1))
    b.eval(np.stack([1 - y2, y2], 1), np.stack([1 - s2, s2], 1))
    both.eval(np.stack([1 - np.concatenate([y1, y2]), np.concatenate([y1, y2])], 1),
              np.stack([1 - np.concatenate([s1, s2]), np.concatenate([s1, s2])], 1))
    a.merge(b)
    assert abs(a.calculate_auc() - both.calculate_auc()) < 1e-12

    ra, rb = ROCBinary(), ROCBinary()
    la, pa = (R.random((50, 3)) > 0.5).astype(float), R.random((50, 3))
    lb, pb = (R.random((70, 3)) > 0.5).astype(float), R.random((70, 3))
    ra.eval(la, pa)
    rb.eval(lb, pb)
    ra.merge(rb)
    whole = ROCBinary()
    whole.eval(np.concatenate([la, lb]), np.concatenate([pa, pb]))
    assert abs(ra.calculate_average_auc() - whole.calculate_average_auc()) < 1e-12

    mc1, mc2 = ROCMultiClass(), ROCMultiClass()
    lc = np.eye(3)[R.integers(0, 3, 80)]
    pc = R.random((80, 3))
    mc1.eval(lc[:30], pc[:30])
    mc2.eval(lc[30:], pc[30:])
    mc1.merge(mc2)
    whole_mc = ROCMultiClass()
    whole_mc.eval(lc, pc)
    assert abs(mc1.calculate_average_auc()
               - whole_mc.calculate_average_auc()) < 1e-12
    # mismatched class counts refuse to merge silently
    bad = ROCMultiClass()
    bad.eval(np.eye(5)[R.integers(0, 5, 10)], R.random((10, 5)))
    with pytest.raises(ValueError, match="output columns"):
        mc1.merge(bad)

    m1, m2 = RegressionEvaluation(), RegressionEvaluation()
    m1.eval(R.normal(size=(40, 2)), R.normal(size=(40, 2)))
    m2.eval(R.normal(size=(60, 2)), R.normal(size=(60, 2)))
    n_before = sum(len(l) for l in m1._labels)
    m1.merge(m2)
    assert sum(len(l) for l in m1._labels) == n_before + 60
    assert np.isfinite(m1.mean_squared_error(0))


def test_binary_eval_per_label_timeseries_mask():
    """A [B,T,L] per-label mask masks each label column independently
    (reference EvaluationBinary supports per-output masking; advisor r2)."""
    from deeplearning4j_tpu.eval.binary import EvaluationBinary
    B, T, L = 4, 6, 3
    labels = (R.random((B, T, L)) > 0.5).astype(np.float32)
    preds = R.random((B, T, L)).astype(np.float32)
    mask = (R.random((B, T, L)) > 0.3).astype(np.float32)

    e3 = EvaluationBinary()
    e3.eval(labels, preds, mask=mask)
    # equivalent flat evaluation with the same per-element mask
    ef = EvaluationBinary()
    ef.eval(labels.reshape(-1, L), preds.reshape(-1, L),
            mask=mask.reshape(-1, L))
    np.testing.assert_array_equal(e3.tp, ef.tp)
    np.testing.assert_array_equal(e3.fn, ef.fn)
    # total counted = number of unmasked elements per label
    totals = [e3.total_count(i) for i in range(L)]
    np.testing.assert_array_equal(totals, mask.reshape(-1, L).sum(0))
    # a bogus mask rank is rejected with a clear error
    import pytest
    with pytest.raises(ValueError, match="mask must be"):
        EvaluationBinary().eval(labels, preds, mask=np.ones((B,)))


def test_fine_tune_skips_frozen_layers_mln():
    """FineTuneConfiguration overrides must not touch frozen layers — same
    behavior as the CG transfer path (advisor r2)."""
    from deeplearning4j_tpu import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.transfer import (FineTuneConfiguration,
                                                TransferLearning)
    from deeplearning4j_tpu.optimize.updaters import Adam, Sgd

    conf = (NeuralNetConfiguration(seed=7, updater=Sgd(0.1))
            .list(DenseLayer(n_in=8, n_out=8, activation="relu", l2=0.25),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    new = (TransferLearning(net)
           .set_feature_extractor(0)
           .fine_tune_configuration(FineTuneConfiguration(updater=Adam(1e-3),
                                                          l2=0.01))
           .build())
    assert new.conf.layers[0].frozen
    assert new.conf.layers[0].l2 == 0.25        # frozen: untouched
    assert new.conf.layers[1].l2 == 0.01        # unfrozen: overridden
