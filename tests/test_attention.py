"""Ring attention (sequence parallelism) + SelfAttentionLayer — net-new
long-context capability (SURVEY.md §5.7: shardable sequence axis)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer
from deeplearning4j_tpu.nn.layers import RnnOutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.ring_attention import (attention,
                                                        ring_attention_sharded,
                                                        sequence_sharding)
from deeplearning4j_tpu.util.gradcheck import check_gradients

R = np.random.default_rng(41)


def _qkv(B=2, H=2, T=16, D=8):
    return (jnp.asarray(R.normal(size=(B, H, T, D)).astype(np.float32)),
            jnp.asarray(R.normal(size=(B, H, T, D)).astype(np.float32)),
            jnp.asarray(R.normal(size=(B, H, T, D)).astype(np.float32)))


def test_reference_attention_is_softmax():
    q, k, v = _qkv(T=6)
    out = attention(q, k, v)
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(8)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, v)
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full_attention(causal):
    """The 8-device ring with online softmax must equal single-device full
    attention on the gathered sequence."""
    mesh = make_mesh((8,), ("seq",))
    q, k, v = _qkv(B=2, H=2, T=32, D=8)
    want = np.asarray(attention(q, k, v, causal=causal))
    fn = ring_attention_sharded(mesh, "seq", causal=causal)
    sh = sequence_sharding(mesh, "seq")
    qs, ks, vs = (jax.device_put(t, sh) for t in (q, k, v))
    got = np.asarray(jax.device_get(fn(qs, ks, vs)))
    np.testing.assert_allclose(got, want, atol=2e-5)


def test_ring_attention_memory_layout_stays_sharded():
    mesh = make_mesh((8,), ("seq",))
    fn = ring_attention_sharded(mesh, "seq")
    sh = sequence_sharding(mesh, "seq")
    q, k, v = _qkv(T=64)
    out = fn(*(jax.device_put(t, sh) for t in (q, k, v)))
    assert out.sharding.spec == P(None, None, "seq", None)


def test_self_attention_layer_gradients():
    conf = (NeuralNetConfiguration(seed=3, updater=Sgd(0.1), dtype="float64")
            .list(SelfAttentionLayer(n_in=4, n_out=8, n_heads=2,
                                     activation="identity"),
                  RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(4, 5)).build())
    net = MultiLayerNetwork(conf).init()
    x = R.normal(size=(3, 5, 4))
    y = np.eye(2)[(x.sum(-1) > 0).astype(int)]
    assert check_gradients(net, x, y, subset=120, print_results=True)


def test_self_attention_layer_masking_and_causal():
    layer = SelfAttentionLayer(n_in=4, n_out=8, n_heads=2, causal=True,
                               activation="identity", weight_init="xavier")
    import jax
    params, _ = layer.init(jax.random.PRNGKey(0), None, jnp.float32)
    x = jnp.asarray(R.normal(size=(2, 6, 4)).astype(np.float32))
    out_full, _ = layer.apply(params, {}, x)
    # causal: output at step t must not change when the future changes
    x2 = x.at[:, 4:].set(0.0)
    out_trunc, _ = layer.apply(params, {}, x2)
    np.testing.assert_allclose(np.asarray(out_full[:, :4]),
                               np.asarray(out_trunc[:, :4]), atol=1e-5)
    # masking: padded keys don't affect earlier outputs
    mask = jnp.asarray([[1, 1, 1, 1, 0, 0], [1, 1, 1, 1, 1, 1]], jnp.float32)
    out_masked, _ = layer.apply(params, {}, x, mask=mask)
    assert np.isfinite(np.asarray(out_masked)).all()


def test_attention_classifier_trains():
    conf = (NeuralNetConfiguration(seed=9, updater=Adam(5e-3), dtype="float32")
            .list(SelfAttentionLayer(n_out=16, n_heads=4, activation="identity"),
                  RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(6, 10)).build())
    net = MultiLayerNetwork(conf).init()
    x = R.normal(size=(32, 10, 6)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(np.cumsum(x.sum(-1), 1) > 0).astype(int)]
    s0 = net.score(x, y)
    net.fit(x, y, epochs=20, batch_size=32)
    assert net.score(x, y) < s0


def test_ring_attention_is_trainable():
    """Gradients flow through the ring (lax.scan, not fori_loop): the
    sharded backward must match single-device full-attention gradients."""
    mesh = make_mesh((8,), ("seq",))
    q, k, v = _qkv(B=1, H=2, T=16, D=4)
    fn = ring_attention_sharded(mesh, "seq", causal=True)
    sh = sequence_sharding(mesh, "seq")

    def ring_loss(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def full_loss(q, k, v):
        return jnp.sum(attention(q, k, v, causal=True) ** 2)

    qs, ks, vs = (jax.device_put(t, sh) for t in (q, k, v))
    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(qs, ks, vs)
    g_full = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(jax.device_get(gr)),
                                   np.asarray(gf), atol=5e-5)


def test_layer_normalization_gradients_and_shapes():
    """LayerNormalization (net-new; required by transformer_lm): [B,T,F]
    and [B,F] shapes, f64 central-difference gradient check."""
    import numpy as np

    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import (DenseLayer, LayerNormalization,
                                              OutputLayer)
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.util.gradcheck import check_gradients

    R = np.random.default_rng(5)
    conf = (NeuralNetConfiguration(seed=1, updater=Sgd(0.1), dtype="float64")
            .list(DenseLayer(n_out=6, activation="tanh"),
                  LayerNormalization(),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    x = R.normal(size=(6, 4))
    y = np.eye(3)[R.integers(0, 3, 6)]
    assert check_gradients(net, x, y, print_results=True)
    # normalization actually happened
    ln = LayerNormalization(n_out=8)
    p, _ = ln.init(jax.random.PRNGKey(0), InputType.feed_forward(8),
                   jnp.float64)
    z = jnp.asarray(R.normal(size=(3, 5, 8)) * 10 + 4)
    out, _ = ln.apply(p, {}, z)
    np.testing.assert_allclose(np.asarray(out.mean(-1)), 0.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out.std(-1)), 1.0, atol=1e-3)


def test_transformer_lm_zoo_model_trains():
    """The transformer_lm zoo model builds, serde-round-trips, and learns
    the shift-by-one task (flash kernels on TPU; XLA fallback here)."""
    import numpy as np

    from deeplearning4j_tpu.models import transformer_lm
    from deeplearning4j_tpu.nn.conf.graph_conf import (
        ComputationGraphConfiguration)
    from deeplearning4j_tpu.nn.graph.graph import ComputationGraph

    from deeplearning4j_tpu.optimize.updaters import Adam as _Adam
    V, T, B = 12, 32, 8
    net = transformer_lm(vocab_size=V, d_model=32, n_heads=2, n_blocks=2,
                        max_length=T, updater=_Adam(3e-3)).init()
    r = np.random.default_rng(0)
    ids = r.integers(0, V, (B, T))
    x = np.eye(V, dtype=np.float32)[ids]
    y = np.eye(V, dtype=np.float32)[np.roll(ids, 1, axis=1)]
    assert np.asarray(net.output(x)).shape == (B, T, V)
    s0 = net.score(x, y)
    net.fit(x, y, epochs=60)
    assert net.score(x, y) < 0.5 * s0
    # config JSON round-trip preserves the whole block structure
    conf2 = ComputationGraphConfiguration.from_json(net.conf.to_json())
    net2 = ComputationGraph(conf2).init()
    assert net2.num_params() == net.num_params()
    # position-awareness: swapping two tokens in the PREFIX must change the
    # prediction at a later step (a position-blind decoder could not tell)
    xa = x[:1].copy()
    xb = xa.copy()
    xb[0, [2, 5]] = xb[0, [5, 2]]
    if not np.allclose(xa, xb):     # tokens actually differ at those slots
        oa = np.asarray(net.output(xa))[0, 10]
        ob = np.asarray(net.output(xb))[0, 10]
        assert not np.allclose(oa, ob, atol=1e-6), \
            "decoder is position-blind"


@pytest.mark.slow
def test_transformer_lm_token_input_trains():
    """token_input=True feeds [B,T] int ids through the
    EmbeddingSequenceLayer gather and learns the same shift-by-one task
    (the TPU-first input path used by the transformer-LM bench row).

    Slow lane (tier-1 budget): the token-input path is trained in tier-1
    by tests/test_tensor_parallel.py's mesh-parity fits and decoded all
    through tests/test_generation.py; the learns-shift-by-one pin stays
    via test_transformer_lm_zoo_model_trains (one-hot path)."""
    import numpy as np

    from deeplearning4j_tpu.models import transformer_lm
    from deeplearning4j_tpu.nn.conf.graph_conf import (
        ComputationGraphConfiguration)
    from deeplearning4j_tpu.nn.graph.graph import ComputationGraph
    from deeplearning4j_tpu.optimize.updaters import Adam as _Adam

    V, T, B = 12, 32, 8
    net = transformer_lm(vocab_size=V, d_model=32, n_heads=2, n_blocks=2,
                         max_length=T, updater=_Adam(3e-3),
                         token_input=True).init()
    r = np.random.default_rng(0)
    ids = r.integers(0, V, (B, T)).astype(np.int32)
    y = np.eye(V, dtype=np.float32)[np.roll(ids, 1, axis=1)]
    assert np.asarray(net.output(ids)).shape == (B, T, V)
    s0 = net.score(ids, y)
    net.fit(ids, y, epochs=60)
    assert net.score(ids, y) < 0.5 * s0
    # serde round-trip preserves the structure
    conf2 = ComputationGraphConfiguration.from_json(net.conf.to_json())
    net2 = ComputationGraph(conf2).init()
    assert net2.num_params() == net.num_params()
    # cross-path invariant: the gather embed carries V*d weights but no
    # bias, so it sits exactly d_model params under the one-hot Dense path
    onehot = transformer_lm(vocab_size=V, d_model=32, n_heads=2, n_blocks=2,
                            max_length=T, token_input=False).init()
    assert net.num_params() == onehot.num_params() - 32


# non-causal variant in the slow lane (tier-1 budget): the causal case is
# the production LM path and keeps the fused-vs-full contract pinned here
@pytest.mark.parametrize("causal", [
    pytest.param(False, marks=pytest.mark.slow), True])
def test_fused_ring_matches_full_attention(causal):
    """The Pallas carry-emitting ring (flash_block_update per hop +
    lax.switch causality) must equal single-device full attention —
    forward AND gradients (the custom_vjp runs the FlashAttention-2
    per-hop backward with rotating dk/dv accumulators)."""
    from deeplearning4j_tpu.ops.pallas_attention import fused_ring_applicable

    mesh = make_mesh((8,), ("seq",))
    T, D = 1024, 64
    assert fused_ring_applicable(T // 8, D, jnp.float32)
    r = np.random.default_rng(7)
    q, k, v = (jnp.asarray(r.normal(size=(1, 2, T, D)) * 0.2, jnp.float32)
               for _ in range(3))
    want = np.asarray(attention(q, k, v, causal=causal))
    fn = ring_attention_sharded(mesh, "seq", causal=causal, use_fused=True)
    sh = sequence_sharding(mesh, "seq")
    qs, ks, vs = (jax.device_put(t, sh) for t in (q, k, v))
    got = np.asarray(jax.device_get(fn(qs, ks, vs)))
    np.testing.assert_allclose(got, want, atol=2e-5)

    def ring_loss(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def full_loss(q, k, v):
        return jnp.sum(attention(q, k, v, causal=causal) ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(qs, ks, vs)
    g_full = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for name, gr, gf in zip("qkv", g_ring, g_full):
        rel = (np.max(np.abs(np.asarray(jax.device_get(gr)) - np.asarray(gf)))
               / (np.max(np.abs(np.asarray(gf))) + 1e-9))
        assert rel < 1e-4, (name, rel)


def test_fused_ring_auto_probe_engages():
    """use_fused=None auto-selects the fused body exactly when the local
    block qualifies (helper-seam contract)."""
    from deeplearning4j_tpu.ops.pallas_attention import fused_ring_applicable
    assert fused_ring_applicable(128, 64, jnp.float32)
    assert fused_ring_applicable(256, 128, jnp.bfloat16)
    assert not fused_ring_applicable(100, 64, jnp.float32)   # t_local % 128
    assert not fused_ring_applicable(128, 80, jnp.float32)   # odd head dim
    # the auto path produces the same numbers as the XLA ring
    mesh = make_mesh((8,), ("seq",))
    r = np.random.default_rng(3)
    q, k, v = (jnp.asarray(r.normal(size=(1, 1, 1024, 64)) * 0.2, jnp.float32)
               for _ in range(3))
    sh = sequence_sharding(mesh, "seq")
    qs, ks, vs = (jax.device_put(t, sh) for t in (q, k, v))
    auto = ring_attention_sharded(mesh, "seq", causal=True)
    xla = ring_attention_sharded(mesh, "seq", causal=True, use_fused=False)
    np.testing.assert_allclose(np.asarray(jax.device_get(auto(qs, ks, vs))),
                               np.asarray(jax.device_get(xla(qs, ks, vs))),
                               atol=2e-5)


def test_use_fused_explicit_misuse_is_a_targeted_error():
    """Regression (ADVICE r5): forcing use_fused=True on an ineligible
    local block must raise a targeted error naming t_local and the
    128-multiple constraint at the misuse site — not a confusing
    'T not a multiple of 128' from inside the Pallas block sizing."""
    mesh = make_mesh((2,), ("seq",), jax.devices()[:2])
    fn = ring_attention_sharded(mesh, "seq", causal=True, use_fused=True)
    q, k, v = _qkv(B=1, H=2, T=64, D=64)     # t_local = 32: not 128-aligned
    sh = sequence_sharding(mesh, "seq")
    with pytest.raises(ValueError, match=r"t_local.*multiple of 128"):
        fn(*(jax.device_put(t, sh) for t in (q, k, v)))


def test_fused_ring_zero_mass_row_degrades_to_zero_not_nan(monkeypatch):
    """Regression (ADVICE r5): a q row that accumulated NO probability
    mass (every hop skipped — a future key_mask case) must normalize to
    zeros via the epsilon guard, matching the XLA ring body, instead of
    emitting 0/0 NaN. Simulated by stubbing the hop kernel to a no-op."""
    from deeplearning4j_tpu.ops import pallas_attention as pa
    from deeplearning4j_tpu.parallel import ring_attention as ra
    from deeplearning4j_tpu.parallel.mesh import shard_map

    monkeypatch.setattr(pa, "flash_block_update",
                        lambda acc, m, l, q, k, v, **kw: (acc, m, l))
    mesh = make_mesh((2,), ("seq",), jax.devices()[:2])
    spec = P(None, "seq", None)

    def body(q3, k3, v3):
        o, _ = ra._ring_fused_fwd(q3, k3, v3, "seq", 2, False, 0.125)
        return o

    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    q3 = jnp.asarray(R.normal(size=(2, 256, 64)).astype(np.float32))
    out = np.asarray(jax.device_get(fn(q3, q3, q3)))
    assert np.all(np.isfinite(out)), "zero-mass rows produced NaN/inf"
    np.testing.assert_array_equal(out, np.zeros_like(out))
