"""tools/fleet_report.py: folding fleet /metrics snapshots into a report.

Stdlib-only CLI (no jax import), same stance as tools/perf_report.py —
tested on fake snapshots shaped like FleetHTTPServer's GET /metrics:
per-replica fold, aggregate hit rate weighting, later-wins merge across
snapshot files, text/JSON rendering, and the bad-input exit code.
"""
import json
import os
import sys


def _tool():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools import fleet_report
    return fleet_report


def _snap():
    return {
        "policy": "affinity", "block_len": 16,
        "requests": 40, "retries": 2, "streams_lost": 1,
        "replica_deaths": 1, "rejected": 0,
        "affinity": {"entries": 6, "capacity": 8192,
                     "entries_per_replica": {"f0": 4, "f1": 2}},
        "replicas": {
            "f0": {"id": "f0", "state": "ready", "restarts": 0,
                   "consecutive_failures": 0, "forwarded": 30,
                   "steering": {"queue_depth": 2, "in_flight": 1,
                                "slot_occupancy": 0.5,
                                "block_pool_free_frac": 0.8,
                                "prefix_hit_rate": 0.9,
                                "prefix_lookups": 30}},
            "f1": {"id": "f1", "state": "dead", "restarts": 1,
                   "consecutive_failures": 3, "forwarded": 10,
                   "steering": {"queue_depth": 0, "in_flight": 0,
                                "slot_occupancy": 0.0,
                                "block_pool_free_frac": 1.0,
                                "prefix_hit_rate": 0.3,
                                "prefix_lookups": 10}},
        },
        "replica_metrics": {
            "f0": {"generation": {"lm": {"ttft_ms": {"p50": 12.0,
                                                     "p99": 40.0}}}},
        },
    }


def test_fold_rows_totals_and_aggregate_hit_rate():
    fr = _tool()
    report = fr.fold(_snap())
    rows = {r["id"]: r for r in report["rows"]}
    assert rows["f0"]["hit_rate"] == 0.9
    assert rows["f0"]["ttft_p50_ms"] == 12.0
    assert rows["f1"]["ttft_p99_ms"] is None
    t = report["totals"]
    assert t["replicas"] == 2 and t["ready"] == 1
    assert t["forwarded"] == 40 and t["queue"] == 2
    assert t["restarts"] == 1
    # request-weighted: (0.9*30 + 0.3*10) / 40
    assert t["aggregate_hit_rate"] == 0.75
    assert report["counters"]["retries"] == 2


def test_merge_later_snapshot_wins_per_replica():
    fr = _tool()
    before = _snap()
    after = _snap()
    after["replicas"] = {"f1": {**before["replicas"]["f1"],
                                "state": "ready", "restarts": 2}}
    merged = fr.merge_snapshots([before, after])
    assert set(merged["replicas"]) == {"f0", "f1"}
    assert merged["replicas"]["f1"]["state"] == "ready"
    assert merged["replicas"]["f1"]["restarts"] == 2
    assert merged["replicas"]["f0"]["state"] == "ready"


def test_render_is_one_aligned_table():
    fr = _tool()
    out = fr.render(fr.fold(_snap()))
    assert "policy=affinity" in out
    lines = out.splitlines()
    assert any(l.lstrip().startswith("f0") for l in lines)
    assert any("TOTAL" in l for l in lines)
    assert any("retries=2" in l for l in lines)
    assert "affinity map: 6/8192" in out and "f0:4" in out


def test_main_text_json_and_merge(tmp_path, capsys):
    fr = _tool()
    p1 = tmp_path / "a.json"
    p1.write_text(json.dumps(_snap()))
    assert fr.main([str(p1)]) == 0
    assert "TOTAL" in capsys.readouterr().out
    assert fr.main([str(p1), "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["totals"]["aggregate_hit_rate"] == 0.75
    # two files merge
    p2 = tmp_path / "b.json"
    snap2 = _snap()
    snap2["replicas"]["f1"]["state"] = "ready"
    p2.write_text(json.dumps(snap2))
    assert fr.main([str(p1), str(p2), "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["totals"]["ready"] == 2


def test_main_rejects_bad_input(tmp_path, capsys):
    fr = _tool()
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"not": "a snapshot"}))
    assert fr.main([str(bad)]) == 2
    assert "fleet_report" in capsys.readouterr().err
    assert fr.main([str(tmp_path / "missing.json")]) == 2
    capsys.readouterr()


def test_tool_stays_importable_without_the_package():
    """Same discipline as perf_report: operators run this against a prod
    dump on a box with no jax — the module must not import the package."""
    import ast
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "fleet_report.py")
    tree = ast.parse(open(path).read())
    mods = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            mods |= {a.name.split(".")[0] for a in node.names}
        elif isinstance(node, ast.ImportFrom) and node.module:
            mods.add(node.module.split(".")[0])
    assert "deeplearning4j_tpu" not in mods
    assert "jax" not in mods and "numpy" not in mods
