"""Test config: force an 8-device virtual CPU platform BEFORE jax import.

Mirrors the reference's test stance (SURVEY.md §4): the CPU backend is the
"fake device" for all tests; multi-device semantics are exercised via
xla_force_host_platform_device_count=8 (the analogue of Spark local[n]).
"""
import os

# Force-override: the sandbox presets JAX_PLATFORMS=axon (the real TPU) and
# its sitecustomize imports jax at interpreter startup, so the env var has
# already been latched — jax.config.update is the reliable override. Tests
# must run on the virtual 8-device CPU platform (SURVEY.md §4: the analogue
# of the reference's Spark local[n] testing).
os.environ["JAX_PLATFORMS"] = "cpu"
# Parity tests exercise the fused Pallas LSTM/attention via the interpreter
# on CPU; production CPU runs take the (much faster) XLA fallbacks instead.
os.environ.setdefault("DL4J_TPU_FUSED_LSTM_INTERPRET", "1")
os.environ.setdefault("DL4J_TPU_FUSED_ATTN_INTERPRET", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (import after env setup)
import numpy as np  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# x64 for gradient checks (reference forces DOUBLE, GradientCheckUtil.java:92-97).
# Regular tests pass explicit float32 dtypes, so they are unaffected.
jax.config.update("jax_enable_x64", True)


@pytest.fixture
def rng():
    return np.random.default_rng(12345)
