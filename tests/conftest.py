"""Test config: force an 8-device virtual CPU platform BEFORE jax import.

Mirrors the reference's test stance (SURVEY.md §4): the CPU backend is the
"fake device" for all tests; multi-device semantics are exercised via
xla_force_host_platform_device_count=8 (the analogue of Spark local[n]).
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (import after env setup)
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(12345)
