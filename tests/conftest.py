"""Test config: force an 8-device virtual CPU platform BEFORE jax import.

Mirrors the reference's test stance (SURVEY.md §4): the CPU backend is the
"fake device" for all tests; multi-device semantics are exercised via
xla_force_host_platform_device_count=8 (the analogue of Spark local[n]).
"""
import os
import tempfile

# Force-override: the sandbox presets JAX_PLATFORMS=axon (the real TPU) and
# its sitecustomize imports jax at interpreter startup, so the env var has
# already been latched — jax.config.update is the reliable override. Tests
# must run on the virtual 8-device CPU platform (SURVEY.md §4: the analogue
# of the reference's Spark local[n] testing).
os.environ["JAX_PLATFORMS"] = "cpu"
# Parity tests exercise the fused Pallas LSTM/attention via the interpreter
# on CPU; production CPU runs take the (much faster) XLA fallbacks instead.
os.environ.setdefault("DL4J_TPU_FUSED_LSTM_INTERPRET", "1")
os.environ.setdefault("DL4J_TPU_FUSED_ATTN_INTERPRET", "1")
os.environ.setdefault("DL4J_TPU_FUSED_ENCODE_INTERPRET", "1")
# Isolate the autotune decision cache from any user-level file: pinned
# block-size expectations (e.g. attention _blocks defaults) must not be
# overridden by stray decisions cached on this machine.
os.environ.setdefault(
    "DL4J_TPU_AUTOTUNE_CACHE",
    os.path.join(tempfile.mkdtemp(prefix="dl4j-autotune-"), "autotune.json"))
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (import after env setup)
import numpy as np  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# x64 for gradient checks (reference forces DOUBLE, GradientCheckUtil.java:92-97).
# Regular tests pass explicit float32 dtypes, so they are unaffected.
jax.config.update("jax_enable_x64", True)


@pytest.fixture
def rng():
    return np.random.default_rng(12345)


@pytest.fixture(autouse=True, scope="session")
def _flightrec_sandbox(tmp_path_factory):
    """Point the process-wide flight recorder at a session tmp dir: fault
    injections and failure-path tests dump black boxes as a side effect,
    and those must never land in the working tree."""
    from deeplearning4j_tpu.telemetry import configure_flight_recorder
    # small capacity: chaos/fault tests dump as a side effect dozens of
    # times across the suite; 256-event tails keep that cheap
    configure_flight_recorder(
        directory=str(tmp_path_factory.mktemp("flightrec")),
        capacity=256)


def pytest_collection_modifyitems(config, items):
    """DL4J_TPU_TEST_REVERSE=1 reverses collection order — the harness for
    verifying the suite is order-independent (no test may depend on state
    another test leaked)."""
    if os.environ.get("DL4J_TPU_TEST_REVERSE") == "1":
        items.reverse()


@pytest.fixture(autouse=True)
def _reset_module_rng(request):
    """Kill the test-ordering flake at its root: many modules share a
    module-level ``R = np.random.default_rng(seed)`` — MUTABLE state, so a
    test's data depended on how many draws earlier-running tests made, and
    any deselection / collection change / reordering shifted the stream
    (the statistical assertions downstream then saw different data).
    Restore each module's generator to its import-time state before every
    test: a test's data becomes a function of the test alone, in any
    order. (Import-time state is captured at the module's first-run test —
    draws only ever happen inside tests, so it equals the seeded state
    regardless of which test runs first.)"""
    import copy
    mod = getattr(request.node, "module", None)
    gen = getattr(mod, "R", None)
    if isinstance(gen, np.random.Generator):
        saved = getattr(mod, "_R_import_state", None)
        if saved is None:
            mod._R_import_state = copy.deepcopy(gen.bit_generator.state)
        else:
            gen.bit_generator.state = copy.deepcopy(saved)
    yield
