"""Pipeline + expert parallelism (net-new mesh-axis capabilities; SURVEY.md
§2.2 extension beyond the reference's DP-only story)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.expert_parallel import (expert_parallel_apply,
                                                         expert_sharding)
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.pipeline import (pipeline_apply,
                                                  stack_stage_params,
                                                  stage_sharding)

R = np.random.default_rng(47)


def _block(params, x):
    return jnp.tanh(x @ params["W"] + params["b"])


def _make_stage_params(n, d, scale=0.4):
    return [{"W": jnp.asarray(R.normal(size=(d, d)).astype(np.float32) * scale),
             "b": jnp.asarray(R.normal(size=(d,)).astype(np.float32) * 0.1)}
            for _ in range(n)]


def test_pipeline_matches_sequential():
    """8-stage pipeline over microbatches == applying the 8 blocks in
    sequence to each microbatch."""
    mesh = make_mesh((8,), ("pipe",))
    d, n_micro, mb = 6, 5, 4
    stages = _make_stage_params(8, d)
    stacked = jax.device_put(stack_stage_params(stages),
                             stage_sharding(mesh, "pipe"))
    x = jnp.asarray(R.normal(size=(n_micro, mb, d)).astype(np.float32))

    fn = pipeline_apply(_block, mesh, "pipe")
    got = np.asarray(jax.device_get(fn(stacked, x)))

    want = np.asarray(x)
    for p in stages:
        want = np.tanh(want @ np.asarray(p["W"]) + np.asarray(p["b"]))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_pipeline_is_differentiable():
    """jax.grad through the pipelined forward equals grad of the sequential
    composition (scan+ppermute transpose = the GPipe backward schedule)."""
    mesh = make_mesh((8,), ("pipe",))
    d = 4
    stages = _make_stage_params(8, d)
    stacked = jax.device_put(stack_stage_params(stages),
                             stage_sharding(mesh, "pipe"))
    x = jnp.asarray(R.normal(size=(3, 2, d)).astype(np.float32))
    fn = pipeline_apply(_block, mesh, "pipe")

    g_pipe = jax.grad(lambda p: jnp.sum(fn(p, x) ** 2))(stacked)

    def seq_loss(plist):
        y = x
        for p in plist:
            y = jnp.tanh(y @ p["W"] + p["b"])
        return jnp.sum(y ** 2)

    g_seq = jax.grad(seq_loss)(stages)
    for i in range(8):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(g_pipe["W"]))[i],
            np.asarray(g_seq[i]["W"]), atol=3e-4)


def test_pipeline_parameters_are_sharded():
    mesh = make_mesh((8,), ("pipe",))
    stacked = jax.device_put(stack_stage_params(_make_stage_params(8, 4)),
                             stage_sharding(mesh, "pipe"))
    # each device holds exactly one stage's W
    assert stacked["W"].sharding.spec[0] == "pipe"
    shard = stacked["W"].addressable_shards[0]
    assert shard.data.shape == (1, 4, 4)


def test_expert_parallel_matches_dense_top1():
    """8-expert EP == dense per-token top-1 expert evaluation (capacity
    large enough that nothing is dropped)."""
    mesh = make_mesh((8,), ("expert",))
    d, N = 6, 32
    experts = _make_stage_params(8, d)
    stacked = jax.device_put(stack_stage_params(experts),
                             expert_sharding(mesh, "expert"))
    tokens = jnp.asarray(R.normal(size=(N, d)).astype(np.float32))
    logits = jnp.asarray(R.normal(size=(N, 8)).astype(np.float32))

    fn = expert_parallel_apply(_block, mesh, "expert", capacity_factor=8.0)
    got = np.asarray(jax.device_get(fn(stacked, tokens, logits)))

    probs = np.asarray(jax.nn.softmax(logits, -1))
    choice = probs.argmax(-1)
    gate = probs.max(-1)
    want = np.zeros((N, d), np.float32)
    for i in range(N):
        e = experts[choice[i]]
        want[i] = np.tanh(np.asarray(tokens[i]) @ np.asarray(e["W"])
                          + np.asarray(e["b"])) * gate[i]
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_expert_parallel_capacity_drops_overflow():
    """With capacity 1 per expert and all tokens routed to expert 0, only
    the first token gets computed; the rest pass through as zeros."""
    mesh = make_mesh((8,), ("expert",))
    d, N = 4, 8
    experts = _make_stage_params(8, d)
    stacked = jax.device_put(stack_stage_params(experts),
                             expert_sharding(mesh, "expert"))
    tokens = jnp.asarray(R.normal(size=(N, d)).astype(np.float32))
    logits = jnp.full((N, 8), -10.0).at[:, 0].set(10.0)  # everyone -> expert 0

    fn = expert_parallel_apply(_block, mesh, "expert", capacity_factor=0.125)
    out = np.asarray(jax.device_get(fn(stacked, tokens, jnp.asarray(logits))))
    assert np.abs(out[0]).sum() > 0          # first token served
    np.testing.assert_allclose(out[1:], 0.0, atol=1e-7)  # overflow dropped


def test_expert_parallel_top2_matches_dense():
    """Top-2 routing (GShard): with ample capacity the output is the
    pair-normalized gate-weighted sum of both chosen experts."""
    mesh = make_mesh((8,), ("expert",))
    d, N = 6, 32
    experts = _make_stage_params(8, d)
    stacked = jax.device_put(stack_stage_params(experts),
                             expert_sharding(mesh, "expert"))
    tokens = jnp.asarray(R.normal(size=(N, d)).astype(np.float32))
    logits = jnp.asarray(R.normal(size=(N, 8)).astype(np.float32))

    fn = expert_parallel_apply(_block, mesh, "expert", capacity_factor=8.0,
                               top_k=2)
    got = np.asarray(jax.device_get(fn(stacked, tokens, logits)))

    probs = np.asarray(jax.nn.softmax(logits, -1))
    want = np.zeros((N, d), np.float32)
    for i in range(N):
        order = np.argsort(-probs[i])[:2]
        p = probs[i][order]
        w = p / p.sum()
        for c, e_idx in enumerate(order):
            e = experts[e_idx]
            want[i] += w[c] * np.tanh(np.asarray(tokens[i]) @ np.asarray(e["W"])
                                      + np.asarray(e["b"]))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_expert_parallel_top2_reroutes_on_overflow():
    """Capacity re-routing: a token whose first choice overflowed is served
    by its second choice with FULL weight (VERDICT r2 weak #8)."""
    mesh = make_mesh((8,), ("expert",))
    d, N = 4, 4
    experts = _make_stage_params(8, d)
    stacked = jax.device_put(stack_stage_params(experts),
                             expert_sharding(mesh, "expert"))
    tokens = jnp.asarray(R.normal(size=(N, d)).astype(np.float32))
    # everyone's first choice is expert 0; second choices are distinct
    logits = np.full((N, 8), -10.0, np.float32)
    logits[:, 0] = 10.0
    for i in range(N):
        logits[i, i + 1] = 9.0
    # cap = ceil(1.0 * 2 * 4 / 8) = 1: expert 0 fits ONE token
    fn = expert_parallel_apply(_block, mesh, "expert", capacity_factor=1.0,
                               top_k=2)
    out = np.asarray(jax.device_get(fn(stacked, tokens, jnp.asarray(logits))))

    def dense(e_idx, t):
        e = experts[e_idx]
        return np.tanh(np.asarray(t) @ np.asarray(e["W"]) + np.asarray(e["b"]))

    # token 0: both choices fit -> pair-normalized blend of experts 0 and 1
    p = np.asarray(jax.nn.softmax(jnp.asarray(logits[0]), -1))
    w0, w1 = p[0] / (p[0] + p[1]), p[1] / (p[0] + p[1])
    np.testing.assert_allclose(out[0], w0 * dense(0, tokens[0])
                               + w1 * dense(1, tokens[0]), atol=1e-5)
    # tokens 1..3: first choice overflowed -> second expert serves with
    # weight 1.0 (re-routing, not a 50% haircut)
    for i in range(1, N):
        np.testing.assert_allclose(out[i], dense(i + 1, tokens[i]), atol=1e-5)


def test_expert_parallel_router_gets_gradient():
    mesh = make_mesh((8,), ("expert",))
    d, N = 4, 16
    stacked = jax.device_put(stack_stage_params(_make_stage_params(8, d)),
                             expert_sharding(mesh, "expert"))
    tokens = jnp.asarray(R.normal(size=(N, d)).astype(np.float32))
    logits = jnp.asarray(R.normal(size=(N, 8)).astype(np.float32))
    for k in (1, 2):
        fn = expert_parallel_apply(_block, mesh, "expert",
                                   capacity_factor=8.0, top_k=k)
        g = jax.grad(lambda l: jnp.sum(fn(stacked, tokens, l) ** 2))(logits)
        assert float(jnp.abs(g).max()) > 0, f"no router grad for top_k={k}"


def test_load_balancing_loss():
    from deeplearning4j_tpu.parallel.expert_parallel import load_balancing_loss
    N, E = 64, 8
    uniform = jnp.zeros((N, E))
    skewed = jnp.full((N, E), -10.0).at[:, 0].set(10.0)
    lb_u = float(load_balancing_loss(uniform, top_k=2))
    lb_s = float(load_balancing_loss(skewed, top_k=2))
    assert lb_s > lb_u
    assert abs(lb_u - 2.0) < 0.3  # top-2 uniform: E * sum_e (2/E)*(1/E) = 2
