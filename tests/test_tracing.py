"""Distributed request tracing (ISSUE 13 tentpole): TraceContext
propagation from HTTP ingress through admission, batching, prefill and
every decode step, span/event trace-id stamping, explicit cross-thread
handoff, and the per-request reconstruction tools.

Acceptance pinned here:
- a generation request submitted over HTTP with ``X-Trace-Id`` yields
  spans/events carrying that id across ingress, admission, prefill and
  every decode step it participated in, reconstructable by
  tools/trace2timeline.py (and the header is echoed on the response);
- span-stack integrity: exception unwinding restores the parent span,
  and cross-thread handoff via the context helpers never attributes a
  child to the wrong parent (threaded stress);
- tools/trace2summary.py accepts gzipped traces and --trace-id filters;
- the tracing+watchdog-enabled fit and serving bench variants stay <5%
  (bench_smoke guard).
"""
import gzip
import json
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.telemetry import (MetricsRegistry, adopt,
                                          current_span_path,
                                          current_trace_context, event,
                                          handoff, new_trace_context, span,
                                          use_trace_context)


@pytest.fixture
def fresh_registry():
    reg = MetricsRegistry(enabled=True)
    prev = telemetry.set_registry(reg)
    try:
        yield reg
    finally:
        telemetry.set_registry(prev)


# ------------------------------------------------------------- context core
def test_trace_context_normalizes_and_validates_header_ids():
    ctx = new_trace_context("AABB-CCDD-00112233445566778899aabbcc")
    assert ctx.trace_id == "aabbccdd00112233445566778899aabbcc"
    # junk (non-hex / too short) -> fresh 128-bit id, never echoed junk
    for bad in ("not hex!", "abc", "", None, "<script>"):
        ctx = new_trace_context(bad)
        assert len(ctx.trace_id) == 32
        assert all(c in "0123456789abcdef" for c in ctx.trace_id)
    a, b = new_trace_context(), new_trace_context()
    assert a.trace_id != b.trace_id
    assert a.span_id != b.span_id


def test_use_trace_context_scopes_and_restores():
    assert current_trace_context() is None
    ctx = new_trace_context()
    with use_trace_context(ctx):
        assert current_trace_context() is ctx
        inner = new_trace_context()
        with use_trace_context(inner):
            assert current_trace_context() is inner
        assert current_trace_context() is ctx
        with use_trace_context(None):        # explicit deactivation
            assert current_trace_context() is None
        assert current_trace_context() is ctx
    assert current_trace_context() is None


# ----------------------------------------------------------- span stamping
def test_spans_and_events_stamp_active_trace_id(fresh_registry):
    reg = fresh_registry
    ctx = new_trace_context()
    with use_trace_context(ctx):
        with span("work", k=1):
            event("milestone", n=3)
    with span("untraced"):
        pass
    by_name = {e["name"]: e for e in reg.trace_events()}
    assert by_name["work"]["args"]["trace_id"] == ctx.trace_id
    assert by_name["milestone"]["args"]["trace_id"] == ctx.trace_id
    assert by_name["milestone"]["args"]["path"] == "work"
    assert by_name["milestone"]["ph"] == "i"
    assert "trace_id" not in by_name["untraced"]["args"]


def test_event_explicit_trace_id_override_and_disabled_noop(fresh_registry):
    reg = fresh_registry
    with use_trace_context(new_trace_context()):
        event("multi", trace_id="feedbeef", slot=2)
    assert reg.trace_events()[0]["args"]["trace_id"] == "feedbeef"
    reg.enabled = False
    event("nothing")
    reg.enabled = True
    assert len(reg.trace_events()) == 1


def test_record_external_span_stamps_trace_id(fresh_registry):
    from deeplearning4j_tpu.telemetry import record_external_span
    ctx = new_trace_context()
    with use_trace_context(ctx):
        record_external_span("collective", 1.5, cat="collective", bucket=0)
    ev = fresh_registry.trace_events()[0]
    assert ev["args"]["trace_id"] == ctx.trace_id


# ----------------------------------------------- span-stack integrity (sat)
def test_exception_unwinding_restores_parent_span(fresh_registry):
    with span("outer"):
        try:
            with span("inner"):
                raise RuntimeError("boom")
        except RuntimeError:
            pass
        assert current_span_path() == "outer"
        with span("after"):
            assert current_span_path() == "outer/after"
    assert current_span_path() == ""


def test_handoff_adopt_isolates_consumer_stack(fresh_registry):
    reg = fresh_registry
    with use_trace_context(new_trace_context()) as ctx:
        with span("producer"):
            token = handoff()
    results = {}

    def worker():
        # the worker has its OWN unrelated span open
        with span("worker_idle"):
            with adopt(token):
                assert current_trace_context() is token.ctx
                with span("child"):
                    results["path"] = current_span_path()
            # adopt restored the worker's own stack
            results["after"] = current_span_path()

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert results["path"] == "producer/child"
    assert results["after"] == "worker_idle"
    child = [e for e in reg.trace_events() if e["name"] == "child"][0]
    assert child["args"]["path"] == "producer/child"
    assert child["args"]["trace_id"] == ctx.trace_id


def test_threaded_handoff_stress_never_misattributes(fresh_registry):
    """Tier-1 stress (satellite): many producers enqueue work carrying
    handoff tokens; a small worker pool adopts and opens spans. Every
    resulting span event must carry ITS producer's trace id and parent
    path — never a sibling's."""
    import queue
    reg = fresh_registry
    n_producers, n_items, n_workers = 8, 25, 4
    q: "queue.Queue" = queue.Queue()
    expected = {}                     # item id -> trace id

    def producer(pi):
        ctx = new_trace_context()
        with use_trace_context(ctx):
            with span(f"producer{pi}"):
                for j in range(n_items):
                    item = (pi, j)
                    expected[item] = ctx.trace_id
                    q.put((item, handoff()))

    producers = [threading.Thread(target=producer, args=(pi,))
                 for pi in range(n_producers)]
    for t in producers:
        t.start()
    for t in producers:
        t.join()
    for _ in range(n_workers):
        q.put(None)

    def worker():
        while True:
            got = q.get()
            if got is None:
                return
            item, token = got
            with adopt(token):
                with span("consume", pi=item[0], j=item[1]):
                    pass

    workers = [threading.Thread(target=worker) for _ in range(n_workers)]
    for t in workers:
        t.start()
    for t in workers:
        t.join()

    consumed = [e for e in reg.trace_events() if e["name"] == "consume"]
    assert len(consumed) == n_producers * n_items
    for e in consumed:
        item = (e["args"]["pi"], e["args"]["j"])
        assert e["args"]["trace_id"] == expected[item], \
            f"item {item} attributed to the wrong trace"
        assert e["args"]["path"] == f"producer{item[0]}/consume", \
            f"item {item} parented under the wrong span"


# ----------------------------------------------------------- jsonl + tools
def test_write_trace_jsonl_and_trace_id_filter(fresh_registry, tmp_path):
    reg = fresh_registry
    a, b = new_trace_context(), new_trace_context()
    for ctx, name in ((a, "req_a"), (b, "req_b")):
        with use_trace_context(ctx):
            with span(name):
                event("tick")
    full = reg.write_trace_jsonl(str(tmp_path / "all.jsonl"))
    events = [json.loads(ln) for ln in open(full)]
    assert len(events) == 4
    only_a = reg.write_trace_jsonl(str(tmp_path / "a.jsonl"),
                                   trace_id=a.trace_id)
    got = [json.loads(ln) for ln in open(only_a)]
    assert {e["args"]["trace_id"] for e in got} == {a.trace_id}
    assert {e["name"] for e in got} == {"req_a", "tick"}


def test_trace2summary_gzip_and_trace_id_filter(fresh_registry, tmp_path,
                                                capsys):
    """Satellite regression: gzipped trace files load, --trace-id folds
    one request, --top still bounds the table (recorded fixture built
    from a real span run)."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.trace2summary import filter_trace_id, load_events, main
    reg = fresh_registry
    ids = []
    for i in range(3):
        ctx = new_trace_context()
        ids.append(ctx.trace_id)
        with use_trace_context(ctx):
            with span("request", i=i):
                with span("phase"):
                    pass
    # fixture: gzipped JSONL
    gz = tmp_path / "trace.jsonl.gz"
    with gzip.open(gz, "wt") as f:
        for e in reg.trace_events():
            f.write(json.dumps(e) + "\n")
    events = load_events(str(gz))
    assert len(events) == 6
    only = filter_trace_id(events, ids[1])
    assert len(only) == 2
    assert all(e["args"]["trace_id"] == ids[1] for e in only)
    # dashes/case in the CLI-provided id are normalized
    pretty = ids[1][:8] + "-" + ids[1][8:].upper()
    assert len(filter_trace_id(events, pretty)) == 2
    assert main([str(gz), "--trace-id", ids[1], "--top", "5"]) == 0
    out = capsys.readouterr().out
    assert "request" in out and "phase" in out


def test_trace2timeline_reconstruction_and_cli(fresh_registry, tmp_path,
                                               capsys):
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.trace2timeline import (format_timeline, list_traces, main,
                                      timeline)
    from tools.trace2summary import load_events
    reg = fresh_registry
    ctx = new_trace_context()
    with use_trace_context(ctx):
        event("ingress", route="/generate")
        with span("prefill", rung=32):
            pass
        event("decode_step", token_index=1)
    path = reg.write_trace_jsonl(str(tmp_path / "t.jsonl"))
    events = load_events(path)
    listing = list_traces(events)
    assert listing[0]["trace_id"] == ctx.trace_id
    assert listing[0]["events"] == 3
    rows = timeline(events, ctx.trace_id)
    assert [r["name"] for r in rows] == ["ingress", "prefill",
                                        "decode_step"]
    assert rows[0]["t_ms"] == 0.0                  # relative to first event
    assert rows[1]["dur_ms"] is not None           # spans carry duration
    assert "route=/generate" in rows[0]["detail"]
    assert "prefill" in format_timeline(rows)
    assert main([path, "--list"]) == 0
    assert ctx.trace_id in capsys.readouterr().out
    assert main([path, "--trace-id", ctx.trace_id]) == 0
    assert "decode_step" in capsys.readouterr().out
    assert main([path, "--trace-id", "0" * 32]) == 1   # unknown id


# -------------------------------------------------- serving path (batcher)
def test_predict_under_context_emits_admit_and_batch_events(fresh_registry):
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.serving import InferenceEngine
    conf = (NeuralNetConfiguration(seed=31, updater=Sgd(0.1))
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    eng = InferenceEngine(net, feature_shape=(4,), buckets=(4,),
                          batch_window_ms=0.5)
    try:
        x = np.random.default_rng(1).normal(size=(2, 4)).astype(np.float32)
        ctx = new_trace_context()
        with use_trace_context(ctx):
            eng.predict(x)
        eng.predict(x)                       # untraced: no events
    finally:
        eng.stop()
    evs = [e for e in fresh_registry.trace_events()
           if e["args"].get("trace_id") == ctx.trace_id]
    names = [e["name"] for e in evs]
    assert "serving.admit" in names
    assert "serving.batch" in names          # stamped from dispatch thread
    batch = [e for e in evs if e["name"] == "serving.batch"][0]
    assert batch["args"]["rows"] == 2
    assert "queue_ms" in batch["args"]
    untraced = [e for e in fresh_registry.trace_events()
                if e["name"] == "serving.batch"
                and "trace_id" not in e["args"]]
    assert not untraced                      # untraced caller -> no event


# ------------------------------------------------------------ solver + fit
def test_fit_spans_share_one_trace_id(fresh_registry, rng):
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
    conf = (NeuralNetConfiguration(seed=12, updater=Sgd(0.1))
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=16)]
    net.fit(iterator=ListDataSetIterator(features=x, labels=y,
                                         batch_size=8),
            epochs=1, async_prefetch=False)
    spans_ = [e for e in fresh_registry.trace_events()
              if e.get("cat") == "span"]
    ids = {e["args"].get("trace_id") for e in spans_}
    assert len(ids) == 1 and None not in ids    # one fresh id per fit
    # a caller-provided context wins over the per-fit fresh one
    ctx = new_trace_context()
    with use_trace_context(ctx):
        net.fit(iterator=ListDataSetIterator(features=x, labels=y,
                                             batch_size=8),
                epochs=1, async_prefetch=False)
    fit_spans = [e for e in fresh_registry.trace_events()
                 if e["name"] == "fit"]
    assert fit_spans[-1]["args"]["trace_id"] == ctx.trace_id


# --------------------------------------------- HTTP end-to-end (acceptance)
def test_http_generation_trace_end_to_end(fresh_registry, tmp_path):
    """THE acceptance path: X-Trace-Id in -> echoed out, and the id rides
    ingress, admission, prefill and every decode step, reconstructable
    with trace2timeline."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.trace2summary import load_events
    from tools.trace2timeline import timeline
    from deeplearning4j_tpu.models.zoo_extra import transformer_lm
    from deeplearning4j_tpu.serving import (GenerationEngine,
                                            ServingHTTPServer)
    net = transformer_lm(vocab_size=29, d_model=16, n_heads=2, n_blocks=1,
                         max_length=32, seed=7, dtype="float32",
                         token_input=True).init()
    eng = GenerationEngine(net, model_name="lm", block_len=8,
                           max_seq_len=32, decode_slots=2,
                           prefill_batches=(1,), prompt_rungs=(32,))
    srv = ServingHTTPServer(generation=eng)
    base = f"http://127.0.0.1:{srv.start()}"
    wire_id = "AABB-ccdd00112233445566778899aabbcc"
    want_id = "aabbccdd00112233445566778899aabbcc"
    try:
        req = urllib.request.Request(
            base + "/generate",
            json.dumps({"prompt": [3, 5, 7], "max_tokens": 6,
                        "stream": False}).encode(),
            {"Content-Type": "application/json", "X-Trace-Id": wire_id})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.headers.get("X-Trace-Id") == want_id   # echoed out
            body = json.loads(r.read())
        assert len(body["tokens"]) == 6
        # a response without an inbound id still carries a generated one
        req2 = urllib.request.Request(
            base + "/generate",
            json.dumps({"prompt": [2, 4], "max_tokens": 2,
                        "stream": False}).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req2, timeout=30) as r:
            gen_id = r.headers.get("X-Trace-Id")
        assert gen_id and len(gen_id) == 32 and gen_id != want_id
    finally:
        srv.stop()
    path = fresh_registry.write_trace_jsonl(str(tmp_path / "t.jsonl"),
                                            trace_id=want_id)
    names = [json.loads(ln)["name"] for ln in open(path)]
    assert names[0] == "http.request"                       # ingress
    assert "generation.submit" in names
    assert "generation.admit" in names                      # admission
    assert "generation.prefill" in names                    # prefill
    # 6 tokens = 1 from prefill + 5 decode steps, every one stamped
    assert names.count("generation.decode_step") == 5
    assert "generation.finish" in names
    # reconstructable per-request view, in causal order
    rows = timeline(load_events(str(tmp_path / "t.jsonl")), want_id)
    order = [r["name"] for r in rows]
    assert order.index("http.request") < order.index("generation.admit") \
        < order.index("generation.prefill") \
        < order.index("generation.decode_step")


# ------------------------------------------------------------- bench guard
@pytest.mark.bench_smoke
def test_traced_overhead_bench_smoke():
    """Tier-1 guard for the ISSUE 13 bench extension: the FULL tracing +
    training-watch fit variant and the HTTP serving tracing variant must
    stay <5%. Same retry discipline as the base telemetry guard — wall
    clock on a shared rig swings, so fail only on three consecutive
    breaches."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    last = None
    for _ in range(3):
        row = bench.bench_telemetry_overhead(steps=96, repeats=4,
                                             serving_requests=80,
                                             variants=("traced", "serving"))
        assert row["traced_steps_per_sec"] > 0
        assert row["serving_traced_req_per_sec"] > 0
        last = row
        if row["traced_fit_overhead_pct"] < 5.0 and \
                row["traced_serving_overhead_pct"] < 5.0:
            return
    pytest.fail(f"tracing overhead >=5% in 3 consecutive runs: {last}")
