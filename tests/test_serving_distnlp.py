"""Distributed Word2Vec, model-serving route, node2vec, CJK tokenizers,
remote stats router, estimator wrappers (reference spark-nlp distributed
training, DL4jServeRouteBuilder, node2vec stub completion, language packs,
RemoteUIStatsStorageRouter, spark-ml wrapper)."""
import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam

R = np.random.default_rng(33)


def test_distributed_w2v_step_matches_single_device():
    """The mesh-sharded SGNS step must equal the single-device step on the
    same batch (identical math, sharded execution)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nlp.distributed_w2v import DistributedWord2Vec
    from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors

    V, D, B, k = 40, 12, 64, 5
    syn0_np = (R.normal(size=(V, D)) * 0.1).astype(np.float32)
    syn1_np = (R.normal(size=(V, D)) * 0.1).astype(np.float32)
    centers = jnp.asarray(R.integers(0, V, B))
    contexts = jnp.asarray(R.integers(0, V, B))
    negs = jnp.asarray(R.integers(0, V, (B, k)))

    # both steps donate their table buffers — hand each its own fresh arrays
    single = SequenceVectors(layer_size=D, negative=k)._build_step()
    s0_a, s1_a, _ = single(jnp.asarray(syn0_np), jnp.asarray(syn1_np),
                           centers, contexts, negs, 0.05)

    dist = DistributedWord2Vec(layer_size=D, negative=k)._build_step()
    s0_b, s1_b, dist_loss = dist(jnp.asarray(syn0_np), jnp.asarray(syn1_np),
                                 centers, contexts, negs, 0.05)
    np.testing.assert_allclose(np.asarray(s0_b), np.asarray(s0_a), atol=2e-6)
    np.testing.assert_allclose(np.asarray(s1_b), np.asarray(s1_a), atol=2e-6)
    # the distributed step reports the real mean pair loss, matching the
    # single-device step's (advisor r2: it used to return a constant 0.0)
    single2 = SequenceVectors(layer_size=D, negative=k)._build_step()
    _, _, single_loss = single2(jnp.asarray(syn0_np), jnp.asarray(syn1_np),
                                centers, contexts, negs, 0.05)
    assert float(dist_loss) > 0.0
    np.testing.assert_allclose(float(dist_loss), float(single_loss), rtol=1e-5)


def test_distributed_w2v_end_to_end_similarity():
    from deeplearning4j_tpu.nlp.distributed_w2v import DistributedWord2Vec
    corpus = [("day night sun moon light dark " * 3).split()
              for _ in range(30)] + \
             [("cat dog pet fur paw tail " * 3).split() for _ in range(30)]
    w2v = DistributedWord2Vec(layer_size=16, window=3, epochs=3, negative=4,
                              seed=4, learning_rate=0.05)
    w2v.fit(corpus)
    assert w2v.similarity("day", "night") > w2v.similarity("day", "dog")


def test_model_serving_server():
    from deeplearning4j_tpu.parallel.model_server import ModelServingServer
    conf = (NeuralNetConfiguration(seed=2, updater=Adam(5e-3), dtype="float32")
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    srv = ModelServingServer(net, batched=True)
    port = srv.start()
    try:
        x = R.normal(size=(5, 4)).astype(np.float32).tolist()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict",
            data=json.dumps({"features": x}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())["output"]
        want = np.asarray(net.output(np.asarray(x, np.float32)))
        np.testing.assert_allclose(np.asarray(out), want, atol=1e-5)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=10) as r:
            h = json.loads(r.read())
        assert h["status"] == "ok" and h["requests_served"] == 1
    finally:
        srv.stop()


def test_node2vec_bias_and_training():
    from deeplearning4j_tpu.graphs import Graph
    from deeplearning4j_tpu.graphs.node2vec import (Node2Vec,
                                                    Node2VecWalkIterator)
    # path graph 0-1-2: from 1 after arriving from 0, returning to 0 has
    # weight 1/p; with huge p returns are rare
    g = Graph(3)
    g.add_edge(0, 1)
    g.add_edge(1, 2)
    returns = 0
    total = 0
    for s in range(60):
        it = Node2VecWalkIterator(g, walk_length=2, p=100.0, q=1.0, seed=s)
        for w in it:
            if w[0] == 0 and len(w) >= 3:       # 0 -> 1 -> ?
                total += 1
                returns += (w[2] == 0)
    assert total > 0
    assert returns / total < 0.2

    # two cliques embed apart (same setup as the DeepWalk test, biased walks)
    k = 6
    g2 = Graph(2 * k)
    for i in range(k):
        for j in range(i + 1, k):
            g2.add_edge(i, j)
            g2.add_edge(k + i, k + j)
    g2.add_edge(0, k)
    nv = Node2Vec(vector_size=16, window_size=4, walk_length=20,
                  walks_per_vertex=8, epochs=3, p=1.0, q=0.5, seed=7).fit(g2)
    same = np.mean([nv.similarity(i, j) for i in range(1, k)
                    for j in range(1, k) if i < j])
    cross = np.mean([nv.similarity(i, j) for i in range(1, k)
                     for j in range(k + 1, 2 * k)])
    assert same > cross


def test_cjk_tokenizer():
    from deeplearning4j_tpu.nlp.tokenizer import CJKTokenizerFactory
    tf = CJKTokenizerFactory()
    toks = tf.create("我爱机器学习 deep learning 딥러닝").get_tokens()
    assert "我爱" in toks and "机器" in toks       # overlapping bigrams
    assert "deep" in toks and "learning" in toks  # latin runs intact
    assert "딥러닝" in toks                        # hangul run intact
    uni = CJKTokenizerFactory(bigrams=False).create("学习").get_tokens()
    assert uni == ["学", "习"]
    custom = CJKTokenizerFactory(segmenter=lambda s: s.split("|"))
    assert custom.create("a|b c|d").get_tokens() == ["a", "b c", "d"]


def test_remote_stats_router_round_trip():
    from deeplearning4j_tpu.ui.dashboard import TrainingUIServer
    from deeplearning4j_tpu.ui.storage import (InMemoryStatsStorage,
                                               RemoteStatsStorageRouter)
    store = InMemoryStatsStorage()
    srv = TrainingUIServer()
    srv.attach(store)
    port = srv.start()
    try:
        router = RemoteStatsStorageRouter(f"http://127.0.0.1:{port}")
        router.put_static_info("sess1", "w0", {"model_class": "TestNet"})
        router.put_update("sess1", "w0", {"iteration": 0, "score": 1.25})
        router.put_update("sess1", "w0", {"iteration": 1, "score": 0.75})
        router.flush()        # posts are async (bounded queue + retries)
        assert router.dropped == 0
        assert store.list_session_ids() == ["sess1"]
        assert store.get_static_info("sess1", "w0")["model_class"] == "TestNet"
        ups = store.get_updates("sess1", "w0")
        assert [u["score"] for u in ups] == [1.25, 0.75]
    finally:
        srv.stop()


def test_sklearn_style_wrappers():
    from deeplearning4j_tpu.ml import NeuralNetClassifier, NeuralNetRegressor
    x = R.normal(size=(200, 4)).astype(np.float32)
    yi = (x[:, 0] + x[:, 1] > 0).astype(int)
    conf = (NeuralNetConfiguration(seed=1, updater=Adam(1e-2), dtype="float32")
            .list(DenseLayer(n_in=4, n_out=16, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    clf = NeuralNetClassifier(conf, epochs=25, batch_size=64).fit(x, yi)
    assert clf.score(x, yi) > 0.85
    assert clf.predict_proba(x).shape == (200, 2)
    assert clf.get_params()["epochs"] == 25

    yr = (2.0 * x[:, 0] - x[:, 2]).astype(np.float32)
    rconf = (NeuralNetConfiguration(seed=2, updater=Adam(1e-2), dtype="float32")
             .list(DenseLayer(n_in=4, n_out=16, activation="tanh"),
                   OutputLayer(n_out=1, activation="identity", loss="mse"))
             .build())
    reg = NeuralNetRegressor(rconf, epochs=40, batch_size=64).fit(x, yr)
    assert reg.score(x, yr) > 0.8


# ------------------------------------------------------ streaming route (r3)
def test_streaming_ingest_trains_live():
    """CamelKafkaRouteBuilder analogue (reference dl4j-streaming): a
    producer thread POSTs minibatches over HTTP while net.fit consumes the
    live topic; training sees every published batch and improves."""
    import json
    import threading
    import urllib.request

    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.updaters import Adam
    from deeplearning4j_tpu.parallel.streaming import (StreamingDataSetIterator,
                                                       StreamingIngestServer)

    conf = (NeuralNetConfiguration(seed=1, updater=Adam(5e-3), dtype="float32")
            .list(DenseLayer(n_in=6, n_out=16, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    X = rng.normal(size=(256, 6)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[(X.sum(-1) > 0).astype(int)]
    s0 = net.score(X, Y)

    topic = StreamingDataSetIterator(capacity=8)
    srv = StreamingIngestServer(topic).start()
    url = f"http://127.0.0.1:{srv.port}"

    def post(path, payload):
        req = urllib.request.Request(url + path,
                                     json.dumps(payload).encode(),
                                     {"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read())

    def producer():
        for s in range(0, 256, 32):
            post("/publish", {"features": X[s:s + 32].tolist(),
                              "labels": Y[s:s + 32].tolist()})
        post("/end", {})

    t = threading.Thread(target=producer)
    t.start()
    net.fit(iterator=topic, epochs=1)    # blocks on the live stream
    t.join()
    stats = json.loads(urllib.request.urlopen(url + "/stats").read())
    srv.stop()
    assert stats["published"] == 8 and stats["consumed"] == 8
    assert stats["closed"]
    assert net.score(X, Y) < s0


def test_streaming_topic_backpressure_and_timeout():
    from deeplearning4j_tpu.parallel.streaming import StreamingDataSetIterator
    topic = StreamingDataSetIterator(capacity=2, timeout=0.2)
    x = np.zeros((4, 3), np.float32)
    y = np.zeros((4, 2), np.float32)
    assert topic.publish(x, y, block=False)
    assert topic.publish(x, y, block=False)
    assert not topic.publish(x, y, block=False)   # full -> back-pressure
    seen = sum(1 for _ in topic)                  # drains 2, then idle timeout
    assert seen == 2
    topic.end_of_stream()
    assert not topic.publish(x, y)                # closed


def test_streaming_close_never_hangs_on_full_topic():
    """end_of_stream on a FULL topic returns immediately and queued batches
    still drain (the close is an event, not a sentinel slot)."""
    import time
    from deeplearning4j_tpu.parallel.streaming import StreamingDataSetIterator
    topic = StreamingDataSetIterator(capacity=2)
    x = np.zeros((1, 2), np.float32)
    y = np.zeros((1, 2), np.float32)
    assert topic.publish(x, y, block=False)
    assert topic.publish(x, y, block=False)   # full
    t0 = time.perf_counter()
    topic.end_of_stream()                      # must not block
    assert time.perf_counter() - t0 < 0.5
    assert sum(1 for _ in topic) == 2          # accepted batches all consumed
