"""Multi-step on-device training: scan-fused step windows
(fit(steps_per_dispatch=K)) + the sync-free deferred-score listener
protocol.

The contract under test (ISSUE 2 tentpole): K prefetched device-resident
batches run through ONE jitted, buffer-donated lax.scan program whose
result is BIT-IDENTICAL to K sequential single-step dispatches — including
label/feature masks, the ragged final window, and the K=1 degenerate case
— while listeners never force a per-step device sync (scores stay
device-resident until log/flush time).

Bit-identity holds exactly under this suite's config (conftest enables
x64, so weak-typed updater scalars ride f64); in pure-f32 runs a
stateful updater's fused elementwise chain can differ by <= 1 ulp per
step between the scan body and the standalone program (same math,
different XLA fusion) — see the README numerics footnote.
"""
import logging

import jax
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import (DataSet, DataSetIterator,
                                                 ListDataSetIterator)
from deeplearning4j_tpu.datasets.iterators import MultiDataSet
from deeplearning4j_tpu.datasets.prefetch import (BatchWindow,
                                                  DevicePrefetchIterator,
                                                  iter_windows)
from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.listeners import (
    CollectScoresIterationListener, ScoreIterationListener, score_to_float)
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd


def _tiny_net(seed=12, updater=None):
    conf = (NeuralNetConfiguration(seed=seed, updater=updater or Sgd(0.1))
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _toy(rng, n=64):
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=n)]
    return x, y


def _it(x, y, bs=8):
    return ListDataSetIterator(features=x, labels=y, batch_size=bs)


def _assert_trees_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


# ---------------------------------------------------------------- parity
@pytest.mark.parametrize("k", [1, 2, 4, 8])
def test_scan_window_bit_identical_params_and_opt_state(rng, k):
    """fit(steps_per_dispatch=K) == K sequential single steps, bit for
    bit, for params AND updater state (Adam: stateful moments make a
    divergence visible immediately); K=1 is the degenerate case."""
    x, y = _toy(rng)
    a = _tiny_net(updater=Adam(1e-2)).fit(iterator=_it(x, y), epochs=3)
    b = _tiny_net(updater=Adam(1e-2)).fit(iterator=_it(x, y), epochs=3,
                                          steps_per_dispatch=k)
    _assert_trees_equal(a.params, b.params)
    _assert_trees_equal(a.opt_state, b.opt_state)
    assert a.iteration_count == b.iteration_count


def test_scan_window_ragged_final_window(rng):
    """10 batches at K=4: two fused windows + a 2-batch per-step ragged
    tail — results still bit-identical, all 10 iterations counted."""
    x, y = _toy(rng, n=80)
    a = _tiny_net().fit(iterator=_it(x, y), epochs=2)
    b = _tiny_net().fit(iterator=_it(x, y), epochs=2, steps_per_dispatch=4)
    assert a.iteration_count == b.iteration_count == 20
    _assert_trees_equal(a.params, b.params)


def test_scan_window_with_label_mask(rng):
    """Per-example label masks ride the stacked window unchanged."""
    x, y = _toy(rng, n=32)
    mask = np.ones((32,), np.float32)
    mask[1::2] = 0.0
    dss = [DataSet(x[i:i + 8], y[i:i + 8], labels_mask=mask[i:i + 8])
           for i in range(0, 32, 8)]
    a = _tiny_net().fit(iterator=ListDataSetIterator(list(dss)), epochs=3)
    b = _tiny_net().fit(iterator=ListDataSetIterator(list(dss)), epochs=3,
                        steps_per_dispatch=2)
    _assert_trees_equal(a.params, b.params)


def test_scan_window_with_feature_and_label_masks(rng):
    """Time-series batches with BOTH [B,T] masks (the recurrent masking
    contract) through a fused window: bit-identical."""
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    B, T = 4, 6
    x = np.random.default_rng(3).normal(size=(16, T, 5)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[
        np.random.default_rng(4).integers(0, 3, size=(16, T))]
    fmask = np.ones((16, T), np.float32)
    fmask[:, -2:] = 0.0
    lmask = np.ones((16, T), np.float32)
    lmask[:, 0] = 0.0

    def build():
        conf = (NeuralNetConfiguration(seed=21, updater=Sgd(0.05))
                .list(LSTM(n_out=7, activation="tanh"),
                      RnnOutputLayer(n_out=3, activation="softmax",
                                     loss="mcxent"))
                .set_input_type(InputType.recurrent(5, T))
                .build())
        return MultiLayerNetwork(conf).init()

    dss = [DataSet(x[i:i + B], y[i:i + B], features_mask=fmask[i:i + B],
                   labels_mask=lmask[i:i + B]) for i in range(0, 16, B)]
    a = build().fit(iterator=ListDataSetIterator(list(dss)), epochs=2)
    b = build().fit(iterator=ListDataSetIterator(list(dss)), epochs=2,
                    steps_per_dispatch=4)
    _assert_trees_equal(a.params, b.params)


def test_scan_window_with_prefetched_iterator(rng):
    """Windows assembled from DevicePrefetchIterator's device-resident
    queue (the intended production pairing) stay bit-identical."""
    x, y = _toy(rng)
    a = _tiny_net().fit(iterator=_it(x, y), epochs=2, async_prefetch=False)
    b = _tiny_net().fit(iterator=_it(x, y).prefetch(depth=3), epochs=2,
                        steps_per_dispatch=4)
    _assert_trees_equal(a.params, b.params)


def test_scan_window_scores_match_per_step(rng):
    """Per-step losses surfaced from the scan's ys equal the per-step
    path's scores — same values, same iteration indices."""
    x, y = _toy(rng)
    ca, cb = CollectScoresIterationListener(), CollectScoresIterationListener()
    _tiny_net().set_listeners(ca).fit(iterator=_it(x, y), epochs=2)
    _tiny_net().set_listeners(cb).fit(iterator=_it(x, y), epochs=2,
                                      steps_per_dispatch=4)
    assert [i for i, _ in ca.scores] == [i for i, _ in cb.scores]
    np.testing.assert_array_equal(np.asarray([s for _, s in ca.scores]),
                                  np.asarray([s for _, s in cb.scores]))


def test_scan_window_computation_graph_bit_identical(rng):
    """The shared Solver serves ComputationGraph too: fused CG windows
    are bit-identical to per-step CG training."""
    from deeplearning4j_tpu.nn.graph.graph import ComputationGraph

    def build():
        g = (NeuralNetConfiguration(seed=5, updater=Adam(5e-3))
             .graph_builder()
             .add_inputs("in")
             .add_layer("d1", DenseLayer(n_out=16, activation="tanh"), "in")
             .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                           loss="mcxent"), "d1")
             .set_outputs("out")
             .set_input_types(InputType.feed_forward(4)))
        return ComputationGraph(g.build()).init()

    x, y = _toy(rng)
    a = build().fit(iterator=_it(x, y), epochs=2)
    b = build().fit(iterator=_it(x, y), epochs=2, steps_per_dispatch=4)
    _assert_trees_equal(a.params, b.params)


# ------------------------------------------------------------- fallbacks
def test_tbptt_falls_back_to_per_step(rng):
    """tBPTT keeps the chunked per-step path under steps_per_dispatch>1
    (documented auto-fallback) — same results as without the knob."""
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    x = rng.normal(size=(8, 12, 5)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=(8, 12))]

    def build():
        conf = (NeuralNetConfiguration(seed=9, updater=Sgd(0.05))
                .list(LSTM(n_out=6, activation="tanh"),
                      RnnOutputLayer(n_out=3, activation="softmax",
                                     loss="mcxent"))
                .set_input_type(InputType.recurrent(5, 12))
                .tbptt_length(4)
                .build())
        return MultiLayerNetwork(conf).init()

    a = build().fit(x, y, epochs=2, batch_size=4)
    b = build().fit(x, y, epochs=2, batch_size=4, steps_per_dispatch=8)
    _assert_trees_equal(a.params, b.params)


def test_second_order_falls_back_to_per_step(rng):
    """Second-order solvers (line search needs host control flow) ignore
    steps_per_dispatch rather than breaking."""
    x, y = _toy(rng, n=32)
    conf = (NeuralNetConfiguration(seed=3, updater=Sgd(0.5),
                                   optimization_algorithm="lbfgs")
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(iterator=_it(x, y, bs=16), epochs=1, steps_per_dispatch=4)
    assert np.all(np.isfinite(np.asarray(net.params_flat())))


def test_steps_per_dispatch_validation(rng):
    x, y = _toy(rng, n=16)
    with pytest.raises(ValueError, match="steps_per_dispatch"):
        _tiny_net().fit(iterator=_it(x, y), steps_per_dispatch=0)


# --------------------------------------------------------- window maker
def test_iter_windows_groups_and_ragged_tail(rng):
    x, y = _toy(rng, n=72)           # 9 batches of 8
    items = list(iter_windows(_it(x, y), 4))
    assert [type(i).__name__ for i in items] == \
        ["BatchWindow", "BatchWindow", "DataSet"]
    assert all(len(w) == 4 for w in items[:2])
    # order + content preserved across the grouping
    flat = [d for i in items for d in (i.datasets
                                       if isinstance(i, BatchWindow) else [i])]
    want = list(_it(x, y))
    assert len(flat) == len(want) == 9
    for g, w in zip(flat, want):
        np.testing.assert_array_equal(np.asarray(g.features), w.features)


def test_iter_windows_mixed_shapes_fall_back(rng):
    """A shape change mid-window degrades that whole group to per-step
    batches (order preserved) instead of mis-stacking."""
    x, y = _toy(rng, n=20)           # batches: 8, 8, 4 — last is ragged
    items = list(iter_windows(_it(x, y), 3))
    assert all(isinstance(i, DataSet) for i in items)
    assert [i.num_examples() for i in items] == [8, 8, 4]


def test_iter_windows_multidataset_falls_back(rng):
    x = rng.normal(size=(8, 4)).astype(np.float32)
    mds = [MultiDataSet([x], [x]) for _ in range(4)]

    class It(DataSetIterator):
        def __iter__(self):
            return iter(mds)

    items = list(iter_windows(It(), 2))
    assert all(isinstance(i, MultiDataSet) for i in items)


def test_prefetch_windows_stack_on_device(rng):
    """DevicePrefetchIterator.windows(k): stacked feeds are [K, B, ...]
    device arrays built from the already-shipped queue entries."""
    x, y = _toy(rng)
    it = DevicePrefetchIterator(_it(x, y), depth=2, dtype="float32")
    wins = [w for w in it.windows(4) if isinstance(w, BatchWindow)]
    assert len(wins) == 2
    xs, ys, lms, fms = wins[0].stacked()
    assert isinstance(xs, jax.Array) and xs.shape == (4, 8, 4)
    assert ys.shape == (4, 8, 3) and lms is None and fms is None
    assert wins[0].num_examples() == 32


# ------------------------------------------- sync-free listener protocol
class _ProbeScore:
    """Duck-typed device scalar that counts host materializations — any
    float()/str()/format() is what a device sync would be."""

    def __init__(self):
        self.syncs = 0

    def __float__(self):
        self.syncs += 1
        return 0.5


def test_score_listener_no_sync_per_step():
    """ScoreIterationListener never materializes the score in the
    dispatch path: off-cycle iterations don't touch it, and on-cycle the
    readback is deferred past the logging gate (no handler -> no sync)."""
    probe = _ProbeScore()
    lst = ScoreIterationListener(10)
    logger = logging.getLogger("deeplearning4j_tpu")
    old = logger.level
    logger.setLevel(logging.WARNING)    # INFO gated off: nothing may sync
    try:
        for i in range(100):
            lst.iteration_done(None, i, probe)
    finally:
        logger.setLevel(old)
    assert probe.syncs == 0


def test_collect_scores_defers_sync_to_flush():
    """CollectScoresIterationListener keeps the device scalar per
    iteration; the readbacks happen only when .scores is first read."""
    probes = [_ProbeScore() for _ in range(50)]
    lst = CollectScoresIterationListener()
    for i, p in enumerate(probes):
        lst.iteration_done(None, i, p)
    assert sum(p.syncs for p in probes) == 0     # collection: sync-free
    scores = lst.scores                          # flush point
    assert len(scores) == 50
    assert all(p.syncs == 1 for p in probes)
    assert lst.scores is scores or lst.scores == scores  # idempotent


def test_collect_scores_bounded_retention():
    """flush_every bounds live device-scalar retention: a run that never
    reads .scores still materializes in batches, not per step."""
    probes = [_ProbeScore() for _ in range(10)]
    lst = CollectScoresIterationListener(flush_every=4)
    for i, p in enumerate(probes):
        lst.iteration_done(None, i, p)
    assert sum(p.syncs for p in probes) == 8      # flushed at 4 and 8
    assert len(lst._raw) == 2                     # only the tail retained
    assert len(lst.scores) == 10                  # final flush on access


def test_collect_scores_interleaves_flush_and_collect():
    lst = CollectScoresIterationListener()
    lst.iteration_done(None, 0, 1.5)
    assert lst.scores == [(0, 1.5)]
    lst.iteration_done(None, 1, 2.5)
    assert lst.scores == [(0, 1.5), (1, 2.5)]
    lst.scores = []                 # pre-protocol reset idiom still works
    assert lst.scores == []
    lst.iteration_done(None, 2, 3.5)
    assert lst.scores == [(2, 3.5)]


def test_score_to_float_handles_device_scalars():
    import jax.numpy as jnp
    assert score_to_float(jnp.float32(1.25)) == 1.25
    assert score_to_float(0.5) == 0.5


def test_fused_loop_never_syncs_on_scores(rng, monkeypatch):
    """End-to-end: with collecting + printing listeners attached, the fit
    loop (fused AND K=1) performs ZERO score materializations until the
    flush point. score_to_float is THE protocol sync point (the probe
    tests above pin that listeners have no other conversion path), so
    counting its calls counts the readbacks."""
    import deeplearning4j_tpu.optimize.listeners as L
    x, y = _toy(rng, n=32)
    calls = {"n": 0}
    orig = L.score_to_float

    def counting(s):
        calls["n"] += 1
        return orig(s)

    logger = logging.getLogger("deeplearning4j_tpu")
    old = logger.level
    logger.setLevel(logging.WARNING)
    try:
        for k in (1, 2):
            calls["n"] = 0
            net = _tiny_net()
            collect = CollectScoresIterationListener()
            net.set_listeners(collect, ScoreIterationListener(2))
            monkeypatch.setattr(L, "score_to_float", counting)
            net.fit(iterator=_it(x, y), epochs=2, steps_per_dispatch=k,
                    async_prefetch=False)
            in_loop = calls["n"]
            assert in_loop == 0, \
                f"K={k}: {in_loop} score readbacks inside the fit loop"
            assert len(collect.scores) == 8          # flush works after
            assert calls["n"] == 8                   # exactly one per score
    finally:
        logger.setLevel(old)


# -------------------------------------------------------- ParallelWrapper
def test_parallel_wrapper_windowed_bit_identical(rng):
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
    x, y = _toy(rng)
    a = _tiny_net()
    ParallelWrapper(a).fit(_it(x, y, bs=16), epochs=3)
    b = _tiny_net()
    ParallelWrapper(b, steps_per_dispatch=2).fit(_it(x, y, bs=16), epochs=3)
    _assert_trees_equal(a.params, b.params)
    _assert_trees_equal(a.opt_state, b.opt_state)
    assert a.iteration_count == b.iteration_count == 12


def test_parallel_wrapper_windowed_ragged(rng):
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
    x, y = _toy(rng, n=48)           # 3 batches of 16: one window + ragged
    a = _tiny_net()
    ParallelWrapper(a).fit(_it(x, y, bs=16), epochs=2)
    b = _tiny_net()
    ParallelWrapper(b, steps_per_dispatch=2).fit(_it(x, y, bs=16), epochs=2)
    _assert_trees_equal(a.params, b.params)
    assert b.iteration_count == 6


def test_parallel_wrapper_rejects_accumulator_with_windows():
    from deeplearning4j_tpu.parallel.accumulation import PsumAccumulator
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
    with pytest.raises(ValueError, match="steps_per_dispatch"):
        ParallelWrapper(_tiny_net(), steps_per_dispatch=4,
                        gradient_accumulator=PsumAccumulator())


# ------------------------------------------------------------ bench smoke
@pytest.mark.bench_smoke
def test_dispatch_bound_bench_smoke():
    """Tier-1 guard for the fused path: the bench row must run end to end
    and the scan-fused column must not be catastrophically slower than
    per-step dispatch (a broken fused path shows up here long before a
    BENCH_* round). The >=2x acceptance number is measured by bench.py on
    the real rig; CI only pins 'not broken'.

    Robustness: a shared CI box can stall a single 32-step epoch for
    >100ms (scheduler/GC), which at repeats=1 tanked the ratio below the
    bound in otherwise-healthy runs — so each attempt takes best-of-3
    epochs per mode, and only three consecutive failing attempts fail
    the guard (a genuinely broken fused path fails every attempt)."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    row = None
    for _ in range(3):
        row = bench.bench_dispatch_bound(steps=32, ks=(1, 4), repeats=3)
        assert row["k1_steps_per_sec"] > 0
        assert row["k4_steps_per_sec"] > 0
        if row["fused_speedup"] > 0.5:
            return
    pytest.fail(f"fused path catastrophically slow in 3 attempts: {row}")
