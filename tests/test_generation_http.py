"""HTTP surface for generation: POST /generate streaming + error taxonomy.

Regression tests alongside the forward-serving 400/429/503/504 suite
(tests/test_serving_engine.py): per-token chunked NDJSON streaming,
block-pool exhaustion -> 429 with a retry hint, mid-stream deadline expiry
terminating the stream cleanly (no hung clients), draining -> 503, and
POST /reload hot-swapping a generation model with the in-flight-on-old,
admissions-on-new cutover rule.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from deeplearning4j_tpu.models.decode import (TransformerDecodeSpec,
                                              naive_generate)
from deeplearning4j_tpu.models.zoo_extra import transformer_lm
from deeplearning4j_tpu.serving import GenerationEngine, ServingHTTPServer

R = np.random.default_rng(17)


def _lm(seed=7, vocab=29, max_length=32):
    return transformer_lm(vocab_size=vocab, d_model=16, n_heads=2,
                          n_blocks=1, max_length=max_length, seed=seed,
                          dtype="float32", token_input=True).init()


def _engine(net, **kw):
    cfg = dict(model_name="lm", block_len=8, max_seq_len=32, decode_slots=2,
               prefill_batches=(1,), prompt_rungs=(32,))
    cfg.update(kw)
    return GenerationEngine(net, **cfg)


def _post(base, path, payload, timeout=30):
    req = urllib.request.Request(base + path, json.dumps(payload).encode(),
                                 {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _stream(base, payload, timeout=30):
    """POST /generate with stream=true; returns the parsed NDJSON lines."""
    req = urllib.request.Request(base + "/generate",
                                 json.dumps(payload).encode(),
                                 {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return [json.loads(line) for line in r if line.strip()]


def test_http_generate_stream_and_blocking():
    net = _lm()
    spec = TransformerDecodeSpec(net)
    eng = _engine(net)
    srv = ServingHTTPServer(generation=eng)
    base = f"http://127.0.0.1:{srv.start()}"
    try:
        prompt = [3, 5, 7]
        want = naive_generate(net, prompt, 6, pad_to=32, spec=spec)
        # stream: one {"token": id} line per token + a done terminator
        lines = _stream(base, {"prompt": prompt, "max_tokens": 6})
        toks = [l["token"] for l in lines if "token" in l]
        assert toks == want
        assert lines[-1] == {"done": True, "reason": "length", "tokens": 6}
        # blocking: single JSON body
        st, body = _post(base, "/generate",
                         {"prompt": prompt, "max_tokens": 6,
                          "stream": False})
        assert st == 200
        assert body["tokens"] == want
        assert body["reason"] == "length" and body["model"] == "lm"
        # observability routes expose the generation engine
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            m = json.loads(r.read())
        assert m["generation"]["lm"]["tokens_out"] >= 12
        with urllib.request.urlopen(base + "/models", timeout=10) as r:
            models = json.loads(r.read())
        assert models["generation"]["lm"]["adapter"] == "paged"
        with urllib.request.urlopen(base + "/health", timeout=10) as r:
            h = json.loads(r.read())
        assert h["generation_models"] == ["lm"]
    finally:
        srv.stop()


def test_http_generation_error_taxonomy():
    """400 malformed / 404 unknown model / 429 pool exhaustion with retry
    hint / 429 queue+pool saturation — the admission decisions surface as
    the right wire responses."""
    net = _lm(seed=9)
    eng = _engine(net, num_blocks=3, queue_limit=1, decode_slots=2)
    srv = ServingHTTPServer(generation=eng)
    base = f"http://127.0.0.1:{srv.start()}"
    try:
        st, body = _post(base, "/generate", {"prompt": "not-token-ids"})
        assert st == 400
        st, body = _post(base, "/generate", {})
        assert st == 400
        st, body = _post(base, "/generate/ghost", {"prompt": [1]})
        assert st == 404
        # over-capacity prompt+max_tokens -> 400 (shape taxonomy)
        st, body = _post(base, "/generate",
                         {"prompt": [1, 2], "max_tokens": 99,
                          "stream": False})
        assert st == 400
        # within capacity but needs more blocks than the pool HAS -> 429,
        # and since no retry can ever help, NO retry hint
        st, body = _post(base, "/generate",
                         {"prompt": [1, 2], "max_tokens": 28,
                          "stream": False})
        assert st == 429
        assert body["kind"] == "BlockPoolExhaustedError"
        assert "retry_after_ms" not in body
        # saturate: r1 holds both blocks, r2 queues, r3 -> 429. Decode is
        # slowed so r1 deterministically holds its blocks across the
        # submit sequence (the un-slowed window is a few ms — flaky under
        # suite load).
        rt = eng._get("lm")
        orig_decode = rt.active_ps.run_decode

        def slow_decode(*a, **k):
            time.sleep(0.01)
            return orig_decode(*a, **k)

        rt.active_ps.run_decode = slow_decode
        results = {}

        def bg(i):
            results[i] = _post(base, "/generate",
                               {"prompt": [i, i + 1], "max_tokens": 14,
                                "stream": False, "timeout_ms": 30000})

        t1 = threading.Thread(target=bg, args=(1,))
        t1.start()
        deadline = time.monotonic() + 5.0
        while eng.metrics()["lm"]["prefills"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.002)
        t2 = threading.Thread(target=bg, args=(2,))
        t2.start()
        deadline = time.monotonic() + 5.0      # wait until r2 is queued
        while eng.queue_depths()["lm"] < 1 and not results.get(2):
            assert time.monotonic() < deadline
            time.sleep(0.002)
        st, body = _post(base, "/generate",
                         {"prompt": [9, 10], "max_tokens": 14,
                          "stream": False})
        t1.join()
        t2.join()
        assert st == 429
        if body["kind"] == "BlockPoolExhaustedError":   # transient flavor
            assert "retry_after_ms" in body             # -> retry hint
        assert results[1][0] == 200 and results[2][0] == 200
        assert len(results[1][1]["tokens"]) == 14
        assert len(results[2][1]["tokens"]) == 14
    finally:
        srv.stop()


def test_http_keepalive_not_desynced_by_preparse_errors():
    """HTTP/1.1 keep-alive: a POST whose error response is written BEFORE
    the body is parsed (unknown route / missing engine) must still drain
    the body, or the unread bytes corrupt the NEXT request on the same
    connection."""
    import http.client
    eng = _engine(_lm(seed=19))
    srv = ServingHTTPServer(generation=eng)
    srv.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        body = json.dumps({"features": [[1.0, 2.0]]})
        # generation-only server: /predict 404s before reading the body
        conn.request("POST", "/predict", body,
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        assert r.status == 404
        r.read()
        # same connection: must parse as a fresh request, not body residue
        conn.request("GET", "/health")
        r2 = conn.getresponse()
        assert r2.status == 200
        assert json.loads(r2.read())["status"] == "ok"
        # unknown POST route with a body, then reuse again
        conn.request("POST", "/nope", body,
                     {"Content-Type": "application/json"})
        r3 = conn.getresponse()
        assert r3.status == 404
        r3.read()
        conn.request("GET", "/models")
        r4 = conn.getresponse()
        assert r4.status == 200
        r4.read()
        conn.close()
    finally:
        srv.stop()


def test_http_midstream_deadline_terminates_cleanly():
    """A deadline expiring mid-stream ends the chunked response with a
    {"done": true, "reason": "deadline"} line — the client's read loop
    completes on its own, nobody hangs on a half-open stream."""
    net = _lm(seed=11, max_length=64)
    eng = _engine(net, max_seq_len=64, decode_slots=1,
                  prompt_rungs=(64,))
    srv = ServingHTTPServer(generation=eng)
    base = f"http://127.0.0.1:{srv.start()}"
    try:
        t0 = time.monotonic()
        # 5ms: small enough that a warm rig cannot emit all 60 tokens
        # (prefill alone approaches it) — the deadline must land before
        # "length" does, whatever the machine speed
        lines = _stream(base, {"prompt": [1, 2, 3], "max_tokens": 60,
                               "timeout_ms": 5}, timeout=15)
        elapsed = time.monotonic() - t0
        assert lines[-1]["done"] is True
        assert lines[-1]["reason"] == "deadline"
        ntok = len([l for l in lines if "token" in l])
        assert ntok < 60 and lines[-1]["tokens"] == ntok
        assert elapsed < 10.0                  # terminated, not hung
        # blocking flavor with zero output -> 504
        st, body = _post(base, "/generate",
                         {"prompt": [1, 2, 3], "max_tokens": 60,
                          "timeout_ms": 0, "stream": False})
        assert st == 504
    finally:
        srv.stop()


def test_http_draining_503():
    net = _lm(seed=13)
    eng = _engine(net)
    srv = ServingHTTPServer(generation=eng)
    base = f"http://127.0.0.1:{srv.start()}"
    try:
        eng.stop(drain=True, timeout=5.0)      # engine drains, HTTP stays up
        st, body = _post(base, "/generate",
                         {"prompt": [1], "max_tokens": 2, "stream": False})
        assert st == 503
        try:
            with urllib.request.urlopen(base + "/health", timeout=10) as r:
                raise AssertionError(f"expected 503, got {r.status}")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert json.loads(e.read())["draining"] is True
    finally:
        srv.stop()


def test_http_reload_hot_swap_under_decode(tmp_path):
    """POST /reload swaps the generation model: the in-flight stream
    finishes on the old params, the next request runs the new ones
    (document cutover rule); unknown names 404."""
    from deeplearning4j_tpu.util.serialization import write_model
    net_a = _lm(seed=7, max_length=64)
    net_b = _lm(seed=8, max_length=64)
    spec_a, spec_b = TransformerDecodeSpec(net_a), TransformerDecodeSpec(net_b)
    prompt = [3, 5, 7, 9]
    want_a = naive_generate(net_a, prompt, 40, pad_to=64, spec=spec_a)
    want_b = naive_generate(net_b, prompt, 40, pad_to=64, spec=spec_b)
    assert want_a != want_b
    zpath = str(tmp_path / "lm_b.zip")
    write_model(net_b, zpath)
    eng = _engine(net_a, max_seq_len=64, prompt_rungs=(64,))
    srv = ServingHTTPServer(generation=eng)
    base = f"http://127.0.0.1:{srv.start()}"
    try:
        got = {}

        def long_client():
            got["a"] = _stream(base, {"prompt": prompt, "max_tokens": 40})

        t = threading.Thread(target=long_client)
        t.start()
        deadline = time.monotonic() + 5.0
        while eng.metrics()["lm"]["prefills"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.002)
        st, body = _post(base, "/reload", {"model": "lm", "path": zpath})
        assert st == 200 and body["version"] == 2
        st, body = _post(base, "/generate",
                         {"prompt": prompt, "max_tokens": 40,
                          "stream": False})
        t.join()
        toks_a = [l["token"] for l in got["a"] if "token" in l]
        assert toks_a == want_a, "in-flight stream must finish on OLD params"
        assert body["tokens"] == want_b, "post-swap request must be NEW"
        st, _ = _post(base, "/reload", {"model": "ghost", "path": zpath})
        assert st == 404
        st, _ = _post(base, "/reload", {"model": "lm", "path": 7})
        assert st == 400
    finally:
        srv.stop()
