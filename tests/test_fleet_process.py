"""The fleet as deployed: real replica OS processes behind the front door.

One module-scoped fleet — two supervised replica subprocesses sharing a
persistent compilation cache, an affinity FleetRouter with a live health
poller, and the FleetHTTPServer front door. Pins the subsystem's
acceptance behaviors end to end:

  - readiness gating (ready file + /health 200) and the /health steering
    payload a router steers on;
  - front-door token streams byte-identical to the single-process
    reference (naive_generate);
  - chaos SIGKILL loses ONLY the in-flight stream — closed with
    ``reason: "replica_lost"`` — while the router marks the victim dead,
    dumps a flight-recorder black box, and survivors keep serving;
  - pre-first-token failures replay idempotently on a survivor (exact
    greedy sequence, ``fleet.retry`` trace marker);
  - a replica joining a WARM compilation cache reaches ready with zero
    fresh backend compiles, then drains out with exit code 0.

Every destructive test revives its victim before returning — the suite
must pass in any order (DL4J_TPU_TEST_REVERSE=1).
"""
import json
import os
import time
from types import SimpleNamespace

import pytest

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.models.decode import (TransformerDecodeSpec,
                                              naive_generate)
from deeplearning4j_tpu.models.zoo_extra import transformer_lm
from deeplearning4j_tpu.serving.fleet import (FleetCollector, FleetHTTPServer,
                                              FleetRouter, ReplicaProcess)
from deeplearning4j_tpu.serving.fleet.collector import FRONT_DOOR
from deeplearning4j_tpu.telemetry import MetricsRegistry
from deeplearning4j_tpu.telemetry.flightrec import get_flight_recorder
from deeplearning4j_tpu.telemetry.spool import read_spool
from deeplearning4j_tpu.util.httpjson import HTTPClient

# big enough that a 200-token decode takes tens of ms on CPU — the chaos
# test needs the SIGKILL to land while tokens are still being produced,
# and a d16/1-block LM finishes the whole stream inside the kill latency
MODEL_KW = dict(vocab_size=64, d_model=64, n_heads=4, n_blocks=2,
                max_length=256, seed=7, dtype="float32", token_input=True)
GEN_KW = dict(block_len=16, max_seq_len=224, decode_slots=2,
              prefill_batches=[1], num_blocks=32, queue_limit=64)


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    work = tmp_path_factory.mktemp("fleet")
    spec = {"model": {"zoo": "transformer_lm", "kwargs": MODEL_KW},
            "model_name": "lm", "generation": GEN_KW,
            "compile_cache": str(work / "cache")}
    procs = {rid: ReplicaProcess(spec, rid, workdir=str(work))
             for rid in ("f0", "f1")}
    router = FleetRouter(policy="affinity", health_period_s=0.1).start()
    front = FleetHTTPServer(router)
    client = HTTPClient(max_per_host=4, timeout=60.0)
    try:
        for rid in ("f0", "f1"):
            router.add_process(procs[rid], wait_ready=True, timeout=240.0)
        base = f"http://127.0.0.1:{front.start()}"
        yield SimpleNamespace(work=work, spec=spec, procs=procs,
                              router=router, front=front, base=base,
                              client=client)
    finally:
        client.close()
        front.stop(close_router=True)   # drain-stops every live replica


def _revive(fleet, rid):
    """Restore the 2-replica fixture state after a destructive test."""
    proc = fleet.procs[rid]
    fleet.router.remove_replica(rid)
    if proc.alive:
        proc.kill()
    proc.restart()
    fleet.router.add_process(proc, wait_ready=True, timeout=240.0)


def _net():
    return transformer_lm(**MODEL_KW).init()


def _stream_lines(fleet, payload, on_line=None):
    body = json.dumps(payload).encode()
    lines = []
    with fleet.client.stream("POST", fleet.base + "/generate", body=body,
                             headers={"Content-Type": "application/json"},
                             timeout=120.0) as resp:
        assert resp.status == 200
        for raw in resp:
            if not raw.strip():
                continue
            obj = json.loads(raw)
            lines.append(obj)
            if on_line is not None:
                on_line(obj)
    return lines


def _blocking(fleet, payload, model=None):
    path = "/generate" + (f"/{model}" if model else "")
    return fleet.client.request_json(
        "POST", fleet.base + path, payload={**payload, "stream": False},
        timeout=120.0)


def _wait_state(router, rid, state, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        rows = {r["id"]: r for r in router.replicas()}
        if rows.get(rid, {}).get("state") == state:
            return True
        time.sleep(0.05)
    return False


# -------------------------------------------------------------- readiness
def test_readiness_gate_and_health_steering(fleet):
    for rid, proc in fleet.procs.items():
        info = proc.ready_info
        assert info["port"] > 0 and info["pid"] > 0
        assert info["ready_s"] > 0
        assert info["cache_dir"] == fleet.spec["compile_cache"]
        assert "fresh_compiles" in info
        # the steering payload the router (and autoscaler) steer on
        status, health = fleet.client.request_json(
            "GET", proc.base_url + "/health", timeout=10.0)
        assert status == 200
        s = health["steering"]
        for key in ("queue_depth", "in_flight", "slot_occupancy",
                    "block_pool_free_frac", "prefix_hit_rate",
                    "prefix_lookups", "block_len"):
            assert key in s, key
        assert s["block_len"] == 16
        assert health["replica"]["id"] == rid
    # front door aggregates
    status, body = fleet.client.request_json(
        "GET", fleet.base + "/health", timeout=10.0)
    assert status == 200 and body["ready"] == 2
    assert body["states"] == {"f0": "ready", "f1": "ready"}
    status, m = fleet.client.request_json(
        "GET", fleet.base + "/metrics", timeout=30.0)
    assert status == 200 and m["policy"] == "affinity"
    assert set(m["replicas"]) == {"f0", "f1"}
    assert set(m["replica_metrics"]) <= {"f0", "f1"}
    assert fleet.router.block_len == 16     # adopted from steering


# ------------------------------------------------------------ correctness
def test_front_door_matches_single_process_reference(fleet):
    net = _net()
    prompt = list(range(2, 18))
    want = naive_generate(net, prompt, 8, pad_to=64,
                          spec=TransformerDecodeSpec(net))
    lines = _stream_lines(fleet, {"prompt": prompt, "max_tokens": 8})
    toks = [l["token"] for l in lines if "token" in l]
    assert toks == want
    done = lines[-1]
    assert done["done"] and done["reason"] == "length"
    assert done["replica"] in ("f0", "f1")
    # blocking rides the same affinity: same tokens, same replica
    status, body = _blocking(fleet, {"prompt": prompt, "max_tokens": 8})
    assert status == 200
    assert body["tokens"] == want
    assert body["replica"] == done["replica"]


# ------------------------------------------------------------------ chaos
def test_sigkill_loses_only_the_inflight_stream(fleet):
    prompt = [5, 9, 13, 17] * 6        # 24 tokens: one full 16-block
    _, probe = _blocking(fleet, {"prompt": prompt, "max_tokens": 2})
    victim = probe["replica"]
    survivor = "f1" if victim == "f0" else "f0"
    try:
        killed = []

        def kill_at_first_token(obj):
            if "token" in obj and not killed:
                killed.append(True)
                fleet.router.kill_replica(victim)

        lines = _stream_lines(fleet,
                              {"prompt": prompt, "max_tokens": 200},
                              on_line=kill_at_first_token)
        done = lines[-1]
        assert done["done"] is True
        # the contract: the stream is CLOSED with an explicit reason, and
        # only this stream is lost — nothing replays after first token
        assert done["reason"] == "replica_lost"
        assert done["replica"] == victim
        n_tokens = sum(1 for l in lines if "token" in l)
        assert done["tokens"] == n_tokens
        assert n_tokens < 200
        # router notices on its own (poller) and marks the victim dead
        assert _wait_state(fleet.router, victim, "dead", timeout=10.0)
        # the black box: a fleet_replica_lost dump naming the victim
        dump_dir = get_flight_recorder().directory
        dumps = [f for f in os.listdir(dump_dir)
                 if "fleet_replica_lost" in f]
        assert dumps
        assert any(json.load(open(os.path.join(dump_dir, f)))
                   ["info"].get("replica") == victim for f in dumps)
        # survivors keep serving
        status, body = _blocking(fleet, {"prompt": prompt,
                                         "max_tokens": 4})
        assert status == 200 and body["replica"] == survivor
    finally:
        _revive(fleet, victim)


def test_pre_first_token_kill_replays_idempotently(fleet):
    """Kill the affinity target BEFORE the request: the router fails over
    and the client sees one clean greedy sequence — the retry-idempotency
    pin — plus the fleet.retry trace marker."""
    prompt = [3, 6, 9, 12] * 5          # distinct prefix from other tests
    _, probe = _blocking(fleet, {"prompt": prompt, "max_tokens": 2})
    victim = probe["replica"]
    net = _net()
    want = naive_generate(net, prompt, 6, pad_to=64,
                          spec=TransformerDecodeSpec(net))
    fleet.router.stop()                 # freeze state: victim stays READY
    reg = MetricsRegistry(enabled=True)
    prev = telemetry.set_registry(reg)
    try:
        fleet.procs[victim].kill()
        lines = list(fleet.router.stream_generate(
            {"prompt": prompt, "max_tokens": 6}))
        toks = [l["token"] for l in lines if "token" in l]
        assert toks == want             # never partial, never double
        done = lines[-1]
        assert done["reason"] == "length"
        assert done["replica"] != victim
        assert done["retries"] >= 1
        names = [e["name"] for e in reg.trace_events()]
        assert "fleet.retry" in names
    finally:
        telemetry.set_registry(prev)
        fleet.router.start()
        _revive(fleet, victim)


# ---------------------------------------------------------- observability
def test_cross_process_trace_stitching(fleet, tmp_path):
    """ISSUE 19 acceptance: ONE X-Trace-Id through front door -> router ->
    replica subprocess comes back as a single ts-ordered timeline with
    per-process replica attribution — front-door spans from the local
    ring, replica spans pulled over /debug/trace — and trace2timeline
    renders the same stitched view."""
    from tools.trace2timeline import format_timeline, load_merged, timeline
    tid = "feedface2026"
    reg = MetricsRegistry(enabled=True)
    prev = telemetry.set_registry(reg)
    col = FleetCollector(fleet.router, registry=reg)
    try:
        status, body = fleet.client.request_json(
            "POST", fleet.base + "/generate",
            payload={"prompt": [2, 4, 6, 8], "max_tokens": 3,
                     "stream": False},
            headers={"X-Trace-Id": tid}, timeout=120.0)
        assert status == 200
        rid = body["replica"]
        assert col.pull_once() > 0 and col.pull_errors == 0
        events = col.events_for_trace(tid)
        replicas = {e["args"]["replica"] for e in events}
        assert {FRONT_DOOR, rid} <= replicas    # both processes present
        names = [e["name"] for e in events]
        assert any(n.startswith("fleet.") for n in names)       # front
        assert any(n.startswith("generation.") for n in names)  # replica
        ts = [e["ts"] for e in events]
        assert ts == sorted(ts)         # epoch-anchored cross-process order
        # receipt at the front door precedes the replica's work (epoch-
        # anchored ts makes cross-process ordering meaningful; fleet.route
        # is recorded when the forward RESOLVES, so it lands later)
        assert names.index("fleet.request") \
            < min(i for i, n in enumerate(names)
                  if n.startswith("generation."))
        # same timeline through the offline tool
        f = tmp_path / "stitched.json"
        f.write_text(json.dumps({"events": events}))
        rows = timeline(load_merged([str(f)]), tid)
        assert [r["name"] for r in rows] == names
        text = format_timeline(rows)
        assert "replica" in text.splitlines()[0] and rid in text
    finally:
        col.stop()
        telemetry.set_registry(prev)


def test_sigkill_black_box_recovered_from_spool(fleet):
    """ISSUE 19 acceptance, crash-durability half: SIGKILL a replica and
    its last periodic spool spill still tells the story — readable from
    disk, embedded as ``victim_spill`` in the fleet_replica_lost dump,
    and ingested by the collector so the victim's spans stitch into the
    fleet timeline after death."""
    tid = "cafebabe2026"
    reg = MetricsRegistry(enabled=True)
    prev = telemetry.set_registry(reg)
    col = FleetCollector(fleet.router, registry=reg)
    victim = None
    try:
        status, body = fleet.client.request_json(
            "POST", fleet.base + "/generate",
            payload={"prompt": [4, 8, 12, 16], "max_tokens": 3,
                     "stream": False},
            headers={"X-Trace-Id": tid}, timeout=120.0)
        assert status == 200
        victim = body["replica"]
        time.sleep(0.8)                 # > 2 spool periods: spill lands
        fleet.router.kill_replica(victim)
        assert _wait_state(fleet.router, victim, "dead", timeout=10.0)
        # the black box on disk outlived the process
        spill = read_spool(fleet.procs[victim].spool_path)
        assert spill is not None and spill["replica"] == victim
        assert spill["pid"] > 0 and spill["seq"] > 0
        assert any(e.get("args", {}).get("trace_id") == tid
                   for e in spill["events"])
        # ...and is embedded in the fleet_replica_lost dump
        dump_dir = get_flight_recorder().directory
        embedded = []
        for fn in os.listdir(dump_dir):
            if "fleet_replica_lost" not in fn:
                continue
            info = json.load(open(os.path.join(dump_dir, fn)))["info"]
            if info.get("replica") == victim and info.get("victim_spill"):
                embedded.append(info["victim_spill"])
        assert any(any(e.get("args", {}).get("trace_id") == tid
                       for e in s.get("events", []))
                   for s in embedded), "no dump embeds the victim's spill"
        # the collector recovers the victim's spans from the spool
        col.pull_once()
        assert col.spools_recovered >= 1
        events = col.events_for_trace(tid)
        assert any(e["args"]["replica"] == victim
                   and e["name"].startswith("generation.")
                   for e in events)
    finally:
        col.stop()
        telemetry.set_registry(prev)
        if victim is not None:
            _revive(fleet, victim)


@pytest.mark.slow
def test_chaos_soak_kill_revive_rounds(fleet):
    """Three kill/recover rounds: every lost stream closes with a reason,
    the fleet returns to full strength each time."""
    for round_i in range(3):
        prompt = [7 + round_i, 11, 19, 23] * 5
        _, probe = _blocking(fleet, {"prompt": prompt, "max_tokens": 2})
        victim = probe["replica"]
        try:
            killed = []

            def kill_once(obj, victim=victim, killed=killed):
                if "token" in obj and not killed:
                    killed.append(True)
                    fleet.router.kill_replica(victim)

            lines = _stream_lines(fleet,
                                  {"prompt": prompt, "max_tokens": 200},
                                  on_line=kill_once)
            assert lines[-1]["done"] is True
            assert lines[-1]["reason"] in ("replica_lost", "length")
            assert _wait_state(fleet.router, victim, "dead", timeout=10.0)
        finally:
            _revive(fleet, victim)
        status, _ = fleet.client.request_json(
            "GET", fleet.base + "/health", timeout=10.0)
        assert status == 200
        assert fleet.router.ready_count() == 2


# -------------------------------------------------------------- elasticity
def test_warm_cache_replica_joins_and_drains_out(fleet):
    """The autoscaler's scale-out path: a third replica pointed at the
    WARM shared compilation cache must reach ready as load-not-compile —
    zero fresh backend compiles — and scale-in must drain, not drop."""
    f2 = ReplicaProcess(fleet.spec, "f2", workdir=str(fleet.work))
    added = False
    try:
        fleet.router.add_process(f2, wait_ready=True, timeout=240.0)
        added = True
        # the cold-start acceptance: load, don't compile
        assert f2.ready_info["fresh_compiles"] == 0
        assert f2.ready_info["cache_hits"] > 0
        assert fleet.router.ready_count() == 3
        assert fleet.router.drain_replica("f2", timeout=20.0) is True
        added = False
        assert {r["id"] for r in fleet.router.replicas()} \
            == {"f0", "f1"}
        assert f2.proc.returncode == 0      # SIGTERM -> drain -> clean exit
    finally:
        if added:
            fleet.router.remove_replica("f2")
        if f2.alive:
            f2.terminate(drain=False)


@pytest.mark.slow
def test_orphaned_replica_exits_when_supervisor_is_killed(fleet):
    """SIGKILL the SUPERVISOR (not the replica): the child gets no signal
    (own session), so without the ppid orphan watchdog it would serve
    nobody forever — the leak this pin exists to prevent."""
    import signal
    import subprocess
    import sys
    import textwrap
    spec_path = str(fleet.work / "orphan.spec.json")
    with open(spec_path, "w") as f:
        json.dump(fleet.spec, f)        # warm shared cache: fast ready
    script = textwrap.dedent("""
        import json, sys, time
        from deeplearning4j_tpu.serving.fleet import ReplicaProcess
        spec = json.load(open(sys.argv[1]))
        p = ReplicaProcess(spec, "orphan", workdir=sys.argv[2]).start()
        p.wait_ready(timeout=240.0)
        print(json.dumps({"replica_pid": p.pid}), flush=True)
        time.sleep(600)                 # hang until SIGKILLed
    """)
    env = {**os.environ,
           "PYTHONPATH": os.pathsep.join(
               [os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                os.environ.get("PYTHONPATH", "")])}
    sup = subprocess.Popen(
        [sys.executable, "-c", script, spec_path, str(fleet.work)],
        stdout=subprocess.PIPE, env=env)
    try:
        replica_pid = json.loads(sup.stdout.readline())["replica_pid"]
        os.kill(replica_pid, 0)         # alive under a live supervisor
        sup.send_signal(signal.SIGKILL)
        sup.wait()
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            try:
                os.kill(replica_pid, 0)
            except ProcessLookupError:
                break                   # orphan noticed the reparent, exited
            time.sleep(0.25)
        else:
            os.kill(replica_pid, signal.SIGKILL)
            pytest.fail("orphaned replica still alive 15s after its "
                        "supervisor was SIGKILLed")
    finally:
        if sup.poll() is None:
            sup.kill()
            sup.wait()


def test_compile_cache_env_knob(tmp_path, monkeypatch):
    """DL4J_TPU_COMPILE_CACHE drives jax's persistent compilation cache;
    '0' (or empty) disables. Restores the process-global jax config."""
    import jax

    from deeplearning4j_tpu.serving.fleet import coldstart
    old_dir = jax.config.jax_compilation_cache_dir
    old_configured = coldstart._configured_dir
    cache = str(tmp_path / "cc")
    try:
        monkeypatch.setenv(coldstart.ENV_CACHE, cache)
        assert coldstart.configure_compile_cache() == cache
        assert jax.config.jax_compilation_cache_dir == cache
        assert coldstart.configured_cache_dir() == cache
        assert os.path.isdir(cache)
        monkeypatch.setenv(coldstart.ENV_CACHE, "0")
        assert coldstart.configure_compile_cache() is None
        # explicit path beats the env var
        explicit = str(tmp_path / "explicit")
        assert coldstart.configure_compile_cache(explicit) == explicit
    finally:
        jax.config.update("jax_compilation_cache_dir", old_dir)
        coldstart._configured_dir = old_configured
    snap = coldstart.snapshot()
    assert {"compiles", "cache_hits", "fresh_compiles"} <= set(snap)
    assert snap["fresh_compiles"] >= 0
