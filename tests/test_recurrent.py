"""Recurrent tests: LSTM gradient checks incl. masking (mirror reference
LSTMGradientCheckTests, GradientCheckTestsMasking), rnn_time_step streaming
consistency, tBPTT training (SURVEY.md §7 stage 6)."""
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import (DenseLayer, GlobalPoolingLayer,
                                          GravesBidirectionalLSTM, GravesLSTM,
                                          LSTM, LastTimeStepLayer, OutputLayer,
                                          RnnOutputLayer)
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd
from deeplearning4j_tpu.util.gradcheck import check_gradients

R = np.random.default_rng(99)


def _rnn_net(layers, dtype="float32", updater=None, tbptt=None, seed=12345):
    b = NeuralNetConfiguration(seed=seed, updater=updater or Sgd(0.1),
                               dtype=dtype).list(*layers)
    b = b.set_input_type(InputType.recurrent(3, 8))
    if tbptt:
        b = b.tbptt_length(tbptt)
    return MultiLayerNetwork(b.build()).init()


def _seq_data(n=4, t=8, f=3, c=2, seed=1):
    r = np.random.default_rng(seed)
    x = r.normal(size=(n, t, f))
    yi = (x.sum((1, 2)) > 0).astype(int)
    y_seq = np.eye(c)[np.tile(yi[:, None], (1, t))]
    y_last = np.eye(c)[yi]
    return x, y_seq, y_last


# GravesLSTM alone rides the slow lane (ISSUE 19 tier-1 budget reclaim,
# ~8s): the Graves cell math is still gradient-checked tier-1 through the
# GravesBidirectionalLSTM variant, which wraps the same cell.
@pytest.mark.parametrize("layer_cls", [
    LSTM,
    pytest.param(GravesLSTM, marks=pytest.mark.slow),
    GravesBidirectionalLSTM,
])
def test_lstm_gradient_checks(layer_cls):
    net = _rnn_net([layer_cls(n_out=4, activation="tanh"),
                    RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
                   dtype="float64")
    x, y_seq, _ = _seq_data()
    assert check_gradients(net, x, y_seq, print_results=True)


def test_lstm_masking_gradient_check():
    net = _rnn_net([GravesLSTM(n_out=4, activation="tanh"),
                    RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
                   dtype="float64")
    x, y_seq, _ = _seq_data()
    mask = np.ones((4, 8))
    mask[1, 5:] = 0.0
    mask[3, 2:] = 0.0
    assert check_gradients(net, x, y_seq, labels_mask=mask, features_mask=mask,
                           print_results=True)


def test_lstm_global_pooling_gradient_check():
    net = _rnn_net([LSTM(n_out=4, activation="tanh"),
                    GlobalPoolingLayer(pooling_type="avg"),
                    OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
                   dtype="float64")
    x, _, y_last = _seq_data()
    assert check_gradients(net, x, y_last, print_results=True)


def test_rnn_time_step_matches_full_sequence():
    net = _rnn_net([GravesLSTM(n_out=5, activation="tanh"),
                    RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")])
    x, _, _ = _seq_data(n=2, t=8)
    x = x.astype(np.float32)
    full = np.asarray(net.output(x))                   # [B,T,C]
    net.rnn_clear_previous_state()
    step_outs = []
    for t in range(8):
        step_outs.append(np.asarray(net.rnn_time_step(x[:, t])))
    stepped = np.stack(step_outs, axis=1)
    assert np.allclose(full, stepped, atol=1e-5), np.abs(full - stepped).max()
    # clearing state restarts the stream
    net.rnn_clear_previous_state()
    again = np.asarray(net.rnn_time_step(x[:, 0]))
    assert np.allclose(again, step_outs[0], atol=1e-6)


def test_tbptt_training_runs_and_learns():
    x, y_seq, _ = _seq_data(n=16, t=8, seed=3)
    x = x.astype(np.float32)
    y_seq = y_seq.astype(np.float32)
    net = _rnn_net([GravesLSTM(n_out=8, activation="tanh"),
                    RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
                   updater=Adam(1e-2), tbptt=4)
    assert net.conf.backprop_type == "tbptt"
    s0 = net.score(x, y_seq)
    net.fit(x, y_seq, epochs=20, batch_size=16)
    assert net.score(x, y_seq) < s0
    assert net.iteration_count == 40  # 2 chunks per batch * 20 epochs


def test_last_time_step_layer():
    net = _rnn_net([LSTM(n_out=4, activation="tanh"),
                    LastTimeStepLayer(),
                    OutputLayer(n_out=2, activation="softmax", loss="mcxent")])
    x, _, y_last = _seq_data()
    out = np.asarray(net.output(x.astype(np.float32)))
    assert out.shape == (4, 2)
    net.fit(x.astype(np.float32), y_last.astype(np.float32), epochs=2)


def test_dense_is_time_distributed():
    """Dense on [B,T,F] applies per timestep (equivalent to the reference's
    RnnToFeedForward sandwich)."""
    net = _rnn_net([DenseLayer(n_out=6, activation="tanh"),
                    RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")])
    x, _, _ = _seq_data()
    out = np.asarray(net.output(x.astype(np.float32)))
    assert out.shape == (4, 8, 2)
