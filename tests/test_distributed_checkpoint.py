"""Sharded checkpoint/restart on the 8-device virtual mesh.

Reference capability: the Spark driver always holds resumable mid-run state
(ParameterAveragingTrainingWorker.java:269; SURVEY.md §5.3-5.4). Here: save
the sharded train state mid-run, throw the run away, restore on a fresh
mesh state, continue — subsequent params must be bit-identical to an
uninterrupted run.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.util.distributed_checkpoint import (
    DistributedCheckpointer, latest_sharded_step, list_sharded_checkpoints,
    restore_sharded_checkpoint, save_sharded_checkpoint)


def _mesh22():
    return make_mesh((4, 2), ("data", "model"), devices=jax.devices())


def test_round_trip_mixed_specs(tmp_path):
    """Sharded, replicated, and mixed leaves all round-trip exactly."""
    mesh = _mesh22()
    r = np.random.default_rng(0)
    tree = {
        "w_model": jax.device_put(r.normal(size=(8, 6)).astype(np.float32),
                                  NamedSharding(mesh, P(None, "model"))),
        "w_data": jax.device_put(r.normal(size=(8, 6)).astype(np.float32),
                                 NamedSharding(mesh, P("data"))),
        "w_both": jax.device_put(r.normal(size=(8, 6)).astype(np.float32),
                                 NamedSharding(mesh, P("data", "model"))),
        "b_rep": jax.device_put(r.normal(size=(6,)).astype(np.float32),
                                NamedSharding(mesh, P())),
        "it": jax.device_put(jnp.asarray(7, jnp.int32),
                             NamedSharding(mesh, P())),
    }
    save_sharded_checkpoint(str(tmp_path), 3, tree)
    assert latest_sharded_step(str(tmp_path)) == 3

    like = jax.tree.map(lambda a: jax.device_put(jnp.zeros_like(a),
                                                 a.sharding), tree)
    got = restore_sharded_checkpoint(str(tmp_path), 3, like)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(tree[k]), err_msg=k)
        assert got[k].sharding.is_equivalent_to(tree[k].sharding,
                                               np.asarray(tree[k]).ndim)


def test_shape_and_leafcount_mismatch_raise(tmp_path):
    mesh = _mesh22()
    rep = NamedSharding(mesh, P())
    tree = {"a": jax.device_put(jnp.ones((4, 4)), rep)}
    save_sharded_checkpoint(str(tmp_path), 1, tree)
    with pytest.raises(ValueError, match="leaves"):
        restore_sharded_checkpoint(
            str(tmp_path), 1,
            {"a": jax.device_put(jnp.ones((4, 4)), rep),
             "b": jax.device_put(jnp.ones((4, 4)), rep)})
    with pytest.raises(ValueError, match="leaf 0"):
        restore_sharded_checkpoint(
            str(tmp_path), 1, {"a": jax.device_put(jnp.ones((2, 4)), rep)})


def _sharded_train_state(net, mesh):
    rep = NamedSharding(mesh, P())
    put = lambda t: jax.tree.map(lambda a: jax.device_put(jnp.asarray(a), rep), t)
    return {"params": put(net.params), "opt": put(net.opt_state),
            "it": jax.device_put(jnp.asarray(0, jnp.int32), rep)}


def _make_step(net, mesh):
    rep = NamedSharding(mesh, P())
    dsh = NamedSharding(mesh, P("data"))

    net_state = net.state

    @jax.jit
    def step(ts, x, y):
        def lf(p):
            return net.loss_fn(p, net_state, x, y, train=True, rng=None)[0]
        grads = jax.grad(lf)(ts["params"])
        new_p, new_o = net.updater.update(grads, ts["opt"], ts["params"],
                                          ts["it"])
        return {"params": new_p, "opt": new_o, "it": ts["it"] + 1}

    def run(ts, x, y):
        return step(ts, jax.device_put(x, dsh), jax.device_put(y, rep))
    return run


def test_kill_and_resume_parity(tmp_path):
    """Checkpoint at step 3 of 6; 'kill'; restore into a fresh sharded
    state; steps 4-6 must produce bit-identical params."""
    mesh = _mesh22()
    conf = (NeuralNetConfiguration(seed=5, updater=Adam(1e-2))
            .list(DenseLayer(n_in=4, n_out=16, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    run = _make_step(net, mesh)
    r = np.random.default_rng(1)
    xs = [r.normal(size=(8, 4)).astype(np.float32) for _ in range(6)]
    ys = [np.eye(3, dtype=np.float32)[r.integers(0, 3, 8)] for _ in range(6)]

    ckpt = DistributedCheckpointer(str(tmp_path), every_n_steps=3,
                                   keep_last=2)
    ts = _sharded_train_state(net, mesh)
    uninterrupted = None
    for i in range(6):
        ts = run(ts, xs[i], ys[i])
        ckpt.maybe_save(int(ts["it"]), ts)
    uninterrupted = jax.tree.leaves(ts["params"])

    # ---- the "crash": discard everything; a fresh process re-inits and
    # restores the newest complete checkpoint (step 3)
    net2 = MultiLayerNetwork(conf).init()
    run2 = _make_step(net2, mesh)
    like = _sharded_train_state(net2, mesh)
    step_restored, ts2 = ckpt.restore_latest(like)
    assert step_restored == 6 or step_restored == 3
    # resume from the step BEFORE the crash point: restore newest <= 3 by
    # dropping the step-6 save to simulate dying after step 3
    for s, manifest in list_sharded_checkpoints(str(tmp_path)):
        if s > 3:
            os.unlink(manifest)
    step_restored, ts2 = ckpt.restore_latest(like)
    assert step_restored == 3
    for i in range(3, 6):
        ts2 = run2(ts2, xs[i], ys[i])
    resumed = jax.tree.leaves(ts2["params"])
    for a, b in zip(uninterrupted, resumed):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pruning_keeps_last(tmp_path):
    mesh = _mesh22()
    rep = NamedSharding(mesh, P())
    ckpt = DistributedCheckpointer(str(tmp_path), every_n_steps=1,
                                   keep_last=2)
    tree = {"a": jax.device_put(jnp.ones((4,)), rep)}
    for s in (1, 2, 3, 4):
        ckpt.save(s, tree)
    steps = [s for s, _ in list_sharded_checkpoints(str(tmp_path))]
    assert steps == [3, 4]
    # pruned steps' shard files are gone too
    assert not [n for n in os.listdir(str(tmp_path))
                if n.startswith("ckpt_step1_") or n.startswith("ckpt_step2_")]


def test_bfloat16_leaves_round_trip(tmp_path):
    """np.savez stores ml_dtypes (bfloat16) as raw void bytes; restore must
    view them back — a bf16 net's checkpoint has to be restorable."""
    mesh = _mesh22()
    rep = NamedSharding(mesh, P())
    tree = {"w": jax.device_put(
        jnp.asarray([[1.5, -2.25], [0.375, 8.0]], jnp.bfloat16),
        NamedSharding(mesh, P(None, "model"))),
        "b": jax.device_put(jnp.asarray([0.5, -1.0], jnp.bfloat16), rep)}
    save_sharded_checkpoint(str(tmp_path), 1, tree)
    like = jax.tree.map(lambda a: jax.device_put(jnp.zeros_like(a),
                                                 a.sharding), tree)
    got = restore_sharded_checkpoint(str(tmp_path), 1, like)
    for k in tree:
        assert got[k].dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(got[k], np.float32), np.asarray(tree[k], np.float32))


def test_incomplete_save_falls_back(tmp_path):
    """A manifest whose peer shard files are missing (preemption mid-save
    on a pod) must NOT be picked: latest() skips to the newest COMPLETE
    save."""
    import json

    mesh = _mesh22()
    rep = NamedSharding(mesh, P())
    tree = {"a": jax.device_put(jnp.ones((4,)), rep)}
    ckpt = DistributedCheckpointer(str(tmp_path), keep_last=5)
    ckpt.save(1, tree)
    # forge step 2: a manifest claiming 4 processes, with only p000 present
    save_sharded_checkpoint(str(tmp_path), 2, tree)
    mpath = tmp_path / "ckpt_step2.json"
    m = json.loads(mpath.read_text())
    m["num_processes"] = 4
    mpath.write_text(json.dumps(m))
    assert ckpt.latest() == 1
    step, got = ckpt.restore_latest(
        {"a": jax.device_put(jnp.zeros((4,)), rep)})
    assert step == 1
    np.testing.assert_array_equal(np.asarray(got["a"]), np.ones((4,)))


def test_prune_never_deletes_only_complete_save(tmp_path):
    """Incomplete saves must not count toward keep_last: with keep_last=2,
    one complete save + newer incomplete ones, pruning keeps the complete
    save (deleting it would leave nothing restorable)."""
    import json

    mesh = _mesh22()
    rep = NamedSharding(mesh, P())
    tree = {"a": jax.device_put(jnp.ones((4,)), rep)}
    ckpt = DistributedCheckpointer(str(tmp_path), keep_last=2)
    ckpt.save(200, tree)
    # forge TWO newer incomplete saves (manifest claims 4 processes)
    for s in (300, 400):
        save_sharded_checkpoint(str(tmp_path), s, tree)
        mpath = tmp_path / f"ckpt_step{s}.json"
        m = json.loads(mpath.read_text())
        m["num_processes"] = 4
        mpath.write_text(json.dumps(m))
    ckpt._prune()
    assert ckpt.latest() == 200          # the complete save survives
    # an OLD incomplete save (stale garbage below the newest kept) is removed
    save_sharded_checkpoint(str(tmp_path), 100, tree)
    mpath = tmp_path / "ckpt_step100.json"
    m = json.loads(mpath.read_text())
    m["num_processes"] = 4
    mpath.write_text(json.dumps(m))
    ckpt._prune()
    assert not (tmp_path / "ckpt_step100.json").exists()
    assert ckpt.latest() == 200
