"""Mixed-precision training (compute_dtype='bfloat16' with f32 master
params) — net-new beyond the reference (ND4J-era DL4J has no AMP); on TPU
it is the standard training recipe: bf16 MXU compute, f32 master weights
and updater state."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.nn.graph.graph import ComputationGraph
from deeplearning4j_tpu.nn.graph.vertices import MergeVertex
from deeplearning4j_tpu.nn.layers import (BatchNormalization,
                                          ConvolutionLayer, DenseLayer, LSTM,
                                          OutputLayer, RnnOutputLayer,
                                          SubsamplingLayer)
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd

R = np.random.default_rng(21)


def _xor_data(n=256):
    x = R.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] * x[:, 1] > 0).astype(int)]
    return x, y


def test_mln_amp_trains_with_f32_master_params():
    conf = (NeuralNetConfiguration(seed=1, updater=Adam(5e-3),
                                   dtype="float32", compute_dtype="bfloat16")
            .list(DenseLayer(n_in=4, n_out=32, activation="tanh"),
                  DenseLayer(n_out=32, activation="relu"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    # master params are f32
    assert all(v.dtype == jnp.float32 for p in net.params for v in p.values())
    x, y = _xor_data()
    s0 = net.score(x, y)
    net.fit(x, y, epochs=30, batch_size=64)
    assert net.score(x, y) < s0 * 0.7
    # ... and STAY f32 after jitted donated training steps
    assert all(v.dtype == jnp.float32 for p in net.params for v in p.values())
    assert net.evaluate(x, y).accuracy() > 0.8


def test_amp_gradients_are_f32_and_track_full_precision():
    conf_kw = dict(seed=3, updater=Sgd(0.1), dtype="float32")
    layers = lambda: (DenseLayer(n_in=4, n_out=16, activation="tanh"),
                      OutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
    amp = MultiLayerNetwork(
        NeuralNetConfiguration(compute_dtype="bfloat16", **conf_kw)
        .list(*layers()).build()).init()
    full = MultiLayerNetwork(
        NeuralNetConfiguration(**conf_kw).list(*layers()).build()).init()
    full.set_params_flat(amp.params_flat())

    x, y = _xor_data(64)

    def grads_of(net):
        g = jax.grad(lambda p: net.loss_fn(p, net.state, x, y,
                                           train=False)[0])(net.params)
        return g

    g_amp = grads_of(amp)
    # master gradients come back f32 (the cast's VJP casts back)
    assert all(v.dtype == jnp.float32 for p in g_amp for v in p.values())
    g_full = grads_of(full)
    fa = np.concatenate([np.ravel(v) for p in g_amp for v in p.values()])
    ff = np.concatenate([np.ravel(v) for p in g_full for v in p.values()])
    denom = np.maximum(np.abs(ff), 1e-2)
    assert float((np.abs(fa - ff) / denom).mean()) < 0.05


def test_amp_cnn_batchnorm_state_stays_f32():
    conf = (NeuralNetConfiguration(seed=5, updater=Adam(1e-3),
                                   dtype="float32", compute_dtype="bfloat16")
            .list(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                   convolution_mode="same", activation="relu"),
                  BatchNormalization(),
                  SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 1)).build())
    net = MultiLayerNetwork(conf).init()
    x = R.normal(size=(16, 8, 8, 1)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[R.integers(0, 3, 16)]
    net.fit(x, y, epochs=3, batch_size=16)
    # BN running stats stored at master precision
    bn_state = net.state[1]
    assert all(v.dtype == jnp.float32 for v in bn_state.values())
    out = np.asarray(net.output(x))
    assert np.isfinite(out).all() and out.shape == (16, 3)


def test_amp_lstm_rides_fused_kernel(monkeypatch):
    """bf16 compute_dtype feeds the LSTM the bf16 fused kernel path."""
    conf = (NeuralNetConfiguration(seed=7, updater=Sgd(0.1), dtype="float32",
                                   compute_dtype="bfloat16")
            .list(LSTM(n_out=128, activation="tanh"),
                  RnnOutputLayer(n_out=5, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(5, 6)).build())
    x = R.normal(size=(16, 6, 5)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[R.integers(0, 5, (16, 6))]
    scores = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("DL4J_TPU_FUSED_LSTM", flag)
        net = MultiLayerNetwork(conf).init()
        s0 = net.score(x, y)
        net.fit(x, y, epochs=3, batch_size=16)
        scores[flag] = net.score(x, y)
        assert scores[flag] < s0
        assert all(v.dtype == jnp.float32 for p in net.params
                   for v in p.values())
    assert np.isclose(scores["1"], scores["0"], rtol=0.05)


def test_amp_computation_graph_and_serde():
    b = (NeuralNetConfiguration(seed=9, updater=Adam(5e-3), dtype="float32",
                                compute_dtype="bfloat16")
         .graph_builder()
         .add_inputs("in")
         .add_layer("d1", DenseLayer(n_out=16, activation="tanh"), "in")
         .add_layer("d2", DenseLayer(n_out=16, activation="relu"), "in")
         .add_vertex("m", MergeVertex(), "d1", "d2")
         .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"), "m")
         .set_outputs("out").set_input_types(InputType.feed_forward(4)))
    net = ComputationGraph(b.build()).init()
    x, y = _xor_data(128)
    s0 = net.score(x, y)
    net.fit(x, y, epochs=20, batch_size=64)
    assert net.score(x, y) < s0
    assert all(v.dtype == jnp.float32 for p in net.params for v in p.values())
    # compute_dtype survives the config JSON round trip
    from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration
    conf2 = ComputationGraphConfiguration.from_json(net.conf.to_json())
    assert conf2.compute_dtype == "bfloat16"


def test_amp_outputs_are_master_dtype_and_bn_stats_full_precision():
    """The public API stays f32 under AMP (outputs/evaluate), and BN running
    stats accumulate at FULL precision (not bf16-requantized each step)."""
    conf = (NeuralNetConfiguration(seed=11, updater=Sgd(0.05),
                                   dtype="float32", compute_dtype="bfloat16")
            .list(DenseLayer(n_in=4, n_out=16, activation="tanh"),
                  BatchNormalization(),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x, y = _xor_data(64)
    out = net.output(x)
    assert out.dtype == jnp.float32          # API dtype contract

    # precision check FROM SHARED FRESH STATE: one train-mode forward updates
    # the EMA once on both an AMP and a full-precision net with identical
    # params; the f32 accumulator must track the f32 run to ~bf16 forward
    # noise, and stay stored at f32
    full = MultiLayerNetwork(
        NeuralNetConfiguration(seed=11, updater=Sgd(0.05), dtype="float32")
        .list(DenseLayer(n_in=4, n_out=16, activation="tanh"),
              BatchNormalization(),
              OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .build()).init()
    full.set_params_flat(net.params_flat())
    _, s_amp = net.apply_fn(net.params, net.state, jnp.asarray(x), train=True)
    _, s_full = full.apply_fn(full.params, full.state, jnp.asarray(x),
                              train=True)
    assert s_amp[1]["mean"].dtype == jnp.float32
    a, f = np.asarray(s_amp[1]["mean"]), np.asarray(s_full[1]["mean"])
    denom = np.maximum(np.abs(f), 1e-3)
    assert float((np.abs(a - f) / denom).mean()) < 0.02, (a, f)


def test_amp_composes_with_parallel_wrapper():
    """AMP + per-step psum DP on the 8-device mesh: f32 masters replicated,
    bf16 compute, training improves."""
    from deeplearning4j_tpu.datasets import ListDataSetIterator
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper

    conf = (NeuralNetConfiguration(seed=13, updater=Adam(5e-3),
                                   dtype="float32", compute_dtype="bfloat16")
            .list(DenseLayer(n_in=4, n_out=16, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x, y = _xor_data(128)
    s0 = net.score(x, y)
    pw = ParallelWrapper(net, workers=8, training_mode="shared_gradients")
    pw.fit(ListDataSetIterator(features=x, labels=y, batch_size=64), epochs=15)
    assert net.score(x, y) < s0
    assert all(v.dtype == jnp.float32 for p in net.params for v in p.values())


def test_amp_tbptt_trains():
    """tBPTT chunked training under AMP: rnn carries cross chunk boundaries
    at master precision, loss decreases."""
    conf = (NeuralNetConfiguration(seed=17, updater=Adam(5e-3),
                                   dtype="float32", compute_dtype="bfloat16")
            .list(LSTM(n_out=16, activation="tanh"),
                  RnnOutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(4, 12))
            .tbptt_length(4).build())
    net = MultiLayerNetwork(conf).init()
    ids = R.integers(0, 4, (8, 12))
    x = np.eye(4, dtype=np.float32)[ids]
    y = np.eye(4, dtype=np.float32)[np.roll(ids, -1, axis=1)]
    s0 = net.score(x, y)
    net.fit(x, y, epochs=8, batch_size=8)
    assert net.score(x, y) < s0
    assert all(v.dtype == jnp.float32 for p in net.params for v in p.values())
