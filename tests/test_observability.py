"""Observability tier tests: StatsListener -> StatsStorage -> dashboard.

Reference test strategy: deeplearning4j-ui-parent tests (TestStatsListener,
TestStatsStorage) — collect stats from a real training run, round-trip them
through storage, render the UI.
"""
import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Sgd
from deeplearning4j_tpu.ui import (FileStatsStorage, InMemoryStatsStorage,
                                   StatsListener, StatsStorageEvent,
                                   StatsUpdateConfiguration, TrainingUIServer,
                                   render_dashboard)


def _tiny_net(seed=12):
    conf = (NeuralNetConfiguration(seed=seed, updater=Sgd(0.1))
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _toy_data(rng, n=64):
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=n)]
    return x, y


def test_stats_listener_collects_into_memory_storage(rng):
    net = _tiny_net()
    storage = InMemoryStatsStorage()
    cfg = StatsUpdateConfiguration(report_frequency=1, collect_histograms=True,
                                   histogram_bins=10)
    listener = StatsListener(storage, config=cfg, session_id="sess1")
    net.set_listeners(listener)
    x, y = _toy_data(rng)
    net.fit(x, y, epochs=2, batch_size=16)

    assert storage.list_session_ids() == ["sess1"]
    workers = storage.list_worker_ids("sess1")
    assert workers == ["worker_0"]
    static = storage.get_static_info("sess1", "worker_0")
    assert static["model_class"] == "MultiLayerNetwork"
    assert static["num_params"] == 4 * 8 + 8 + 8 * 3 + 3
    assert len(static["param_names"]) == 4  # 0/W 0/b 1/W 1/b

    updates = storage.get_updates("sess1", "worker_0")
    assert len(updates) == 8  # 64/16 * 2 epochs
    u = updates[-1]
    assert "score" in u and np.isfinite(u["score"])
    assert set(u["params"]) == set(static["param_names"])
    pw = u["params"]["0/W"]
    assert {"mean", "stdev", "meanmag", "min", "max"} <= set(pw)
    # histogram counts must account for every element of the leaf
    assert sum(pw["histogram"]["counts"]) == 4 * 8
    # update stats present from the second report on
    assert "updates" in u and u["updates"]["0/W"]["meanmag"] > 0
    # get_updates(since) filters
    later = storage.get_updates("sess1", "worker_0",
                                since_iteration=u["iteration"] - 1)
    assert [v["iteration"] for v in later] == [u["iteration"]]


def test_file_stats_storage_round_trip(tmp_path, rng):
    path = str(tmp_path / "stats.jsonl")
    storage = FileStatsStorage(path)
    net = _tiny_net()
    net.set_listeners(StatsListener(storage, session_id="fsess"))
    x, y = _toy_data(rng, n=32)
    net.fit(x, y, epochs=1, batch_size=16)

    # independent reader process sees the same data (fresh instance, same file)
    reader = FileStatsStorage(path)
    assert reader.list_session_ids() == ["fsess"]
    ups = reader.get_updates("fsess", "worker_0")
    assert len(ups) == 2
    assert reader.get_static_info("fsess", "worker_0")["num_params"] > 0
    # file really is JSON-lines
    with open(path) as f:
        kinds = [json.loads(line)["kind"] for line in f]
    assert kinds[0] == "static" and kinds.count("update") == 2


def test_storage_events_fire(rng):
    storage = InMemoryStatsStorage()
    events = []
    storage.register_listener(lambda ev: events.append(ev.kind))
    storage.put_static_info("s", "w", {"a": 1})
    storage.put_update("s", "w", {"iteration": 0, "score": 1.0})
    assert StatsStorageEvent.NEW_SESSION in events
    assert StatsStorageEvent.POST_UPDATE in events


def test_render_dashboard_artifact(tmp_path, rng):
    net = _tiny_net()
    storage = InMemoryStatsStorage()
    cfg = StatsUpdateConfiguration(collect_histograms=True)
    net.set_listeners(StatsListener(storage, config=cfg, session_id="dash"))
    x, y = _toy_data(rng)
    net.fit(x, y, epochs=1, batch_size=16)

    out = render_dashboard(storage, str(tmp_path / "train.html"))
    html = open(out).read()
    assert "<svg" in html            # charts rendered
    assert "Score vs. iteration" in html
    assert "Parameter histograms" in html
    assert "dash" in html


def test_training_ui_server_serves_live_page(rng):
    net = _tiny_net()
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(storage, session_id="live"))
    x, y = _toy_data(rng, n=32)
    net.fit(x, y, epochs=1, batch_size=16)

    server = TrainingUIServer()
    server.attach(storage)
    port = server.start()
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/", timeout=5) as r:
            body = r.read().decode()
        assert r.status == 200
        assert "Training overview" in body and "live" in body
    finally:
        server.stop()


def test_activation_stats_optional(rng):
    x, y = _toy_data(rng, n=32)
    net = _tiny_net()
    storage = InMemoryStatsStorage()
    cfg = StatsUpdateConfiguration(collect_activation_stats=True)
    net.set_listeners(StatsListener(storage, config=cfg, session_id="act",
                                    activation_sample=x[:8]))
    net.fit(x, y, epochs=1, batch_size=16)
    u = storage.get_latest_update("act", "worker_0")
    assert "activations" in u and len(u["activations"]) >= 2
    assert all(np.isfinite(v) for v in u["activations"].values())


# ---------------------------------------------------------- visual tier (r3)
def test_conv_activation_listener_renders_grids():
    """ConvolutionalIterationListener analogue: activation image grids land
    in the storage and render in the dashboard (reference
    ConvolutionalIterationListener.java; VERDICT r2 missing #6)."""
    import base64
    from deeplearning4j_tpu.models.lenet import lenet
    from deeplearning4j_tpu.ui import (ConvolutionalIterationListener,
                                       InMemoryStatsStorage,
                                       render_dashboard_html)

    net = lenet(n_classes=3, height=12, width=12, channels=1).init()
    store = InMemoryStatsStorage()
    lst = ConvolutionalIterationListener(
        np.random.default_rng(0).normal(size=(2, 12, 12, 1)).astype(np.float32),
        storage=store, frequency=2, session_id="s", worker_id="w")
    net.set_listeners(lst)
    x = np.random.default_rng(1).normal(size=(8, 12, 12, 1)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.random.default_rng(2).integers(0, 3, 8)]
    net.fit(x, y, epochs=4, batch_size=8)

    ups = store.get_updates("s", "w")
    grids = [u for u in ups if u.get("conv_activations")]
    assert grids, "no activation records"
    imgs = grids[-1]["conv_activations"]
    assert len(imgs) >= 2      # two conv layers in LeNet
    png = base64.b64decode(next(iter(imgs.values())))
    assert png[:8] == b"\x89PNG\r\n\x1a\n"
    page = render_dashboard_html(store)
    assert "Convolutional activations" in page
    assert "data:image/png;base64," in page


def test_model_graph_view_in_dashboard():
    """Model-graph/flow view (reference FlowIterationListener +
    TrainModule.java:94-110): the DAG SVG renders from the posted config for
    a branching ComputationGraph and appears in the dashboard."""
    from deeplearning4j_tpu import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.graph.graph import ComputationGraph
    from deeplearning4j_tpu.nn.graph.vertices import MergeVertex
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.ui import (InMemoryStatsStorage, StatsListener,
                                       render_dashboard_html,
                                       render_model_graph_svg)

    b = (NeuralNetConfiguration(seed=5, updater=Sgd(0.1)).graph_builder()
         .add_inputs("in")
         .add_layer("d1", DenseLayer(n_out=8, activation="tanh"), "in")
         .add_layer("d2", DenseLayer(n_out=8, activation="relu"), "in")
         .add_vertex("merge", MergeVertex(), "d1", "d2")
         .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"), "merge")
         .set_outputs("out").set_input_types(InputType.feed_forward(4)))
    net = ComputationGraph(b.build()).init()

    svg = render_model_graph_svg(net.conf)
    for name in ("d1", "d2", "merge", "out"):
        assert name in svg
    assert "MergeVertex" in svg and svg.startswith("<svg")

    store = InMemoryStatsStorage()
    net.set_listeners(StatsListener(store, session_id="s2", worker_id="w"))
    x = np.random.default_rng(3).normal(size=(8, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[np.random.default_rng(4).integers(0, 2, 8)]
    net.fit(x, y, epochs=2, batch_size=8)
    page = render_dashboard_html(store)
    assert "Model graph" in page and "MergeVertex" in page


def test_model_graph_mln_chain():
    from deeplearning4j_tpu.models.lenet import lenet
    from deeplearning4j_tpu.ui import render_model_graph_svg
    svg = render_model_graph_svg(lenet(n_classes=3).conf)
    assert "ConvolutionLayer" in svg and "OutputLayer" in svg


def test_tsne_page_renders(tmp_path):
    """t-SNE page (reference play tsne module)."""
    from deeplearning4j_tpu.ui import render_tsne
    rng = np.random.default_rng(5)
    coords = np.vstack([rng.normal(0, 1, (20, 2)),
                        rng.normal(6, 1, (20, 2))])
    labels = ["a"] * 20 + ["b"] * 20
    p = render_tsne(coords, str(tmp_path / "tsne.html"), labels)
    page = open(p).read()
    assert page.count("<circle") == 40
    assert "&#9679;" in page  # legend


def test_sqlite_stats_storage_round_trip(tmp_path):
    """SQLite indexed backend (reference ui/storage/sqlite module): full SPI
    round trip incl. since_iteration queries, cross-connection read, and
    dashboard rendering."""
    from deeplearning4j_tpu.ui import SqliteStatsStorage, render_dashboard_html

    path = str(tmp_path / "stats.db")
    store = SqliteStatsStorage(path)
    store.put_static_info("s1", "w0", {"model_class": "M", "num_params": 7})
    for i in range(5):
        store.put_update("s1", "w0", {"iteration": i, "score": 5.0 - i})
    store.put_update("s1", "w1", {"iteration": 0, "score": 9.0})

    assert store.list_session_ids() == ["s1"]
    assert store.list_worker_ids("s1") == ["w0", "w1"]
    assert store.get_static_info("s1", "w0")["num_params"] == 7
    assert len(store.get_updates("s1", "w0")) == 5
    assert [u["iteration"] for u in store.get_updates("s1", "w0",
                                                      since_iteration=2)] == [3, 4]
    assert store.get_latest_update("s1", "w0")["score"] == 1.0

    # independent connection (dashboard process) sees the same data
    reader = SqliteStatsStorage(path)
    page = render_dashboard_html(reader, "s1", "w0")
    assert "Score vs. iteration" in page
    reader.close()
    store.close()


def test_sqlite_storage_with_stats_listener(tmp_path):
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.ui import SqliteStatsStorage, StatsListener

    store = SqliteStatsStorage(str(tmp_path / "train.db"))
    conf = (NeuralNetConfiguration(seed=1, updater=Sgd(0.1), dtype="float32")
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(StatsListener(store, session_id="t", worker_id="w"))
    x = np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[np.random.default_rng(1).integers(0, 2, 16)]
    net.fit(x, y, epochs=3, batch_size=16)
    ups = store.get_updates("t", "w")
    assert len(ups) == 3 and all("score" in u for u in ups)
    store.close()


def test_dashboard_i18n_and_multisession():
    """TrainModule parity depth (reference TrainModule.java:94-110 +
    DefaultI18N): the page renders in each of the reference's six
    languages and links every attached session."""
    from deeplearning4j_tpu.ui import i18n
    from deeplearning4j_tpu.ui.dashboard import render_dashboard_html
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

    store = InMemoryStatsStorage()
    for sid in ("sessA", "sessB"):
        store.put_static_info(sid, "w0", {"model": "mlp"})
        store.put_update(sid, "w0", {"iteration": 1, "score": 1.5})
    # multi-session nav: both sessions linked regardless of which renders
    page = render_dashboard_html(store, "sessA")
    assert "session=sessA" in page and "session=sessB" in page
    # i18n: all six reference languages render their own page title
    assert sorted(i18n.languages()) == ["de", "en", "ja", "ko", "ru", "zh"]
    for lang in i18n.languages():
        p = render_dashboard_html(store, "sessA", lang=lang)
        assert i18n.get_message("train.pagetitle", lang) in p
    # unknown keys and fallback
    assert i18n.get_message("train.model", "ja") == "モデル"
    assert i18n.get_message("no.such.key", "ja") == "no.such.key"
    # ?lang= routing through the live server
    import urllib.request
    from deeplearning4j_tpu.ui.dashboard import TrainingUIServer
    srv = TrainingUIServer(port=0)
    srv.attach(store)
    port = srv.start()
    try:
        html_ja = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/?session=sessA&lang=ja",
            timeout=10).read().decode()
        assert "トレーニング概要" in html_ja
    finally:
        srv.stop()
