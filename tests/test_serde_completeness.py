"""Every registered config dataclass must JSON round-trip with NON-DEFAULT
field values — the completeness check that catches a field added to a
config class but forgotten by serde (reference: the custom deserializers in
nn/conf/serde/BaseNetConfigDeserializer.java are exercised by every config
test; here one generative test covers the whole registry)."""
import dataclasses
import enum
import typing

import pytest

from deeplearning4j_tpu.nn.conf import serde

# classes whose constructor args are not independent plain fields (built
# via their own factories); covered by their dedicated tests instead
_SKIP = {"MultiLayerConfiguration", "CompositeReconstructionDistribution",
         "LossFunctionWrapper", "MapSchedule"}


def _poke(value, field_name=""):
    """A deterministic non-default replacement for a field value."""
    if isinstance(value, bool):
        return not value
    if isinstance(value, int):
        return value + 1
    if isinstance(value, float):
        return value + 0.125
    if isinstance(value, str):
        # enum-like string fields must keep a valid vocabulary: leave them,
        # they are exercised by behavior tests; still round-trip as-is
        return value
    if isinstance(value, tuple):
        return tuple(_poke(v) for v in value)
    if isinstance(value, list):
        return [_poke(v) for v in value]
    return value


def _instantiate(cls):
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.default is not dataclasses.MISSING:
            kwargs[f.name] = _poke(f.default, f.name)
        elif f.default_factory is not dataclasses.MISSING:  # type: ignore
            kwargs[f.name] = _poke(f.default_factory(), f.name)
        else:
            # required field: synthesize by annotation
            ann = str(f.type)
            if "int" in ann:
                kwargs[f.name] = 3
            elif "float" in ann:
                kwargs[f.name] = 0.25
            elif "str" in ann:
                kwargs[f.name] = "x"
            elif "Tuple" in ann or "tuple" in ann:
                kwargs[f.name] = (2, 2)
            else:
                kwargs[f.name] = None
    return cls(**kwargs)


@pytest.mark.parametrize("name", sorted(
    n for n, c in serde._REGISTRY.items()
    if dataclasses.is_dataclass(c) and n not in _SKIP))
def test_registered_config_round_trips_with_non_defaults(name):
    cls = serde._REGISTRY[name]
    obj = _instantiate(cls)
    payload = serde.to_json(obj)
    back = serde.from_json(payload)
    assert type(back) is cls
    for f in dataclasses.fields(cls):
        if f.metadata.get("skip_serde", False):
            continue
        a, b = getattr(obj, f.name), getattr(back, f.name)
        # tuples may deserialize as lists — compare by content
        if isinstance(a, tuple):
            a = list(a)
        if isinstance(b, tuple):
            b = list(b)
        assert a == b, (name, f.name, a, b)
