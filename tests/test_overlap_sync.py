"""Overlapped gradient synchronization (parallel/overlap.py): bucket
schedule packing, bucketed/fused pmean parity with the per-leaf sweep,
ParallelWrapper overlap-path parity (per-step, fused scan window, all
bucket sizes), the fused Pallas threshold-encode kernel vs the XLA path,
and the per-bucket collective telemetry/trace plumbing."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd
from deeplearning4j_tpu.parallel import ParallelWrapper
from deeplearning4j_tpu.parallel.mesh import make_mesh, shard_map
from deeplearning4j_tpu.parallel.overlap import (build_bucket_schedule,
                                                 bucketed_pmean, fused_pmean,
                                                 profile_schedule)

R = np.random.default_rng(23)


# ------------------------------------------------------------- scheduling
def test_bucket_schedule_covers_every_leaf_once():
    tree = {"a": jnp.zeros((100,)), "b": jnp.zeros((3, 7)),
            "c": (jnp.zeros((50,)), jnp.zeros((2, 2, 2)))}
    sched = build_bucket_schedule(tree, bucket_bytes=256)
    seen = sorted(i for b in sched.buckets for i in b.indices)
    assert seen == list(range(sched.num_leaves))
    assert sched.total_bytes == sum(
        int(np.prod(s)) * dt.itemsize
        for s, dt in zip(sched.leaf_shapes, sched.leaf_dtypes))


def test_bucket_schedule_reverse_order_and_singletons():
    """Buckets pack from the LAST leaf backwards (backward-pass production
    order) and a leaf >= bucket_bytes ships as its own singleton."""
    leaves = [jnp.zeros((4,)), jnp.zeros((1000,)), jnp.zeros((4,)),
              jnp.zeros((4,))]
    sched = build_bucket_schedule(leaves, bucket_bytes=64)
    # bucket 0 holds the tail leaves (3, 2), the 1000-elem leaf is a
    # singleton, leaf 0 closes the schedule
    assert sched.buckets[0].indices == (3, 2)
    assert sched.buckets[1].indices == (1,)   # the big leaf, alone
    assert sched.buckets[2].indices == (0,)


def test_bucket_schedule_separates_dtypes():
    leaves = [jnp.zeros((8,), jnp.float32), jnp.zeros((8,), jnp.bfloat16),
              jnp.zeros((8,), jnp.float32)]
    sched = build_bucket_schedule(leaves, bucket_bytes=1 << 20)
    for b in sched.buckets:
        dts = {sched.leaf_dtypes[i] for i in b.indices}
        assert len(dts) == 1, b


def test_bucket_schedule_rejects_empty_and_bad_bytes():
    with pytest.raises(ValueError, match="empty"):
        build_bucket_schedule([], 1024)
    with pytest.raises(ValueError, match="bucket_bytes"):
        build_bucket_schedule([jnp.zeros((4,))], 0)


# ------------------------------------------------- pmean grouping parity
def _rand_tree():
    return {"w1": jnp.asarray(R.normal(size=(64, 32)).astype(np.float32)),
            "b1": jnp.asarray(R.normal(size=(32,)).astype(np.float32)),
            "w2": jnp.asarray(R.normal(size=(32, 8)).astype(np.float32)),
            "b2": jnp.asarray(R.normal(size=(8,)).astype(np.float32))}


def _run_on_mesh(fn, tree):
    mesh = make_mesh()
    leaves, treedef = jax.tree.flatten(tree)
    wrapped = shard_map(
        lambda *ls: tuple(jax.tree.leaves(
            fn(jax.tree.unflatten(treedef, ls)))),
        mesh=mesh, in_specs=(P(),) * len(leaves),
        out_specs=(P(),) * len(leaves), check_vma=False)
    out = jax.jit(wrapped)(*leaves)
    return jax.tree.unflatten(treedef, out)


def test_bucketed_pmean_bit_identical_to_per_leaf_sweep():
    """Grouping must not change any element's reduction: bucketed_pmean
    (all bucket sizes, incl. one-giant-bucket and per-leaf) == the
    per-leaf tree.map(pmean) sweep, bitwise, on the 8-device mesh."""
    tree = _rand_tree()
    ref = _run_on_mesh(
        lambda t: jax.tree.map(lambda a: jax.lax.pmean(a, "data"), t), tree)
    for bucket_bytes in (1, 2048, 1 << 30):
        sched = build_bucket_schedule(tree, bucket_bytes)
        got = _run_on_mesh(lambda t: bucketed_pmean(t, sched, "data"), tree)
        for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fused_pmean_bit_identical_to_per_leaf_sweep():
    tree = _rand_tree()
    ref = _run_on_mesh(
        lambda t: jax.tree.map(lambda a: jax.lax.pmean(a, "data"), t), tree)
    got = _run_on_mesh(lambda t: fused_pmean(t, "data"), tree)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bucketed_pmean_rejects_mismatched_tree():
    tree = _rand_tree()
    sched = build_bucket_schedule(tree, 2048)
    other = {"x": jnp.zeros((4,))}
    with pytest.raises(ValueError, match="schedule"):
        bucketed_pmean(other, sched, "data")


# ------------------------------------------------ ParallelWrapper parity
def _net(seed=7, updater=None):
    conf = (NeuralNetConfiguration(seed=seed, updater=updater or Sgd(0.1))
            .list(DenseLayer(n_in=6, n_out=24, activation="tanh"),
                  DenseLayer(n_in=24, n_out=16, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=128):
    x = R.normal(size=(n, 6)).astype(np.float32)
    yi = (x.sum(-1) > 0).astype(int) + (x[:, 0] > 1).astype(int)
    return x, np.eye(3, dtype=np.float32)[yi]


def test_overlap_sync_parity_all_bucket_sizes():
    """Same seed -> bit-identical params after N steps for every bucket
    size (per-leaf, default, one-bucket), and the overlap path tracks the
    GSPMD sync path."""
    x, y = _data()
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    ref = _net()
    ParallelWrapper(ref).fit(it, epochs=3)
    ref_flat = np.asarray(ref.params_flat())
    flats = []
    for bucket_bytes in (1, 4 * 2 ** 20, 1 << 30):
        it.reset()
        net = _net()
        ParallelWrapper(net, overlap_sync=True,
                        bucket_bytes=bucket_bytes).fit(it, epochs=3)
        flats.append(np.asarray(net.params_flat()))
    for f in flats[1:]:
        np.testing.assert_array_equal(flats[0], f)
    # vs the GSPMD path: same math, different collective plumbing — on
    # the CPU test backend this is elementwise-identical too, but the
    # pinned contract is numerical equivalence
    np.testing.assert_allclose(flats[0], ref_flat, atol=1e-6)


def test_overlap_window_bit_identical_to_per_step():
    """K fused overlap steps (steps_per_dispatch) == K per-step overlap
    dispatches, bitwise — the grad_sync seam rides train_step_math into
    the scan body structurally."""
    x, y = _data(128)
    a = _net(updater=Adam(5e-3))
    b = _net(updater=Adam(5e-3))
    b.set_params_flat(a.params_flat())
    it = ListDataSetIterator(features=x, labels=y, batch_size=32)
    ParallelWrapper(a, overlap_sync=True, bucket_bytes=2048).fit(it, epochs=2)
    it.reset()
    ParallelWrapper(b, overlap_sync=True, bucket_bytes=2048,
                    steps_per_dispatch=2).fit(it, epochs=2)
    np.testing.assert_array_equal(np.asarray(a.params_flat()),
                                  np.asarray(b.params_flat()))


def test_overlap_sync_converges():
    x, y = _data(256)
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    net = _net(updater=Adam(5e-3))
    pw = ParallelWrapper(net, overlap_sync=True)
    s0 = net.score(x, y)
    pw.fit(it, epochs=12)
    assert net.score(x, y) < s0
    assert net.evaluate(x, y).accuracy() > 0.8


def test_sync_remainder_batch_dispatches_replicated():
    """Regression: a batch whose size does not tile the mesh (the
    end-of-epoch remainder the prefetcher ships unsharded) raised the
    divisibility error on BOTH sync paths — shard_map (overlap) and
    jit+in_shardings (GSPMD) each enforce it — killing the epoch. It
    must dispatch through the replicated-feed program instead, with the
    identical update, and the single-net fit is the ground truth."""
    from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator as LI
    x, y = _data(100)           # batch 64 -> remainder 36 (36 % 8 != 0)
    single = _net()
    single.fit(iterator=LI(features=x, labels=y, batch_size=64), epochs=2,
               async_prefetch=False)
    for kw in ({}, {"overlap_sync": True, "bucket_bytes": 2048}):
        it = LI(features=x, labels=y, batch_size=64)
        net = _net()
        pw = ParallelWrapper(net, **kw)
        pw.fit(it, epochs=2)
        assert pw._remainder_step is not None    # the remainder took it
        np.testing.assert_allclose(np.asarray(net.params_flat()),
                                   np.asarray(single.params_flat()),
                                   rtol=2e-5, atol=2e-6)


def test_sync_remainder_window_dispatches_replicated():
    """Window variant: uniformly non-divisible batches stack into regular
    windows, which neither fused sync program can tile — the replicated
    window program must take the dispatch on the plain and overlap
    paths, bit-identical to each other."""
    x, y = _data(120)           # batches of 60; 60 % 8 != 0
    it = ListDataSetIterator(features=x, labels=y, batch_size=60)
    ref = _net()
    pw_ref = ParallelWrapper(ref, steps_per_dispatch=2)
    pw_ref.fit(it, epochs=2)
    assert pw_ref._remainder_window_step is not None
    it.reset()
    net = _net()
    pw = ParallelWrapper(net, overlap_sync=True, bucket_bytes=2048,
                         steps_per_dispatch=2)
    pw.fit(it, epochs=2)
    assert pw._remainder_window_step is not None
    np.testing.assert_array_equal(np.asarray(net.params_flat()),
                                  np.asarray(ref.params_flat()))


def test_overlap_rejects_accumulator():
    from deeplearning4j_tpu.parallel.accumulation import PsumAccumulator
    with pytest.raises(ValueError, match="overlap_sync"):
        ParallelWrapper(_net(), overlap_sync=True,
                        gradient_accumulator=PsumAccumulator())


def test_overlap_rejects_averaging_path():
    """Regression: overlap_sync on the K-step averaging path was silently
    ignored (no bucketing, no metrics) — it must refuse like the
    accumulator combination does."""
    with pytest.raises(ValueError, match="averaging"):
        ParallelWrapper(_net(), overlap_sync=True,
                        training_mode="averaging", averaging_frequency=4)
    # averaging_frequency=1 IS the sync path: allowed
    ParallelWrapper(_net(), overlap_sync=True, training_mode="averaging",
                    averaging_frequency=1)


def test_encode_signs_multidim_takes_xla_fallback():
    """Regression: a kernel-eligible leading dim on a 2-D residual was
    routed into the Pallas kernel, which only serves the flat 1-D view —
    the public dispatcher must fall back instead of raising."""
    from deeplearning4j_tpu.ops.compression import threshold_encode_signs
    r = jnp.asarray(R.normal(0, 2e-3, (70000, 4)).astype(np.float32))
    signs, res = threshold_encode_signs(r, 1e-3)
    assert signs.shape == r.shape
    t = jnp.asarray(1e-3, r.dtype)
    s_ref = jnp.where(jnp.abs(r) >= t, jnp.sign(r), jnp.zeros((), r.dtype))
    np.testing.assert_array_equal(np.asarray(signs),
                                  np.asarray(s_ref.astype(jnp.int8)))
    np.testing.assert_array_equal(np.asarray(res), np.asarray(r - s_ref * t))


def test_overlap_collective_launch_telemetry():
    reg = telemetry.get_registry()
    telemetry.reset()
    x, y = _data(128)
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    net = _net()
    pw = ParallelWrapper(net, overlap_sync=True, bucket_bytes=512)
    pw.fit(it, epochs=1)
    n_buckets = len(pw._bucket_schedule)
    assert n_buckets >= 2
    assert reg.gauge("parallel.bucket_count").value == n_buckets
    # 2 steps/epoch x (grad buckets + the fused state/loss launch)
    assert reg.counter("parallel.collective_launches").value == \
        2 * (n_buckets + 1)


# ------------------------------------------- profiling + trace folding
def test_profile_schedule_emits_per_bucket_collective_events(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import trace2summary

    reg = telemetry.get_registry()
    telemetry.reset()
    tree = _rand_tree()
    sched = build_bucket_schedule(tree, 2048)
    with telemetry.span("fit"):
        out = profile_schedule(make_mesh(), sched)
    assert len(out["buckets"]) == len(sched)
    assert out["collective_ms"] > 0
    assert reg.gauge("parallel.collective_ms").value == \
        pytest.approx(out["collective_ms"], rel=0.01)
    trace = tmp_path / "trace.json"
    reg.write_chrome_trace(str(trace))
    rows = trace2summary.summarize(trace2summary.load_events(str(trace)))
    phases = {r["phase"] for r in rows}
    # every bucket's psum folds into its OWN [bucket_psum:i] phase,
    # nested under the span it ran in
    for i in range(len(sched)):
        assert f"fit/[bucket_psum:{i}]" in phases, phases


# --------------------------------------------------- pallas fused encode
def test_pallas_encode_bit_identical_to_xla_fallback():
    from deeplearning4j_tpu.ops.compression import threshold_encode_signs
    from deeplearning4j_tpu.ops.pallas_compression import (
        fused_threshold_encode_applicable, threshold_encode_pallas)

    n_block = 1 << 16
    for n in (n_block, n_block + 77, 2 * n_block + 12345):
        for dt in (jnp.float32, jnp.bfloat16):
            assert fused_threshold_encode_applicable(n, dt)
            r = jnp.asarray(R.normal(0, 2e-3, (n,)), dt)
            t = jnp.asarray(1e-3, r.dtype)
            s_ref = jnp.where(jnp.abs(r) >= t, jnp.sign(r),
                              jnp.zeros((), r.dtype))
            signs, res = threshold_encode_pallas(r, 1e-3)
            assert signs.dtype == jnp.int8 and res.dtype == r.dtype
            np.testing.assert_array_equal(
                np.asarray(signs), np.asarray(s_ref.astype(jnp.int8)))
            np.testing.assert_array_equal(
                np.asarray(res), np.asarray(r - s_ref * t))
            # the front-door dispatcher routes to the same result
            signs2, res2 = threshold_encode_signs(r, 1e-3)
            np.testing.assert_array_equal(np.asarray(signs),
                                          np.asarray(signs2))
            np.testing.assert_array_equal(np.asarray(res), np.asarray(res2))


def test_pallas_encode_gating():
    from deeplearning4j_tpu.ops.pallas_compression import \
        fused_threshold_encode_applicable as app
    assert not app(100, jnp.float32)          # below one block
    assert not app(1 << 20, jnp.int8)         # non-float dtype
    old = os.environ.get("DL4J_TPU_FUSED_ENCODE")
    try:
        os.environ["DL4J_TPU_FUSED_ENCODE"] = "0"
        assert not app(1 << 20, jnp.float32)  # kill switch
    finally:
        if old is None:
            os.environ.pop("DL4J_TPU_FUSED_ENCODE", None)
        else:
            os.environ["DL4J_TPU_FUSED_ENCODE"] = old


def test_encoded_accumulator_identical_with_and_without_kernel():
    """EncodedAccumulator's dense combine must produce the SAME update and
    residual whether the Pallas kernel or the XLA fallback encodes —
    pinned at a kernel-eligible size on the 8-device mesh."""
    from deeplearning4j_tpu.parallel.accumulation import EncodedAccumulator

    n, sz = 8, 1 << 16
    mesh = make_mesh()
    acc = EncodedAccumulator(threshold=1e-3)
    grads = jnp.asarray(R.normal(0, 2e-3, (n, sz)).astype(np.float32))
    state = jnp.zeros((n, sz), jnp.float32)

    def worker(g, s):
        u, ns = acc.combine(g[0], s[0], axis="data")
        return u[None], ns[None]

    fn = jax.jit(shard_map(worker, mesh=mesh,
                           in_specs=(P("data"), P("data")),
                           out_specs=(P("data"), P("data")),
                           check_vma=False))
    u_pallas, ns_pallas = fn(grads, state)
    old = os.environ.get("DL4J_TPU_FUSED_ENCODE")
    try:
        os.environ["DL4J_TPU_FUSED_ENCODE"] = "0"
        fn2 = jax.jit(shard_map(worker, mesh=mesh,
                                in_specs=(P("data"), P("data")),
                                out_specs=(P("data"), P("data")),
                                check_vma=False))
        u_xla, ns_xla = fn2(grads, state)
    finally:
        if old is None:
            os.environ.pop("DL4J_TPU_FUSED_ENCODE", None)
        else:
            os.environ["DL4J_TPU_FUSED_ENCODE"] = old
    np.testing.assert_array_equal(np.asarray(u_pallas), np.asarray(u_xla))
    np.testing.assert_array_equal(np.asarray(ns_pallas), np.asarray(ns_xla))


# ------------------------------------------------------------ bench smoke
@pytest.mark.bench_smoke
def test_collective_overlap_bench_smoke():
    """Tier-1 guard: the collective_overlap row must run end to end and
    bucketed sync must not be catastrophically slower than the per-leaf
    sweep. The >=25%-at-mesh-8 acceptance number is measured by bench.py
    on the real rig at full scale; CI pins structure + 'not broken' (a
    shared CI box swings these multi-replica CPU timings, so three
    consecutive failing attempts are required to fail)."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    row = None
    for _ in range(3):
        row = bench.bench_collective_overlap(meshes=(4,),
                                             total_elems=120_000,
                                             bucket_bytes=128 * 1024,
                                             timeout=240)
        sub = row["4"]
        assert row["buckets"] < row["leaves"]
        assert sub["serialized_ms"] > 0 and sub["overlapped_ms"] > 0
        assert sub["collective_ms_serialized"] >= 0
        assert sub["collective_ms_overlapped"] >= 0
        if (sub["sync_step_reduction"] is not None
                and sub["sync_step_reduction"] > -0.5):
            return
    pytest.fail(f"bucketed sync catastrophically slow in 3 attempts: {row}")
