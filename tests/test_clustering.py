"""Clustering/NN tests (mirror reference nearestneighbor-core tests:
VP-tree kNN correctness vs brute force, k-means convergence, t-SNE
neighborhood preservation)."""
import numpy as np
import pytest

from deeplearning4j_tpu.clustering import KMeansClustering, Tsne, VPTree


def test_vptree_matches_brute_force():
    r = np.random.default_rng(0)
    pts = r.normal(size=(200, 8))
    tree = VPTree(pts)
    for qi in [0, 17, 99]:
        q = pts[qi] + 0.01
        idx, dist = tree.knn(q, k=5)
        brute = np.argsort(np.linalg.norm(pts - q, axis=1))[:5]
        assert set(idx) == set(brute.tolist()), (idx, brute)
        assert dist == sorted(dist)


def test_vptree_cosine():
    r = np.random.default_rng(1)
    pts = r.normal(size=(100, 4))
    tree = VPTree(pts, metric="cosine")
    idx, _ = tree.knn(pts[3], k=1)
    assert idx[0] == 3


def test_kmeans_separates_blobs():
    r = np.random.default_rng(2)
    blobs = np.concatenate([
        r.normal(loc=(0, 0), scale=0.3, size=(50, 2)),
        r.normal(loc=(5, 5), scale=0.3, size=(50, 2)),
        r.normal(loc=(0, 5), scale=0.3, size=(50, 2))])
    km = KMeansClustering(k=3, seed=4).fit(blobs)
    labels = km.predict(blobs)
    # each true blob maps to a single cluster
    for s in range(3):
        seg = labels[s * 50:(s + 1) * 50]
        assert (seg == np.bincount(seg).argmax()).mean() > 0.95
    # centroids near blob centers
    cents = np.sort(km.centroids.round(0), axis=0)
    assert cents.shape == (3, 2)


def test_tsne_preserves_clusters():
    r = np.random.default_rng(3)
    a = r.normal(loc=0, scale=0.1, size=(30, 10))
    b = r.normal(loc=3, scale=0.1, size=(30, 10))
    X = np.concatenate([a, b])
    Y = Tsne(perplexity=10, n_iter=300, seed=1).fit_transform(X)
    assert Y.shape == (60, 2)
    da = np.linalg.norm(Y[:30] - Y[:30].mean(0), axis=1).mean()
    cross = np.linalg.norm(Y[:30].mean(0) - Y[30:].mean(0))
    assert cross > 3 * da, (cross, da)  # clusters separate in the embedding
