"""Central-difference gradient checks for the net-new parallel blocks
(VERDICT r3 item 7): MoE top-2 (router + experts), a pipeline-wrapped block
stack, and GravesBidirectionalLSTM-with-mask.

These paths had parity/convergence tests but no numerical gradient
verification — the repo's stated backbone (SURVEY.md §4; reference
GradientCheckUtil forces DOUBLE, GradientCheckUtil.java:92-97).

The fused Pallas path is f32-only by design (fused_lstm_applicable rejects
f64), so the bidirectional-with-mask check verifies the f64 SCAN twin
numerically here; tests/test_pallas_lstm.py::
test_bidirectional_layer_fused_matches_scan ties the fused VJP to that
scan math at f32 — together the fused path is numerically anchored.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import GravesBidirectionalLSTM, RnnOutputLayer
from deeplearning4j_tpu.optimize.updaters import Sgd
from deeplearning4j_tpu.parallel.expert_parallel import expert_parallel_apply
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.pipeline import (pipeline_apply,
                                                  stack_stage_params)
from deeplearning4j_tpu.util.gradcheck import check_gradients

R = np.random.default_rng(99)


def _central_diff_check(loss, flat0, *, subset=40, epsilon=1e-6,
                        max_rel_error=1e-3, min_abs_error=1e-8, seed=0):
    """f64 central differences vs jax.grad for an arbitrary flat-vector
    loss (the _check_flat contract, re-implemented with a plain loop so the
    loss may contain jitted shard_map programs that vmap can't batch)."""
    flat0 = np.asarray(flat0, np.float64)
    analytic = np.asarray(jax.grad(loss)(jnp.asarray(flat0)))
    n = flat0.shape[0]
    idxs = (np.random.default_rng(seed).choice(n, subset, replace=False)
            if subset < n else np.arange(n))
    fails, max_rel = 0, 0.0
    for i in idxs:
        row = flat0.copy()
        row[i] += epsilon
        lp = float(loss(jnp.asarray(row)))
        row[i] = flat0[i] - epsilon
        lm = float(loss(jnp.asarray(row)))
        numeric = (lp - lm) / (2 * epsilon)
        a = float(analytic[i])
        denom = abs(a) + abs(numeric)
        rel = abs(a - numeric) / denom if denom > 0 else 0.0
        max_rel = max(max_rel, rel)
        if rel > max_rel_error and abs(a - numeric) > min_abs_error:
            fails += 1
            print(f"param {i}: analytic={a:.8g} numeric={numeric:.8g} "
                  f"rel={rel:.3g}")
    print(f"checked {len(idxs)}/{n} params, max rel {max_rel:.3g}, "
          f"{fails} failures")
    return fails == 0


def test_moe_top2_router_and_expert_gradients():
    """MoE top-2 (GShard routing): numerical gradients must match the
    analytic ones for BOTH the expert params and the router matrix — the
    router grads flow through the renormalized surviving-choice weights."""
    E, D, N = 4, 6, 16
    mesh = make_mesh((E,), ("expert",), devices=jax.devices()[:E])
    blocks = [{"W": jnp.asarray(R.normal(size=(D, D)) * 0.4, jnp.float64),
               "b": jnp.asarray(R.normal(size=(D,)) * 0.1, jnp.float64)}
              for _ in range(E)]
    stacked = stack_stage_params(blocks)
    router = jnp.asarray(R.normal(size=(D, E)) * 0.5, jnp.float64)
    toks = jnp.asarray(R.normal(size=(N, D)), jnp.float64)
    tgt = jnp.asarray(R.normal(size=(N, D)), jnp.float64)
    moe = expert_parallel_apply(
        lambda p, x: jnp.tanh(x @ p["W"] + p["b"]), mesh, "expert", top_k=2)

    sizes = [(k, np.prod(v.shape)) for k, v in
             [("W", stacked["W"]), ("b", stacked["b"]), ("r", router)]]

    def unflatten(flat):
        off = 0
        out = {}
        for k, sz in sizes:
            ref = {"W": stacked["W"], "b": stacked["b"], "r": router}[k]
            out[k] = flat[off:off + sz].reshape(ref.shape)
            off += sz
        return out

    def loss(flat):
        p = unflatten(flat)
        logits = toks @ p["r"]
        y = moe({"W": p["W"], "b": p["b"]}, toks, logits)
        return 0.5 * jnp.sum((y - tgt) ** 2)

    flat0 = np.concatenate([np.asarray(stacked["W"]).ravel(),
                            np.asarray(stacked["b"]).ravel(),
                            np.asarray(router).ravel()])
    # check ALL router params (they're few and the interesting ones) plus a
    # sample of expert params
    n_router = router.size
    assert _central_diff_check(loss, flat0, subset=60 + n_router)


def test_pipeline_stack_gradients():
    """GPipe pipeline over 4 stages: central differences through the
    scan-scheduled microbatch pipeline must match jax.grad."""
    S, D = 4, 5
    mesh = make_mesh((S,), ("pipe",), devices=jax.devices()[:S])
    blocks = [{"W": jnp.asarray(R.normal(size=(D, D)) * 0.4, jnp.float64),
               "b": jnp.asarray(R.normal(size=(D,)) * 0.1, jnp.float64)}
              for _ in range(S)]
    stacked = stack_stage_params(blocks)
    x_micro = jnp.asarray(R.normal(size=(4, 3, D)), jnp.float64)
    tgt = jnp.asarray(R.normal(size=(4, 3, D)), jnp.float64)
    pipe = pipeline_apply(lambda p, x: jnp.tanh(x @ p["W"] + p["b"]),
                          mesh, "pipe")

    shapes = [stacked["W"].shape, stacked["b"].shape]

    def loss(flat):
        w = flat[:np.prod(shapes[0])].reshape(shapes[0])
        b = flat[np.prod(shapes[0]):].reshape(shapes[1])
        y = pipe({"W": w, "b": b}, x_micro)
        return 0.5 * jnp.sum((y - tgt) ** 2)

    flat0 = np.concatenate([np.asarray(stacked["W"]).ravel(),
                            np.asarray(stacked["b"]).ravel()])
    assert _central_diff_check(loss, flat0, subset=60)


@pytest.mark.slow
def test_bidirectional_lstm_masked_gradients():
    """Slow lane (ISSUE 14 tier-1 budget reclaim): ~10s combination
    variant — bidirectional-LSTM gradients stay tier-1
    (test_recurrent.test_lstm_gradient_checks[GravesBidirectionalLSTM])
    and masked recurrent gradients stay tier-1 (the seq2seq
    masked-gradient check in test_graph_recurrent).

    GravesBidirectionalLSTM with variable-length masks, f64: the scan
    twin of the fused kernel, numerically verified end-to-end through the
    MLN loss (masked loss + masked eval; reference
    GradientCheckTestsMasking)."""
    T, V = 5, 3
    conf = (NeuralNetConfiguration(seed=12345, updater=Sgd(0.1),
                                   dtype="float64")
            .list(GravesBidirectionalLSTM(n_out=6, activation="tanh"),
                  RnnOutputLayer(n_out=V, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(V, T)).build())
    net = MultiLayerNetwork(conf).init()
    x = R.normal(size=(4, T, V))
    y = np.eye(V)[R.integers(0, V, (4, T))]
    lens = np.asarray([2, 5, 3, 4])
    m = (np.arange(T)[None, :] < lens[:, None]).astype(np.float64)
    assert check_gradients(net, x, y, labels_mask=m, features_mask=m,
                           print_results=True)
