"""Zoo part-2 models, dataset fetchers, iterator adapters, pretrained cache
(reference zoo/model/{GoogLeNet,InceptionResNetV1,FaceNetNN4Small2,
TextGenerationLSTM}.java, ZooModel.initPretrained :40-81,
datasets/fetchers/*, datasets/iterator/*)."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.datasets.fetchers import (Cifar10DataSetIterator,
                                                  CurvesDataSetIterator,
                                                  IrisDataSetIterator,
                                                  load_cifar10, load_curves,
                                                  load_iris)
from deeplearning4j_tpu.datasets.iterators import (
    EarlyTerminationDataSetIterator, ExistingDataSetIterator,
    IteratorDataSetIterator, ListMultiDataSetIterator, MultiDataSet,
    MultipleEpochsIterator, SamplingDataSetIterator)
from deeplearning4j_tpu.models.pretrained import (adler32_of, fetch_cached,
                                                  init_pretrained)
from deeplearning4j_tpu.models.zoo_extra import (facenet_nn4_small2,
                                                 googlenet,
                                                 inception_resnet_v1,
                                                 text_generation_lstm)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.util.serialization import write_model

R = np.random.default_rng(21)


# ------------------------------------------------------------------ zoo builds
def _step_graph(net, h, w, n_classes, batch=2):
    x = R.normal(size=(batch, h, w, 3)).astype(np.float32)
    y = np.eye(n_classes, dtype=np.float32)[R.integers(0, n_classes, batch)]
    s0 = net.score(x, y)
    net.fit(x, y, epochs=1, batch_size=batch)
    assert np.isfinite(s0)
    out = np.asarray(net.output(x))
    assert out.shape == (batch, n_classes)
    return out


@pytest.mark.slow
def test_zoo_extra_models_build():
    """Structure checks: init + param counts at small spatial dims. Slow
    lane (ISSUE 14 tier-1 budget reclaim): ~21s of tier-1 whose unique
    coverage is thin — test_googlenet_steps re-checks the googlenet param
    count (already slow) and test_facenet_l2_embeddings_forward (also
    slow since ISSUE 19) inits facenet end-to-end."""
    # GoogLeNet's param count is input-size independent (global pooling);
    # ~6M at 10 classes vs reference ~7M at 1000 (the fc1 input is 1024)
    assert 4_000_000 < googlenet(n_classes=10, height=48,
                                 width=48).init().num_params() < 9_000_000
    assert facenet_nn4_small2(n_classes=5, height=48, width=48,
                              embedding_size=32).init().num_params() > 1_000_000


@pytest.mark.slow
def test_googlenet_steps():
    # reference GoogLeNet has ~7M params at 1000 classes
    assert 5_000_000 < googlenet(n_classes=1000).init().num_params() < 9_000_000
    net = googlenet(n_classes=7, height=64, width=64).init()
    out = _step_graph(net, 64, 64, 7)
    assert np.allclose(out.sum(-1), 1.0, atol=1e-4)


@pytest.mark.slow
def test_facenet_l2_embeddings_forward():
    # Slow lane (ISSUE 19 tier-1 budget reclaim): ~18s init+forward of the
    # biggest zoo graph. The facenet leg (build + L2-normalized embeddings
    # + train steps) now lives entirely in the slow lane alongside
    # test_facenet_nn4_small2_steps / test_zoo_extra_models_build.
    net = facenet_nn4_small2(n_classes=5, height=48, width=48,
                             embedding_size=32).init()
    # embeddings vertex is L2-normalized
    acts = net.feed_forward(R.normal(size=(3, 48, 48, 3)).astype(np.float32))
    emb = np.asarray(acts["embeddings"])
    np.testing.assert_allclose(np.linalg.norm(emb, axis=1), 1.0, atol=1e-3)


@pytest.mark.slow
def test_facenet_nn4_small2_steps():
    net = facenet_nn4_small2(n_classes=5, height=64, width=64,
                             embedding_size=32).init()
    _step_graph(net, 64, 64, 5)


@pytest.mark.slow
def test_inception_resnet_v1_steps():
    net = inception_resnet_v1(n_classes=5, height=64, width=64,
                              embedding_size=32,
                              res_a=1, res_b=1, res_c=1).init()
    assert net.num_params() > 1_000_000
    _step_graph(net, 64, 64, 5)


def test_text_generation_lstm_fits():
    net = text_generation_lstm(vocab_size=12, max_length=16,
                               hidden=24, tbptt_length=8).init()
    ids = R.integers(0, 12, (8, 16))
    x = np.eye(12, dtype=np.float32)[ids]
    y = np.eye(12, dtype=np.float32)[np.roll(ids, -1, axis=1)]
    s0 = float(net.score(x, y))
    net.fit(x, y, epochs=5, batch_size=8)
    assert float(net.score(x, y)) < s0


# -------------------------------------------------------------------- datasets
def test_iris_loads_and_trains():
    x, y = load_iris()
    assert x.shape == (150, 4) and y.shape == (150, 3)
    assert y.sum() == 150
    conf = (NeuralNetConfiguration(seed=3, updater=Adam(5e-2), dtype="float32")
            .list(DenseLayer(n_in=4, n_out=16, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(iterator=IrisDataSetIterator(batch_size=50), epochs=40)
    assert net.evaluate(x, y).accuracy() > 0.9


def test_cifar_synthetic_fallback_shapes():
    x, y, synthetic = load_cifar10(cache_dir="/nonexistent-cache",
                                   n_synthetic=64)
    assert synthetic is True
    assert x.shape == (64, 32, 32, 3) and y.shape == (64, 10)
    assert 0.0 <= x.min() and x.max() <= 1.0
    it = Cifar10DataSetIterator(batch_size=32, cache_dir="/nonexistent-cache")
    batches = list(it)
    assert batches[0].features.shape[0] == 32


def test_curves_generation():
    x, y = load_curves(n=16, resolution=16)
    assert x.shape == (16, 256)
    np.testing.assert_array_equal(x, y)
    assert x.max() <= 1.0 + 1e-6 and x.max() > 0.5   # strokes present
    it = CurvesDataSetIterator(batch_size=8, num_examples=16, resolution=16)
    assert sum(d.num_examples() for d in it) == 16


# ----------------------------------------------------------- iterator adapters
def _mini_iter(n=10, bs=2):
    x = R.normal(size=(n, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[R.integers(0, 2, n)]
    return ListDataSetIterator(features=x, labels=y, batch_size=bs)


def test_multiple_epochs_iterator():
    it = MultipleEpochsIterator(3, _mini_iter(10, 2))
    assert len(list(it)) == 15


def test_early_termination_iterator():
    it = EarlyTerminationDataSetIterator(_mini_iter(10, 2), max_batches=2)
    assert len(list(it)) == 2
    it.reset()
    assert len(list(it)) == 2
    with pytest.raises(ValueError):
        EarlyTerminationDataSetIterator(_mini_iter(), 0)


def test_sampling_iterator():
    ds = DataSet(R.normal(size=(20, 3)).astype(np.float32),
                 np.eye(2, dtype=np.float32)[R.integers(0, 2, 20)])
    it = SamplingDataSetIterator(ds, batch_size=8, n_batches=5)
    batches = list(it)
    assert len(batches) == 5
    assert all(b.features.shape == (8, 3) for b in batches)


def test_iterator_dataset_iterator_rebatches():
    singles = [DataSet(R.normal(size=(1, 3)).astype(np.float32),
                       np.eye(2, dtype=np.float32)[[i % 2]])
               for i in range(7)]
    it = IteratorDataSetIterator(lambda: iter(singles), batch_size=3)
    sizes = [d.num_examples() for d in it]
    assert sizes == [3, 3, 1]


def test_existing_and_multidataset_iterators():
    mds = MultiDataSet(
        features=[R.normal(size=(10, 4)).astype(np.float32),
                  R.normal(size=(10, 2)).astype(np.float32)],
        labels=[np.eye(2, dtype=np.float32)[R.integers(0, 2, 10)]])
    it = ListMultiDataSetIterator(mds, batch_size=4)
    batches = list(it)
    assert [b.num_examples() for b in batches] == [4, 4, 2]
    assert len(batches[0].features) == 2
    wrapped = ExistingDataSetIterator(batches)
    assert len(list(wrapped)) == 3


# ------------------------------------------------------------------ pretrained
def test_pretrained_cache_checksum_and_load(tmp_path):
    conf = (NeuralNetConfiguration(seed=9, updater=Adam(1e-3), dtype="float32")
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    src = str(tmp_path / "model.zip")
    write_model(net, src)
    csum = adler32_of(src)
    cache = str(tmp_path / "cache")

    # fetch + checksum hit
    p = fetch_cached(src, checksum=csum, cache_dir=cache)
    assert os.path.exists(p)
    # wrong checksum -> IOError after one retry
    with pytest.raises(IOError):
        fetch_cached(src, checksum=csum + 1, cache_dir=str(tmp_path / "c2"))

    fresh = MultiLayerNetwork(conf).init(seed=123)
    assert not np.allclose(np.asarray(fresh.params_flat()),
                           np.asarray(net.params_flat()))
    init_pretrained(fresh, src, checksum=csum, cache_dir=cache)
    np.testing.assert_allclose(np.asarray(fresh.params_flat()),
                               np.asarray(net.params_flat()))

    # architecture mismatch -> clear error
    conf2 = (NeuralNetConfiguration(seed=9, updater=Adam(1e-3), dtype="float32")
             .list(DenseLayer(n_in=4, n_out=16, activation="tanh"),
                   OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
             .build())
    with pytest.raises(ValueError, match="params"):
        init_pretrained(MultiLayerNetwork(conf2).init(), src, checksum=csum,
                        cache_dir=cache)


@pytest.mark.slow
def test_text_generation_sampling():
    """Streaming temperature sampling off a trained char model (reference
    TextGenerationLSTM's use case). Slow lane (ISSUE 19 tier-1 budget
    reclaim): ~11s of 120-epoch training to a learnable cycle; the
    char-LM fit contract stays tier-1 via test_text_generation_lstm_fits
    and temperature/sampling decode paths are tier-1-exercised by the
    generation engine's mixed-settings stream
    (test_generation.py::test_zero_recompiles_generation_after_warmup)."""
    from deeplearning4j_tpu.models.zoo_extra import sample_text
    V = 8
    net = text_generation_lstm(vocab_size=V, max_length=16, hidden=32,
                               tbptt_length=8, updater=Adam(1e-2)).init()
    # teach a trivial cycle 0->1->2->...->0 from every phase offset
    ids = (np.arange(V)[:, None] + np.arange(17)[None, :]) % V   # [V, 17]
    x = np.eye(V, dtype=np.float32)[ids[:, :-1]]
    y = np.eye(V, dtype=np.float32)[ids[:, 1:]]
    net.fit(x, y, epochs=120, batch_size=V)
    out = sample_text(net, vocab_size=V, seed_ids=[0, 1, 2], n_steps=10,
                      temperature=0.1, rng_seed=3)
    assert len(out) == 10
    assert all(0 <= t < V for t in out)
    # low temperature on a learned cycle: most transitions follow +1 mod V
    seq = [2] + out
    follows = sum(1 for a, b in zip(seq, seq[1:]) if b == (a + 1) % V)
    assert follows >= 6, (seq, follows)


def test_lfw_iterator_synthetic_fallback(tmp_path):
    """LFW fetcher (reference datasets/fetchers/LFWDataFetcher.java): no
    archive present -> deterministic synthetic identities."""
    from deeplearning4j_tpu.datasets import LFWDataSetIterator
    it = LFWDataSetIterator(batch_size=16, height=32, width=32,
                            cache_dir=str(tmp_path))
    assert it.synthetic
    assert len(it.people) == 5
    ds = next(iter(it))
    assert ds.features.shape == (16, 32, 32, 3)
    assert ds.labels.shape == (16, 5)
    assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0


def test_lfw_reads_person_directories(tmp_path):
    """With a real lfw/ tree of person-named jpg dirs, images load, scale,
    and label by identity; num_people keeps the most-photographed."""
    from PIL import Image
    from deeplearning4j_tpu.datasets import load_lfw
    root = tmp_path / "lfw"
    rng = np.random.default_rng(3)
    counts = {"Alice_A": 3, "Bob_B": 2, "Carol_C": 1}   # Carol < min filter
    for name, k in counts.items():
        d = root / name
        d.mkdir(parents=True)
        for i in range(k):
            arr = (rng.random((40, 30, 3)) * 255).astype(np.uint8)
            Image.fromarray(arr).save(str(d / f"{name}_{i:04d}.jpg"))
    x, y, people, synthetic = load_lfw(str(tmp_path), height=24, width=24,
                                       min_images_per_person=2)
    assert not synthetic
    assert people == ["Alice_A", "Bob_B"]
    assert x.shape == (5, 24, 24, 3) and y.shape == (5, 2)
    assert y.sum(0).tolist() == [3.0, 2.0]


def test_pretrained_round_trip_committed_fixture(tmp_path):
    """Full init_pretrained path on a COMMITTED zoo-model weight artifact:
    fetch into cache -> Adler32 verify -> restore -> predict matches the
    committed expected outputs (reference ZooModel.initPretrained
    :40-52,81; VERDICT r2 missing #7)."""
    import os
    from deeplearning4j_tpu.models.pretrained import init_pretrained
    from deeplearning4j_tpu.models.zoo_extra import text_generation_lstm

    fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                           "pretrained_textgen_small.zip")
    expected = np.load(os.path.join(os.path.dirname(__file__), "fixtures",
                                    "pretrained_textgen_small_expected.npz"))
    net = text_generation_lstm(vocab_size=12, hidden=16, max_length=8,
                               seed=99)  # different seed: weights must come
    # from the artifact, not init
    cache = str(tmp_path / "cache")
    init_pretrained(net, fixture, checksum=530652660, cache_dir=cache)
    out = np.asarray(net.output(expected["x"]))
    np.testing.assert_allclose(out, expected["out"], atol=1e-5)
    # cached copy exists and is reused
    assert os.path.exists(os.path.join(cache,
                                       "pretrained_textgen_small.zip"))
    # wrong checksum -> IOError after one retry
    with pytest.raises(IOError, match="Checksum"):
        init_pretrained(net, fixture, checksum=12345,
                        cache_dir=str(tmp_path / "cache2"))


def test_pretrained_shape_mismatch_raises(tmp_path):
    import os
    from deeplearning4j_tpu.models.pretrained import init_pretrained
    from deeplearning4j_tpu.models.zoo_extra import text_generation_lstm
    fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                           "pretrained_textgen_small.zip")
    net = text_generation_lstm(vocab_size=30, hidden=16, max_length=8)
    with pytest.raises(ValueError, match="params"):
        init_pretrained(net, fixture, cache_dir=str(tmp_path))


def test_lfw_empty_after_filter_raises_clear_error(tmp_path):
    from PIL import Image
    from deeplearning4j_tpu.datasets import load_lfw
    d = tmp_path / "lfw" / "Solo_Person"
    d.mkdir(parents=True)
    Image.fromarray(np.zeros((10, 10, 3), np.uint8)).save(str(d / "a.jpg"))
    with pytest.raises(FileNotFoundError, match="min_images_per_person"):
        load_lfw(str(tmp_path), min_images_per_person=2)


def test_export_and_sharded_streaming(tmp_path):
    """Export-based pipeline (reference ParameterAveragingTrainingMaster
    export path :326-335 + ExportSupport): iterator -> .npz shards ->
    per-worker disjoint streaming -> training."""
    from deeplearning4j_tpu.datasets import (ListDataSetIterator,
                                             ShardedFileDataSetIterator,
                                             export_dataset_iterator)

    rng = np.random.default_rng(0)
    X = rng.normal(size=(96, 6)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[(X.sum(-1) > 0).astype(int)]
    src = ListDataSetIterator(features=X, labels=Y, batch_size=8)  # 12 batches
    man = export_dataset_iterator(src, str(tmp_path / "exp"),
                                  batches_per_shard=3)
    assert man["num_batches"] == 12 and man["num_shards"] == 4
    assert man["num_examples"] == 96

    # full read-back reproduces the data exactly
    it = ShardedFileDataSetIterator(str(tmp_path / "exp"))
    got = np.concatenate([np.asarray(d.features) for d in it])
    np.testing.assert_allclose(got, X, atol=0)

    # 2-worker partition: disjoint, complete, balanced
    parts = [ShardedFileDataSetIterator(str(tmp_path / "exp"),
                                        shard_index=k, num_shards=2)
             for k in range(2)]
    rows = [np.concatenate([np.asarray(d.features) for d in p]) for p in parts]
    assert rows[0].shape[0] + rows[1].shape[0] == 96
    both = np.concatenate(rows)
    assert np.unique(both, axis=0).shape[0] == np.unique(X, axis=0).shape[0]

    # a net trains straight off the exported shards
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration(seed=1, updater=Adam(5e-3), dtype="float32")
            .list(DenseLayer(n_in=6, n_out=16, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    s0 = net.score(X, Y)
    net.fit(iterator=ShardedFileDataSetIterator(str(tmp_path / "exp"),
                                                shuffle_shards=True, seed=3),
            epochs=5)
    assert net.score(X, Y) < s0


def test_sharded_iterator_masks_and_validation(tmp_path):
    from deeplearning4j_tpu.datasets import (ShardedFileDataSetIterator,
                                             export_dataset_iterator)
    from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator

    x = np.zeros((4, 3, 2), np.float32)
    y = np.zeros((4, 3, 2), np.float32)
    m = np.ones((4, 3), np.float32)
    export_dataset_iterator(ListDataSetIterator([DataSet(x, y, m, m)],
                                                batch_size=4),
                            str(tmp_path / "e2"))
    ds = next(iter(ShardedFileDataSetIterator(str(tmp_path / "e2"))))
    assert ds.features_mask.shape == (4, 3)
    assert ds.labels_mask.shape == (4, 3)
    with pytest.raises(ValueError, match="shard_index"):
        ShardedFileDataSetIterator(str(tmp_path / "e2"), shard_index=2,
                                   num_shards=2)
    with pytest.raises(FileNotFoundError):
        ShardedFileDataSetIterator(str(tmp_path / "empty"))


def test_export_multi_input_and_empty_partition(tmp_path):
    """Multi-input/multi-output DataSets export as per-part arrays and read
    back as lists; an empty worker partition fails at construction."""
    from deeplearning4j_tpu.datasets import (ShardedFileDataSetIterator,
                                             export_dataset_iterator)
    from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator

    x1 = np.ones((4, 3), np.float32)
    x2 = np.full((4, 7, 2), 2.0, np.float32)     # different shape per input
    y1 = np.zeros((4, 2), np.float32)
    y2 = np.ones((4, 1), np.float32)
    src = ListDataSetIterator([DataSet([x1, x2], [y1, y2])], batch_size=4)
    export_dataset_iterator(src, str(tmp_path / "mi"))
    ds = next(iter(ShardedFileDataSetIterator(str(tmp_path / "mi"))))
    assert isinstance(ds.features, list) and len(ds.features) == 2
    np.testing.assert_allclose(ds.features[1], x2)
    assert isinstance(ds.labels, list)
    np.testing.assert_allclose(ds.labels[1], y2)

    with pytest.raises(ValueError, match="gets no shards"):
        ShardedFileDataSetIterator(str(tmp_path / "mi"), shard_index=1,
                                   num_shards=2)  # only 1 shard file


def test_export_none_labels_and_none_holes(tmp_path):
    """Unlabeled DataSets export/read back (labels stay None — no pickled
    object arrays); list values keep None holes at their positions."""
    from deeplearning4j_tpu.datasets import (ShardedFileDataSetIterator,
                                             export_dataset_iterator)
    from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator

    x = np.ones((4, 3), np.float32)
    export_dataset_iterator(ListDataSetIterator([DataSet(x, None)],
                                                batch_size=4),
                            str(tmp_path / "unl"))
    ds = next(iter(ShardedFileDataSetIterator(str(tmp_path / "unl"))))
    np.testing.assert_allclose(ds.features, x)
    assert ds.labels is None

    y = [np.zeros((4, 2), np.float32), np.ones((4, 1), np.float32)]
    m = [None, np.ones((4,), np.float32)]
    export_dataset_iterator(
        ListDataSetIterator([DataSet([x, x], y, None, m)], batch_size=4),
        str(tmp_path / "holes"))
    ds2 = next(iter(ShardedFileDataSetIterator(str(tmp_path / "holes"))))
    assert isinstance(ds2.labels_mask, list) and len(ds2.labels_mask) == 2
    assert ds2.labels_mask[0] is None
    np.testing.assert_allclose(ds2.labels_mask[1], m[1])


def test_sharded_iterator_reads_legacy_multi_input_shards(tmp_path):
    """Shards written before the _len marker (bare _inJ parts) still read."""
    from deeplearning4j_tpu.datasets import ShardedFileDataSetIterator
    d = tmp_path / "legacy"
    d.mkdir()
    np.savez(str(d / "shard_00000.npz"),
             features_0_in0=np.ones((2, 3), np.float32),
             features_0_in1=np.full((2, 5), 2.0, np.float32),
             labels_0=np.zeros((2, 2), np.float32))
    ds = next(iter(ShardedFileDataSetIterator(str(d))))
    assert isinstance(ds.features, list) and len(ds.features) == 2
    np.testing.assert_allclose(ds.features[1], 2.0)


def test_legacy_shard_none_hole_positions_survive(tmp_path):
    """Legacy shards encode None holes by ABSENCE of an index: the reader
    reconstructs parts at their parsed positions."""
    from deeplearning4j_tpu.datasets import ShardedFileDataSetIterator
    d = tmp_path / "legacy2"
    d.mkdir()
    np.savez(str(d / "shard_00000.npz"),
             features_0=np.ones((2, 3), np.float32),
             labels_0_in0=np.zeros((2, 2), np.float32),
             labels_0_in1=np.ones((2, 1), np.float32),
             labels_mask_0_in1=np.ones((2,), np.float32))  # hole at 0
    ds = next(iter(ShardedFileDataSetIterator(str(d))))
    assert isinstance(ds.labels_mask, list) and len(ds.labels_mask) == 2
    assert ds.labels_mask[0] is None
    np.testing.assert_allclose(ds.labels_mask[1], 1.0)

