"""Threshold compression + GradientsAccumulator seam (reference
EncodingHandler.java:64-66 thresholdEncode/Decode semantics, residual error
feedback, and DP training through the accumulator hook)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.ops.compression import (ThresholdPayload,
                                                threshold_decode,
                                                threshold_encode,
                                                threshold_roundtrip)
from deeplearning4j_tpu.optimize.updaters import Sgd
from deeplearning4j_tpu.parallel.accumulation import (EncodedAccumulator,
                                                      PsumAccumulator)
from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper

R = np.random.default_rng(13)


def test_encode_decode_roundtrip_quantizes_above_threshold():
    g = jnp.asarray([0.5, -0.2, 0.05, -0.9, 0.0, 0.11])
    payload, residual = threshold_encode(g, threshold=0.1, capacity=6)
    update = threshold_decode(payload, 0.1, 6, g.dtype)
    # entries with |g| >= 0.1 became +-0.1; others 0
    np.testing.assert_allclose(np.asarray(update),
                               [0.1, -0.1, 0.0, -0.1, 0.0, 0.1], atol=1e-7)
    assert int(payload.count) == 4
    # residual carries exactly what was not sent
    np.testing.assert_allclose(np.asarray(residual + update), np.asarray(g),
                               atol=1e-7)


def test_encode_capacity_caps_payload():
    g = jnp.asarray(R.normal(size=(100,)).astype(np.float32))
    payload, residual = threshold_encode(g, threshold=1e-4, capacity=10)
    assert payload.indices.shape == (10,)
    assert int(payload.count) <= 10
    update = threshold_decode(payload, 1e-4, 100, g.dtype)
    assert int(jnp.sum(update != 0)) <= 10
    # compaction semantics: the 10 sent entries are the FIRST 10 above
    # threshold in index order (reference EncodingHandler has no magnitude
    # ordering; overflow stays in the residual and ships next round)
    sent_idx = np.asarray(payload.indices).tolist()
    first10 = np.where(np.abs(np.asarray(g)) >= 1e-4)[0][:10].tolist()
    assert sent_idx == first10


def test_residual_feedback_retransmits_small_values():
    """A value below threshold must accumulate in the residual and be sent
    once it crosses the threshold (Strom error feedback)."""
    size = 4
    residual = jnp.zeros((size,), jnp.float32)
    g = jnp.asarray([0.04, 0.0, 0.0, 0.0], jnp.float32)
    sent_total = np.zeros(size, np.float32)
    for _ in range(5):   # 5 * 0.04 = 0.2 -> two 0.1-quanta sent along the way
        update, residual, _ = threshold_roundtrip(residual + g,
                                                  threshold=0.1, capacity=4)
        sent_total += np.asarray(update)
    np.testing.assert_allclose(sent_total[0] + float(residual[0]), 0.2,
                               atol=1e-6)
    assert sent_total[0] > 0.0


def test_roundtrip_is_jittable_static_shapes():
    g = jnp.asarray(R.normal(size=(1000,)).astype(np.float32))
    update, residual, payload = threshold_roundtrip(g, threshold=0.01,
                                                    capacity=100)
    assert payload.indices.shape == (100,)
    assert payload.signs.dtype == jnp.int8
    np.testing.assert_allclose(np.asarray(update + residual), np.asarray(g),
                               atol=1e-6)


def _dp_net(updater=None):
    conf = (NeuralNetConfiguration(seed=4, updater=updater or Sgd(0.1),
                                   dtype="float32")
            .list(DenseLayer(n_in=6, n_out=16, activation="tanh"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _dp_data(n=128):
    x = R.normal(size=(n, 6)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(-1) > 0).astype(int)]
    return x, y


def test_psum_accumulator_matches_default_sync_path():
    """The accumulator seam with an exact PsumAccumulator must reproduce the
    GSPMD-psum path bit-for-bit (same math, different plumbing)."""
    x, y = _dp_data()
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    a = _dp_net()
    b = _dp_net()
    b.set_params_flat(a.params_flat())
    ParallelWrapper(a).fit(it, epochs=2)
    it.reset()
    ParallelWrapper(b, gradient_accumulator=PsumAccumulator()).fit(it, epochs=2)
    np.testing.assert_allclose(np.asarray(a.params_flat()),
                               np.asarray(b.params_flat()), atol=1e-6)


def test_encoded_accumulator_converges():
    """DP training through threshold compression still learns the task
    (reference convergence claim for threshold SGD with error feedback)."""
    x, y = _dp_data(256)
    it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    # raw-gradient quanta are +-threshold, so the effective step per entry is
    # lr*threshold — pick them jointly (the reference encodes post-updater
    # updates, where lr is already folded in)
    net = _dp_net(updater=Sgd(2.0))
    acc = EncodedAccumulator(threshold=0.01, capacity_fraction=0.5)
    pw = ParallelWrapper(net, gradient_accumulator=acc)
    s0 = net.score(x, y)
    pw.fit(it, epochs=25)
    s1 = net.score(x, y)
    assert s1 < s0
    ev = net.evaluate(x, y)
    assert ev.accuracy() > 0.8
    # residuals are per-worker state with the mesh leading dim
    assert pw._acc_state.shape == (pw.n, net.num_params())


def test_native_codec_matches_xla_path():
    """The C++ host codec (native/threshold_codec.cpp — the analogue of the
    reference's native ND4J thresholdEncode/Decode) must agree exactly with
    the XLA implementation."""
    from deeplearning4j_tpu import native
    if not native.available():
        pytest.skip("no C++ toolchain on this host")
    g = R.normal(size=(500,)).astype(np.float32)
    for threshold, capacity in [(0.01, 50), (0.5, 500), (2.0, 100)]:
        payload, res_x = threshold_encode(jnp.asarray(g), threshold, capacity)
        idx, signs, count, res_c = native.native_threshold_encode(
            g, threshold, capacity)
        assert count == int(payload.count)
        np.testing.assert_allclose(res_c, np.asarray(res_x), atol=1e-6)
        dec_x = threshold_decode(payload, threshold, 500, jnp.float32)
        dec_c = native.native_threshold_decode(idx, signs, threshold, 500)
        np.testing.assert_allclose(dec_c, np.asarray(dec_x), atol=1e-6)


def test_dense_encode_exact_reference_semantics():
    """threshold_encode_dense: EVERY entry above threshold ships as
    +-threshold and is subtracted from the residual (reference
    EncodingHandler semantics, no capacity bound)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.compression import threshold_encode_dense

    r = jnp.asarray(np.array([0.5, -0.002, 0.0009, -1.5, 0.001], np.float32))
    sent, new_r = threshold_encode_dense(r, 1e-3)
    np.testing.assert_allclose(np.asarray(sent),
                               [1e-3, -1e-3, 0.0, -1e-3, 1e-3], atol=1e-9)
    np.testing.assert_allclose(np.asarray(new_r),
                               np.asarray(r) - np.asarray(sent), atol=1e-9)


def test_encoded_accumulator_bf16_gradients():
    """bf16 gradients through the dense EncodedAccumulator on the 8-device
    mesh: the combine stays in bf16 end to end (no silent f32 promotion)
    and matches the manual bf16 threshold math exactly."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from deeplearning4j_tpu.parallel.accumulation import EncodedAccumulator
    from deeplearning4j_tpu.parallel.mesh import make_mesh, shard_map

    n, sz = 8, 64
    mesh = make_mesh((n,), ("data",))
    acc = EncodedAccumulator(threshold=1e-2)
    grads = jnp.asarray(R.normal(0, 2e-2, (n, sz)), jnp.bfloat16)
    state = acc.init(sz, jnp.bfloat16)
    assert state.dtype == jnp.bfloat16
    states = jnp.broadcast_to(state, (n, sz))

    def worker(g, s):
        u, ns = acc.combine(g[0], s[0], axis="data")
        return u[None], ns[None]

    u, ns = jax.jit(shard_map(worker, mesh=mesh,
                              in_specs=(P("data"), P("data")),
                              out_specs=(P("data"), P("data")),
                              check_vma=False))(grads, states)
    assert u.dtype == jnp.bfloat16 and ns.dtype == jnp.bfloat16
    t = jnp.asarray(1e-2, jnp.bfloat16)
    sent = jnp.where(jnp.abs(grads) >= t, jnp.sign(grads) * t,
                     jnp.zeros((), jnp.bfloat16))
    np.testing.assert_array_equal(
        np.asarray(ns, np.float32), np.asarray(grads - sent, np.float32))
    np.testing.assert_allclose(
        np.asarray(u[0], np.float32),
        np.asarray(jnp.mean(sent.astype(jnp.float32), axis=0)), atol=1e-2)


def test_all_below_threshold_step_ships_nothing():
    """A step where NO entry clears the threshold: the dense path ships an
    all-zero update and the residual is carried bit-exactly; the topk
    payload is EMPTY (count 0, all slots sign 0) and decodes to zero."""
    g = jnp.asarray(R.normal(0, 1e-4, (256,)).astype(np.float32))
    # dense
    from deeplearning4j_tpu.ops.compression import threshold_encode_signs
    signs, res = threshold_encode_signs(g, 1.0)
    assert int(jnp.sum(jnp.abs(signs.astype(jnp.int32)))) == 0
    np.testing.assert_array_equal(np.asarray(res), np.asarray(g))
    # bounded payload
    payload, res2 = threshold_encode(g, 1.0, capacity=32)
    assert int(payload.count) == 0
    assert int(jnp.sum(jnp.abs(payload.signs.astype(jnp.int32)))) == 0
    np.testing.assert_array_equal(np.asarray(res2), np.asarray(g))
    update = threshold_decode(payload, 1.0, 256, g.dtype)
    assert not np.any(np.asarray(update))


def test_residual_carry_bit_exact_across_steps():
    """>=3 consecutive combine steps: the residual state must equal the
    sequentially-computed reference BITWISE at every step (error feedback
    drifts when the carry is even one ulp off)."""
    from deeplearning4j_tpu.ops.compression import threshold_encode_signs

    size = 512
    threshold = 5e-3
    rng = np.random.default_rng(77)
    grads = [jnp.asarray(rng.normal(0, 4e-3, (size,)).astype(np.float32))
             for _ in range(4)]
    res = jnp.zeros((size,), jnp.float32)
    ref = np.zeros((size,), np.float32)
    t32 = np.float32(threshold)
    for g in grads:
        signs, res = threshold_encode_signs(res + g, threshold)
        # numpy reference computed in f32 with identical op order
        acc = ref + np.asarray(g)
        s = np.where(np.abs(acc) >= t32, np.sign(acc).astype(np.float32),
                     np.float32(0))
        ref = acc - s * t32
        np.testing.assert_array_equal(np.asarray(res), ref)
        np.testing.assert_array_equal(
            np.asarray(signs), s.astype(np.int8))


def test_encoded_accumulator_dense_matches_manual():
    """EncodedAccumulator(encoder='dense') on the 8-device mesh: the applied
    update equals the mean of per-worker thresholded residuals, and the
    residual carries the unsent mass."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P
    from deeplearning4j_tpu.parallel.accumulation import EncodedAccumulator
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    n, sz = 8, 64
    mesh = make_mesh((n,), ("data",))
    acc = EncodedAccumulator(threshold=1e-2)
    rng = np.random.default_rng(0)
    grads = jnp.asarray(rng.normal(0, 2e-2, (n, sz)).astype(np.float32))
    state = jnp.zeros((n, sz), jnp.float32)

    def worker(g, s):
        u, ns = acc.combine(g[0], s[0], axis="data")
        return u[None], ns[None]

    u, ns = jax.jit(shard_map(worker, mesh=mesh, in_specs=(P("data"), P("data")),
                              out_specs=(P("data"), P("data")),
                              check_vma=False))(grads, state)
    g_np = np.asarray(grads)
    sent = np.where(np.abs(g_np) >= 1e-2, np.sign(g_np) * 1e-2, 0.0)
    np.testing.assert_allclose(np.asarray(u)[0], sent.mean(0), atol=1e-7)
    np.testing.assert_allclose(np.asarray(ns), g_np - sent, atol=1e-7)
