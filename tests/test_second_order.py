"""Second-order solvers: line search, CG, LBFGS (reference
optimize/solvers/{LineGradientDescent,ConjugateGradient,LBFGS,
BackTrackLineSearch}.java; OptimizationAlgorithm dispatch)."""
import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.second_order import (BackTrackLineSearch,
                                                      LBFGS, make_optimizer)
from deeplearning4j_tpu.optimize.updaters import Sgd

R = np.random.default_rng(8)


def test_backtrack_line_search_quadratic():
    f = lambda x: float(np.sum((x - 1.0) ** 2))
    x0 = np.zeros(3)
    g0 = 2 * (x0 - 1.0)
    ls = BackTrackLineSearch(max_iterations=20)
    step, fx = ls.search(f, x0, -g0, f(x0), g0, initial_step=1.0)
    assert step > 0
    assert fx < f(x0)
    # ascent direction is rejected
    step2, fx2 = ls.search(f, x0, g0, f(x0), g0)
    assert step2 == 0.0 and fx2 == f(x0)


def _net(algo, seed=4):
    conf = (NeuralNetConfiguration(seed=seed, updater=Sgd(0.1), dtype="float64",
                                   optimization_algorithm=algo,
                                   max_num_line_search_iterations=8)
            .list(DenseLayer(n_in=4, n_out=12, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=120):
    x = R.normal(size=(n, 4))
    yi = (x[:, 0] + x[:, 1] > 0).astype(int) + (x[:, 2] > 0.5).astype(int)
    return x, np.eye(3)[yi]


@pytest.mark.parametrize("algo", ["line_gradient_descent",
                                  "conjugate_gradient", "lbfgs"])
def test_second_order_solvers_reduce_score(algo):
    net = _net(algo)
    x, y = _data()
    s0 = net.score(x, y)
    net.fit(x, y, epochs=25, batch_size=120)   # full-batch outer iterations
    s1 = net.score(x, y)
    assert s1 < s0 * 0.8, (s0, s1)
    assert net.evaluate(x, y).accuracy() > 0.7


def test_lbfgs_beats_plain_gd_on_quadratic_net():
    """On a smooth full-batch objective LBFGS should make at least as much
    progress per outer iteration as steepest descent."""
    x, y = _data(80)
    a = _net("line_gradient_descent", seed=6)
    b = _net("lbfgs", seed=6)
    b.set_params_flat(a.params_flat())
    a.fit(x, y, epochs=15, batch_size=80)
    b.fit(x, y, epochs=15, batch_size=80)
    assert b.score(x, y) <= a.score(x, y) * 1.05


def test_unknown_algorithm_raises():
    net = _net("sgd")
    with pytest.raises(ValueError, match="available"):
        make_optimizer("newton", net)


def test_lbfgs_history_curvature_guard():
    net = _net("lbfgs")
    opt = LBFGS(net)
    x, y = _data(40)
    for _ in range(6):
        opt.step(x, y)
    assert len(opt._hist) >= 1
    for s, yv in opt._hist:
        assert float(s @ yv) > 0
