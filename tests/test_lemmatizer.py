"""Rule lemmatizer (nlp/lemmatizer.py) — the UIMA lemma seam
(PosUimaTokenizer.java:76-77) without analysis-engine downloads."""
import pytest

from deeplearning4j_tpu.nlp import (LemmatizingTokenizerFactory,
                                    RuleBasedLemmatizer)
from deeplearning4j_tpu.nlp.tokenizer import (CommonPreprocessor,
                                              DefaultTokenizerFactory)


@pytest.mark.parametrize("word,lemma", [
    ("running", "run"), ("makes", "make"), ("driving", "drive"),
    ("tried", "try"), ("wanted", "want"), ("stopped", "stop"),
    ("cities", "city"), ("dogs", "dog"), ("boxes", "box"),
    ("churches", "church"), ("heroes", "hero"), ("leaves", "leaf"),
    ("was", "be"), ("is", "be"), ("been", "be"), ("has", "have"),
    ("went", "go"), ("taken", "take"), ("children", "child"),
    ("women", "woman"), ("wrote", "write"), ("bigger", "big"),
    ("best", "good"), ("earliest", "early"),
    # must NOT be mangled
    ("this", "this"), ("news", "news"), ("glass", "glass"),
    ("series", "series"), ("run", "run"), ("red", "red"),
])
def test_lemma_cases(word, lemma):
    assert RuleBasedLemmatizer().lemmatize(word) == lemma


def test_factory_wraps_any_tokenizer():
    f = LemmatizingTokenizerFactory(DefaultTokenizerFactory())
    toks = f.create("the children were running and the dogs barked").get_tokens()
    assert toks == ["the", "child", "be", "run", "and", "the", "dog", "bark"]


def test_factory_composes_with_preprocessor():
    f = LemmatizingTokenizerFactory(DefaultTokenizerFactory())
    f.set_token_pre_processor(CommonPreprocessor())
    toks = f.create("Dogs, running!").get_tokens()
    assert "dog" in toks and "run" in toks


def test_vocab_folding_shrinks_vocabulary():
    """The use case the reference's lemma path serves: inflected variants
    fold into one vocabulary entry for embedding training."""
    text = ("the dog runs . the dogs ran . a dog is running . "
            "dogs have run .")
    base = DefaultTokenizerFactory()
    lem = LemmatizingTokenizerFactory(base)
    v_base = set(base.create(text).get_tokens())
    v_lem = set(lem.create(text).get_tokens())
    assert {"dog", "run"} <= v_lem
    assert not {"dogs", "running", "ran"} & v_lem
    assert len(v_lem) < len(v_base)


@pytest.mark.parametrize("word,lemma", [
    # multi-syllable regular verbs must NOT grow an invented trailing e
    ("opened", "open"), ("happened", "happen"), ("visited", "visit"),
    ("listened", "listen"), ("covered", "cover"), ("opening", "open"),
    # stems that really dropped an e still restore it
    ("believed", "believe"), ("received", "receive"), ("danced", "dance"),
    ("argued", "argue"), ("loved", "love"),
])
def test_restore_e_multisyllable(word, lemma):
    assert RuleBasedLemmatizer().lemmatize(word) == lemma


def test_pos_disambiguates_irregular_forms():
    """The caller's Penn tag picks the reading: 'lives' is the verb
    'live' as VBZ but the noun 'life' as NNS."""
    L = RuleBasedLemmatizer()
    assert L.lemmatize("lives", "VBZ") == "live"
    assert L.lemmatize("lives", "NNS") == "life"
    assert L.lemmatize("leaves", "VBZ") == "leave"
    assert L.lemmatize("leaves", "NNS") == "leaf"
    # a mis-tagged unambiguous irregular still folds
    assert L.lemmatize("children", "VB") == "child"
    assert L.lemmatize("went", "NN") == "go"
