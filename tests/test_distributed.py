"""Multi-host smoke test: 2 CPU processes + gloo collectives (the analogue of
the reference's Spark local[n] testing, SURVEY.md §4; VERDICT r1 item 10).

Each subprocess joins the coordination service via
distributed.initialize_distributed, builds the 2-device global mesh, and runs
a shard_map psum plus one data-parallel gradient step where each process
holds HALF the global batch — asserting both see the identical combined
gradient."""
import os
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent("""
import sys
import numpy as np
pid = int(sys.argv[1])
port = sys.argv[2]
import jax
jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu.parallel import distributed
distributed.initialize_distributed(f"127.0.0.1:{port}", num_processes=2,
                                   process_id=pid, cpu_collectives="gloo")
assert distributed.process_count() == 2
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from deeplearning4j_tpu.parallel.mesh import shard_map

mesh = distributed.global_mesh(("data",))
assert mesh.devices.size == 2

# psum across hosts
f = jax.jit(shard_map(lambda a: jax.lax.psum(a, "data"), mesh=mesh,
                      in_specs=P("data"), out_specs=P()))
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")),
    np.asarray([float(pid + 1)], np.float32), (2,))
out = jax.device_get(f(arr))
assert float(out[0]) == 3.0, out     # 1 + 2

# one DP gradient step: per-process half-batches, identical combined grad
W = jnp.ones((4, 2))
xs = np.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], np.float32) * (pid + 1)
gx = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), xs, (4, 4))

def loss(W, x):
    return jnp.mean((x @ W) ** 2)

g = jax.jit(jax.grad(loss),
            in_shardings=(NamedSharding(mesh, P()),
                          NamedSharding(mesh, P("data"))),
            out_shardings=NamedSharding(mesh, P()))(W, gx)
g_local = np.asarray(jax.device_get(
    [s.data for s in g.addressable_shards][0]))
print("PID", pid, "grad00", float(g_local[0, 0]), flush=True)
print(f"WORKER_{pid}_OK", flush=True)
""")


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_WORKER4 = textwrap.dedent("""
import os, sys
import numpy as np
pid = int(sys.argv[1])
port = sys.argv[2]
ckpt_dir = sys.argv[3]
import jax
jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu.parallel import distributed
distributed.initialize_distributed(f"127.0.0.1:{port}", num_processes=4,
                                   process_id=pid, cpu_collectives="gloo")
assert distributed.process_count() == 4
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from deeplearning4j_tpu.util.distributed_checkpoint import (
    save_sharded_checkpoint, restore_sharded_checkpoint)

# 2x2 data x model mesh over 4 single-device processes
mesh = distributed.global_mesh(("data", "model"), shape=(2, 2))
assert mesh.devices.shape == (2, 2)

# a tensor-parallel matmul + data-parallel batch: y = x @ W with W sharded
# over 'model' columns and x sharded over 'data' rows
W_global = np.arange(16, dtype=np.float32).reshape(4, 4)
x_global = np.arange(32, dtype=np.float32).reshape(8, 4) / 10.0
wsh = NamedSharding(mesh, P(None, "model"))
xsh = NamedSharding(mesh, P("data", None))
# each process owns one device = one (data, model) block
W = jax.make_array_from_callback((4, 4), wsh, lambda idx: W_global[idx])
x = jax.make_array_from_callback((8, 4), xsh, lambda idx: x_global[idx])

@jax.jit
def f(x, W):
    return x @ W
y = f(x, W)
y_local = np.asarray(y.addressable_shards[0].data)
want = (x_global @ W_global)
idx = y.addressable_shards[0].index
np.testing.assert_allclose(y_local, want[idx], rtol=1e-6)

# ---- distributed checkpoint across 4 processes: every process writes its
# own shard file; process 0 writes the manifest; all restore and verify
tree = {"W": W, "x": x}
save_sharded_checkpoint(ckpt_dir, 11, tree)
# wait until all 4 per-process files + manifest exist (shared tmp dir)
import time
deadline = time.time() + 60
while time.time() < deadline:
    names = set(os.listdir(ckpt_dir))
    if {"ckpt_step11.json"} | {f"ckpt_step11_p{i:03d}.npz" for i in range(4)} \
            <= names:
        break
    time.sleep(0.2)
like = {"W": jax.make_array_from_callback((4, 4), wsh,
                                          lambda idx: np.zeros((4, 4),
                                          np.float32)[idx]),
        "x": jax.make_array_from_callback((8, 4), xsh,
                                          lambda idx: np.zeros((8, 4),
                                          np.float32)[idx])}
got = restore_sharded_checkpoint(ckpt_dir, 11, like)
np.testing.assert_array_equal(
    np.asarray(got["W"].addressable_shards[0].data),
    np.asarray(W.addressable_shards[0].data))
np.testing.assert_array_equal(
    np.asarray(got["x"].addressable_shards[0].data),
    np.asarray(x.addressable_shards[0].data))
print(f"WORKER_{pid}_OK", flush=True)
""")


def test_two_process_cpu_distributed(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)   # exactly 1 local CPU device per process
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen([sys.executable, "-c", _WORKER, str(i), str(port)],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-2000:]}"
        assert f"WORKER_{i}_OK" in out
    # both processes computed the same replicated combined gradient
    g0 = [l for l in outs[0].splitlines() if l.startswith("PID 0 grad00")]
    g1 = [l for l in outs[1].splitlines() if l.startswith("PID 1 grad00")]
    assert g0 and g1
    assert g0[0].split()[-1] == g1[0].split()[-1]


@pytest.mark.slow
def test_four_process_mesh_and_distributed_checkpoint(tmp_path):
    """4 CPU processes on a 2x2 data x model mesh: tensor-parallel matmul
    correctness + cross-process sharded checkpoint save/restore (VERDICT r3
    item 3; reference analogue: the Spark driver's resumable mid-run state,
    ParameterAveragingTrainingWorker.java:269)."""
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)   # exactly 1 local CPU device per process
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt, exist_ok=True)
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER4, str(i), str(port), ckpt],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(4)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-2000:]}"
        assert f"WORKER_{i}_OK" in out
