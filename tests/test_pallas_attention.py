"""Fused flash-attention kernels (ops/pallas_attention.py): parity against
the XLA reference path (parallel/ring_attention.attention) across
causal x mask x dtype, gradients included, plus the layer-level seam.
Interpreter mode on CPU (conftest sets DL4J_TPU_FUSED_ATTN_INTERPRET)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.pallas_attention import (flash_attention,
                                                     fused_attention_applicable)
from deeplearning4j_tpu.parallel.ring_attention import attention

R = np.random.default_rng(11)
B, H, T, D = 2, 2, 256, 128


def _qkv(dtype=jnp.float32):
    return tuple(jnp.asarray(R.normal(size=(B, H, T, D)), dtype)
                 for _ in range(3))


def _mask():
    lens = R.integers(T // 4, T, B)
    return jnp.asarray((np.arange(T)[None, :] < lens[:, None])
                       .astype(np.float32))


def test_applicability_probe():
    assert fused_attention_applicable(B, H, T, D, jnp.float32)
    assert fused_attention_applicable(B, H, T, D, jnp.bfloat16)
    # GPT-2-class head dims ride Mosaic's minor-dim padding (round-5)
    assert fused_attention_applicable(B, H, T, 64, jnp.float32)
    assert fused_attention_applicable(B, H, T, 96, jnp.float32)
    assert not fused_attention_applicable(B, H, T, 80, jnp.float32)   # odd D
    assert not fused_attention_applicable(B, H, 200, D, jnp.float32)  # T%128
    assert not fused_attention_applicable(B, H, 128, D, jnp.float32)  # tiny T
    assert not fused_attention_applicable(B, H, T, D, jnp.float64)


@pytest.mark.parametrize("d", [
    64,
    # d=96 in the slow lane (ISSUE 14 tier-1 budget reclaim): ~5s second
    # head-dim config; d=64 keeps the small-head-dim kernel path tier-1
    pytest.param(96, marks=pytest.mark.slow),
])
def test_small_head_dim_parity(d):
    """D=64/96 (the common transformer head dims) engage the fused path
    and match the XLA reference, gradients included."""
    q, k, v = (jnp.asarray(R.normal(size=(B, H, T, d)), jnp.float32)
               for _ in range(3))
    km = _mask()
    ours = flash_attention(q, k, v, causal=True, key_mask=km)
    ref = attention(q, k, v, causal=True, key_mask=km)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref), atol=2e-5)

    def lf(fn):
        def loss(q, k, v):
            out = fn(q, k, v, causal=True, key_mask=km)
            return jnp.sum(out * out)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    for name, a, b in zip("qkv", lf(flash_attention), lf(attention)):
        rel = (float(jnp.max(jnp.abs(a - b)))
               / (float(jnp.max(jnp.abs(b))) + 1e-9))
        assert rel < 1e-4, (name, rel)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("masked", [False, True])
def test_forward_parity(causal, masked):
    q, k, v = _qkv()
    km = _mask() if masked else None
    ours = flash_attention(q, k, v, causal=causal, key_mask=km)
    ref = attention(q, k, v, causal=causal, key_mask=km)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref), atol=2e-5)


def test_gradient_parity_causal_masked():
    q, k, v = _qkv()
    km = _mask()

    def lf(fn):
        def loss(q, k, v):
            out = fn(q, k, v, causal=True, key_mask=km)
            return jnp.sum(out * out)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    g_fused = lf(flash_attention)
    g_ref = lf(attention)
    for name, a, b in zip("qkv", g_fused, g_ref):
        rel = (float(jnp.max(jnp.abs(a - b)))
               / (float(jnp.max(jnp.abs(b))) + 1e-9))
        assert rel < 1e-4, (name, rel)


def test_asymmetric_blocks_parity_t1024():
    """T>=1024 selects the autotuned ASYMMETRIC default (BQ=512, BK=1024)
    — the config every real model run uses. Parity incl. gradients guards
    kernel edits that are only correct when BQ == BK."""
    from deeplearning4j_tpu.ops.pallas_attention import _blocks
    assert _blocks(1024) == (512, 1024)
    T2 = 1024
    q, k, v = (jnp.asarray(R.normal(size=(1, 2, T2, 64)), jnp.float32)
               for _ in range(3))
    km = jnp.asarray((np.arange(T2)[None, :] <
                      np.asarray([700, 1024])[:, None]).astype(np.float32))
    km = km[:1]
    ours = flash_attention(q, k, v, causal=True, key_mask=km)
    ref = attention(q, k, v, causal=True, key_mask=km)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref), atol=2e-5)

    def lf(fn):
        def loss(q, k, v):
            return jnp.sum(fn(q, k, v, causal=True, key_mask=km) ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    for name, a, b in zip("qkv", lf(flash_attention), lf(attention)):
        rel = (float(jnp.max(jnp.abs(a - b)))
               / (float(jnp.max(jnp.abs(b))) + 1e-9))
        assert rel < 1e-4, (name, rel)


def test_bf16_io_close_to_f32():
    qf, kf, vf = _qkv(jnp.float32)
    q, k, v = (a.astype(jnp.bfloat16) for a in (qf, kf, vf))
    out_bf = flash_attention(q, k, v, causal=True)
    out_f = flash_attention(qf, kf, vf, causal=True)
    assert out_bf.dtype == jnp.bfloat16
    # f32 accumulation + f32 softmax recurrence: bf16 operand rounding
    # of p per block compounds only mildly across T/BK updates
    np.testing.assert_allclose(np.asarray(out_bf, np.float32),
                               np.asarray(out_f), atol=0.05)


def test_fully_masked_row_is_uniform_not_nan():
    q, k, v = _qkv()
    km = jnp.zeros((B, T), jnp.float32)     # everything masked
    out = flash_attention(q, k, v, key_mask=km)
    ref = jnp.mean(v, axis=2, keepdims=True)  # uniform attention
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out),
                               np.broadcast_to(np.asarray(ref), out.shape),
                               atol=2e-5)


def test_layer_routes_through_fused_path(monkeypatch):
    """SelfAttentionLayer parity fused-vs-XLA through the layer seam
    (Dh = n_out/n_heads = 128 makes the probe pass)."""
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers import SelfAttentionLayer

    layer = SelfAttentionLayer(n_in=16, n_out=256, n_heads=2, causal=True)
    params, state = layer.init(jax.random.PRNGKey(0),
                               InputType.recurrent(16, T), jnp.float32)
    x = jnp.asarray(R.normal(size=(2, T, 16)), jnp.float32)
    outs = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("DL4J_TPU_FUSED_ATTENTION", flag)
        out, _ = layer.apply(params, state, x)
        outs[flag] = np.asarray(out)
    np.testing.assert_allclose(outs["1"], outs["0"], atol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_multi_block_grid_parity(causal):
    """T=768 -> _block(768)=256 -> a 3x3 block grid: exercises the
    online-softmax (acc,m,l) rescale carry across k-blocks, the causal
    block-skip predicate, and cross-block dq/dkv accumulation — logic a
    single-block T=256 test never touches. Interpreter mode = f32-exact."""
    T2 = 768
    q, k, v = (jnp.asarray(R.normal(size=(1, 2, T2, 128)), jnp.float32)
               for _ in range(3))
    lens = R.integers(T2 // 4, T2, 1)
    km = jnp.asarray((np.arange(T2)[None, :] < lens[:, None])
                     .astype(np.float32))
    for mask in (None, km):
        ours = flash_attention(q, k, v, causal=causal, key_mask=mask)
        ref = attention(q, k, v, causal=causal, key_mask=mask)
        np.testing.assert_allclose(np.asarray(ours), np.asarray(ref),
                                   atol=3e-5,
                                   err_msg=f"mask={mask is not None}")

    def lf(fn):
        def loss(q, k, v):
            return jnp.sum(fn(q, k, v, causal=causal, key_mask=km) ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", lf(flash_attention), lf(attention)):
        rel = (float(jnp.max(jnp.abs(a - b)))
               / (float(jnp.max(jnp.abs(b))) + 1e-9))
        assert rel < 1e-4, (name, rel)
