"""int8-quantized KV cache + int8 serving forward (ISSUE 17).

Pins:
  - capacity: the int8 pool holds >= 1.9x the tokens per byte of the f32
    pool at the same ``num_blocks`` (the acceptance currency), measured
    BOTH ways: raw ``pool_bytes`` on ``make_pools`` output and the
    published ``kv_bytes_per_token`` engine row/gauge;
  - determinism: quantize-on-write is one deterministic expression, so
    quantized greedy decode is self-consistent — repeated runs identical,
    prefix-cache hit == miss token-for-token, speculative == plain
    token-for-token (each against its OWN quantized baseline — the int8
    tier never promises f32 token identity);
  - zero steady-state recompiles under concurrent quantized decode (the
    QuantizedPool is a pytree: the warmed programs, donation and COW all
    run unchanged);
  - config validation: only None/'int8' dtypes; the state adapter (no
    token-addressed pool) rejects the quantized tier;
  - the int8 dynamic-quantized serving forward stays within the
    bounded-error tier vs the f32 forward on a dense net.
"""
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.decode import truncated_draft
from deeplearning4j_tpu.models.zoo_extra import (text_generation_lstm,
                                                 transformer_lm)
from deeplearning4j_tpu.serving import (GenerationEngine,
                                        xla_compile_count)
from deeplearning4j_tpu.serving.generation.kvcache import (
    QuantizedPool, kv_dequantize, kv_quantize, make_pools, pool_bytes)
from deeplearning4j_tpu.serving.generation.programs import GenerationConfig
from deeplearning4j_tpu.telemetry import RecompileDetector

R = np.random.default_rng(1717)


def _lm(seed=123, vocab=128, d_model=64, n_heads=2, n_blocks=2,
        max_length=64):
    return transformer_lm(vocab_size=vocab, d_model=d_model,
                          n_heads=n_heads, n_blocks=n_blocks,
                          max_length=max_length, seed=seed,
                          dtype="float32", token_input=True).init()


def _engine(net, **kw):
    kw.setdefault("block_len", 16)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("decode_slots", 4)
    kw.setdefault("prefill_batches", (1, 2))
    return GenerationEngine(net, model_name="lm", kv_cache_dtype="int8",
                            **kw)


@pytest.fixture(scope="module")
def lm_net():
    return _lm()


@pytest.fixture(scope="module")
def eng8(lm_net):
    """ONE warmed int8 engine shared by the behavioural tests (AOT warm
    is the expensive part; every test below reads deltas, not absolute
    counters, so sharing is safe)."""
    eng = _engine(lm_net, draft=truncated_draft(lm_net, 1), spec_k=3,
                  prompt_rungs=(16, 64), prefix_cache=True)
    yield eng
    eng.stop()


# ------------------------------------------------------------ quantization
def test_kv_quantize_roundtrip_bound_and_determinism():
    x = jnp.asarray(R.standard_normal((3, 16, 4, 32)) * 2.0, jnp.float32)
    q1, s1 = kv_quantize(x)
    q2, s2 = kv_quantize(x)
    assert q1.dtype == jnp.int8 and s1.dtype == jnp.float32
    assert np.array_equal(np.asarray(q1), np.asarray(q2))
    assert np.array_equal(np.asarray(s1), np.asarray(s2))
    deq = kv_dequantize(q1, s1, jnp.float32)
    # symmetric rounding: per-vector error <= half a quantization step
    step = np.asarray(s1)[..., None]
    assert np.all(np.abs(np.asarray(deq) - np.asarray(x)) <= step * 0.5 + 1e-7)
    # zero vectors stay exactly zero (scale clamps to 1, codes to 0)
    qz, sz = kv_quantize(jnp.zeros((2, 4)))
    assert np.all(np.asarray(qz) == 0) and np.all(np.asarray(sz) == 1.0)


def test_pool_capacity_per_byte():
    """ISSUE 17 acceptance: >= 1.9x tokens per byte vs the f32 pool at
    identical geometry (head_dim 32: 8*32=256 f32 bytes vs 2*(32+4)=72
    int8 bytes per token/layer/head — 3.56x)."""
    geom = dict(n_layers=2, num_blocks=8, block_len=16, n_heads=2,
                head_dim=32)
    kf, vf = make_pools(dtype=jnp.float32, **geom)
    kq, vq = make_pools(dtype=jnp.float32, quantized=True, **geom)
    assert isinstance(kq, QuantizedPool) and isinstance(vq, QuantizedPool)
    ratio = (pool_bytes(kf) + pool_bytes(vf)) / \
        (pool_bytes(kq) + pool_bytes(vq))
    assert ratio >= 1.9, ratio
    assert kq.q.shape == kf.shape and kq.scale.shape == kf.shape[:-1]


def test_kv_bytes_per_token_row_gauge_and_ratio(lm_net, eng8):
    # warm=False: the row is geometry-derived, no need to AOT-compile
    eng32 = GenerationEngine(lm_net, model_name="lm", block_len=16,
                             max_seq_len=64, decode_slots=4,
                             prefill_batches=(1, 2), warm=False)
    try:
        b8 = eng8.models()["lm"]["kv_bytes_per_token"]
        b32 = eng32.models()["lm"]["kv_bytes_per_token"]
        # d_model 64 / 2 heads -> head_dim 32: 2 layers * 2 heads *
        # (8*32) = 1024 f32 vs * (32+4)*2 = 288 int8
        assert b32 == 1024.0 and b8 == 288.0
        assert b32 / b8 >= 1.9
        assert eng8.models()["lm"]["kv_cache_dtype"] == "int8"
        assert eng8.metrics()["lm"]["kv_bytes_per_token"] == b8
    finally:
        eng32.stop()


def test_config_validation():
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        GenerationConfig(kv_cache_dtype="fp8")
    net = text_generation_lstm(vocab_size=40, hidden=32, seed=5).init()
    with pytest.raises(ValueError, match="paged"):
        GenerationEngine(net, model_name="lstm", warm=False,
                         kv_cache_dtype="int8")


# ------------------------------------------------------------- determinism
def test_quantized_greedy_deterministic_across_runs(eng8):
    prompt = R.integers(1, 128, size=8).tolist()
    eng8.generate(prompt, max_tokens=4, temperature=0.0)   # settle
    c0 = xla_compile_count()
    runs = [eng8.generate(prompt, max_tokens=16, temperature=0.0)
            for _ in range(3)]
    toks = [t for t, _ in runs]
    assert toks[0] == toks[1] == toks[2]
    assert len(toks[0]) == 16
    assert xla_compile_count() == c0     # steady-state: zero recompiles


def test_prefix_cache_hit_matches_miss_quantized(eng8):
    """The fake-quantized prefill (QuantSimStore) is the load-bearing
    part: a prefix-cache HIT replays the suffix through the decode
    program against dequantized int8 blocks, so prefill must have sampled
    from the SAME numbers — hit and miss decode identical tokens."""
    prompt = R.integers(1, 128, size=20).tolist()   # 1 full block + 4
    base, _ = eng8.generate(prompt, max_tokens=12, temperature=0.0)
    m0 = eng8.metrics()["lm"]["prefix"]
    c0 = xla_compile_count()
    hit, _ = eng8.generate(prompt, max_tokens=12, temperature=0.0)
    m1 = eng8.metrics()["lm"]["prefix"]
    assert hit == base
    assert m1["hits"] > m0["hits"]
    assert xla_compile_count() == c0     # the hit replay stays warmed


def test_speculative_matches_plain_quantized(eng8):
    prompt = R.integers(1, 128, size=8).tolist()
    c0 = xla_compile_count()
    plain, _ = eng8.generate(prompt, max_tokens=16, temperature=0.0,
                             speculative=False)
    spec, _ = eng8.generate(prompt, max_tokens=16, temperature=0.0,
                            speculative=True)
    assert spec == plain
    snap = eng8.metrics()["lm"]
    assert snap["speculative"]["verify_steps"] > 0
    assert xla_compile_count() == c0     # both paths fully warmed


def test_zero_steady_state_recompiles_concurrent_quantized(eng8):
    compiles0 = xla_compile_count()
    work = [(8, 6, 0.0), (8, 6, 0.0), (20, 5, 0.0), (20, 5, 0.0),
            (3, 8, 0.7), (13, 6, 0.0)]
    res = {}

    def client(i):
        plen, mx, temp = work[i]
        p = [(j * 7 + 1) % 120 + 1 for j in range(plen)]
        res[i] = eng8.generate(p, max_tokens=mx, temperature=temp)

    with RecompileDetector(allowed=0) as det:
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(work))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    for i, (plen, mx, _) in enumerate(work):
        assert len(res[i][0]) == mx and res[i][1] == "length", \
            (i, res[i])
    assert det.count == 0, f"steady state compiled: {det.events}"
    assert xla_compile_count() == compiles0


# -------------------------------------------------------- int8 forward tier
def test_int8_forward_bounded_error():
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.ops.kernels.quantized import int8_forward_fn
    from deeplearning4j_tpu.optimize.updaters import Sgd
    import jax

    conf = (NeuralNetConfiguration(seed=3, updater=Sgd(0.1),
                                   dtype="float32")
            .list(DenseLayer(n_in=32, n_out=64, activation="tanh"),
                  OutputLayer(n_out=8, activation="softmax",
                              loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = jnp.asarray(R.standard_normal((16, 32)), jnp.float32)
    y32 = np.asarray(net.output(x))
    fwd = jax.jit(int8_forward_fn(net))
    y8 = np.asarray(fwd(net.params, net.state, x))
    rel = np.max(np.abs(y8 - y32)) / (np.max(np.abs(y32)) + 1e-12)
    assert rel < 0.05, rel
    # int8 tier quantizes FROM full precision only
    amp = (NeuralNetConfiguration(seed=3, updater=Sgd(0.1),
                                  dtype="float32",
                                  compute_dtype="bfloat16")
           .list(DenseLayer(n_in=32, n_out=64, activation="tanh"),
                 OutputLayer(n_out=8, activation="softmax", loss="mcxent"))
           .build())
    with pytest.raises(ValueError, match="full-precision"):
        int8_forward_fn(MultiLayerNetwork(amp).init())


# -------------------------------------------------------------------- bench
@pytest.mark.bench_smoke
def test_quantized_kv_bench_smoke():
    """Tier-1 guard for the quantized_kv_decode row: zero steady-state
    compiles in BOTH pool modes, the capacity-per-byte acceptance >=
    1.9x, greedy probe parity between a run and itself (determinism is
    folded into greedy_tokens_match only when int8 == f32 — informational
    there), and the int8 window not catastrophically slower than f32.
    Three consecutive failing attempts required to fail (rig co-tenant
    bursts; the capacity ratio and compile counts are deterministic, the
    tokens/sec ratio is the noisy part)."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    row = None
    for _ in range(3):
        row = bench.bench_quantized_kv(duration=0.8, clients=3,
                                       decode_slots=4, max_new=12)
        assert row["int8_steady_state_compiles"] == 0, row
        assert row["f32_steady_state_compiles"] == 0, row
        assert row["capacity_per_byte_vs_f32"] >= 1.9, row
        if row["int8_tokens_per_sec"] >= 0.25 * row["f32_tokens_per_sec"]:
            return
    pytest.fail(f"quantized decode catastrophically slower than f32 in "
                f"3 attempts: {row}")
