"""End-to-end slice (SURVEY.md §7 stage 3 exit criterion): LeNet on the MNIST
pipeline trains and reaches high accuracy. Uses the synthetic-fallback MNIST
when the real set can't be downloaded (egress-less CI)."""
import numpy as np

from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.models.lenet import lenet
from deeplearning4j_tpu.optimize.listeners import CollectScoresIterationListener


def test_lenet_trains_on_mnist():
    train_it = MnistDataSetIterator(batch_size=128, train=True, max_examples=2048)
    test_it = MnistDataSetIterator(batch_size=256, train=False, max_examples=512)
    net = lenet(seed=7).init()
    scores = CollectScoresIterationListener()
    net.set_listeners(scores)
    net.fit(iterator=train_it, epochs=3)
    ev = net.evaluate(test_it)
    # Real MNIST: LeNet gets >97% in 3 epochs; synthetic prototype set is
    # easier but noisier — 90% is a safe floor for both.
    assert ev.accuracy() > 0.90, ev.stats()
    assert scores.scores[-1][1] < scores.scores[0][1]


def test_mnist_iterator_shapes():
    it = MnistDataSetIterator(batch_size=32, train=True, max_examples=64, flat=True)
    ds = next(iter(it))
    assert ds.features.shape == (32, 784)
    assert ds.labels.shape == (32, 10)
    assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0
    it2 = MnistDataSetIterator(batch_size=32, train=True, max_examples=64)
    ds2 = next(iter(it2))
    assert ds2.features.shape == (32, 28, 28, 1)
