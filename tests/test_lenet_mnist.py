"""End-to-end slice (SURVEY.md §7 stage 3 exit criterion): LeNet on the MNIST
pipeline trains and reaches high accuracy. Uses the synthetic-fallback MNIST
when the real set can't be downloaded (egress-less CI)."""
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.models.lenet import lenet
from deeplearning4j_tpu.optimize.listeners import CollectScoresIterationListener


@pytest.mark.slow
def test_lenet_trains_on_mnist():
    # Slow lane (ISSUE 19 tier-1 budget reclaim): ~26s 3-epoch train whose
    # contract — a LeNet-style conv net trains to held-out accuracy on a
    # real digit pipeline — stays tier-1 via
    # test_lenet_real_handwritten_digits (genuine scans, >=0.95 acc);
    # test_mnist_iterator_shapes keeps the MNIST iterator surface.
    train_it = MnistDataSetIterator(batch_size=128, train=True, max_examples=2048)
    test_it = MnistDataSetIterator(batch_size=256, train=False, max_examples=512)
    net = lenet(seed=7).init()
    scores = CollectScoresIterationListener()
    net.set_listeners(scores)
    net.fit(iterator=train_it, epochs=3)
    ev = net.evaluate(test_it)
    # Real MNIST: LeNet gets >97% in 3 epochs; synthetic prototype set is
    # easier but noisier — 90% is a safe floor for both.
    assert ev.accuracy() > 0.90, ev.stats()
    assert scores.scores[-1][1] < scores.scores[0][1]


def test_mnist_iterator_shapes():
    it = MnistDataSetIterator(batch_size=32, train=True, max_examples=64, flat=True)
    ds = next(iter(it))
    assert ds.features.shape == (32, 784)
    assert ds.labels.shape == (32, 10)
    assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0
    it2 = MnistDataSetIterator(batch_size=32, train=True, max_examples=64)
    ds2 = next(iter(it2))
    assert ds2.features.shape == (32, 28, 28, 1)


def test_lenet_real_handwritten_digits():
    """REAL handwritten-digit evidence (BASELINE row 1; no MNIST archive is
    reachable from this rig, so the real-data leg uses the UCI optical
    digits bundled with scikit-learn: 1797 genuine 8x8 scans). A LeNet-style
    conv net must reach >= 0.95 held-out accuracy — the same train-a-CNN-on-
    real-scans contract the reference's MnistClassifier example demonstrates.
    Real MNIST runs through the same pipeline when idx files are present in
    the cache dir (datasets/mnist.py load_mnist)."""
    from sklearn.datasets import load_digits

    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                              OutputLayer, SubsamplingLayer)
    from deeplearning4j_tpu.optimize.updaters import Adam

    digits = load_digits()
    x = (digits.images / 16.0).astype(np.float32)[..., None]   # [N, 8, 8, 1]
    y = np.eye(10, dtype=np.float32)[digits.target]
    rng = np.random.default_rng(0)
    order = rng.permutation(len(x))
    x, y = x[order], y[order]
    n_test = 400
    x_tr, y_tr, x_te, y_te = x[n_test:], y[n_test:], x[:n_test], y[:n_test]

    conf = (NeuralNetConfiguration(seed=7, updater=Adam(1e-3), dtype="float32")
            .list(ConvolutionLayer(n_out=16, kernel_size=(3, 3),
                                   convolution_mode="same",
                                   activation="relu"),
                  SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                   stride=(2, 2)),
                  ConvolutionLayer(n_out=32, kernel_size=(3, 3),
                                   convolution_mode="same",
                                   activation="relu"),
                  SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                   stride=(2, 2)),
                  DenseLayer(n_out=64, activation="relu"),
                  OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 1)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(x_tr, y_tr, epochs=30, batch_size=128)
    acc = net.evaluate(x_te, y_te).accuracy()
    assert acc >= 0.95, f"real-digits accuracy {acc:.4f}"
