"""CG topology x preprocessor matrix (VERDICT r3 weak #7: the reference's
127-file core suite covers config/topology combinatorics the repo sampled
thinly — reference ComputationGraphTestRNN, TestGraphNodes,
GradientCheckTestsComputationGraph CNN/RNN mixed-topology cases).

Every net here is gradient-checked in f64 (the repo's correctness backbone)
— not just shape-checked."""
import numpy as np
import pytest

from deeplearning4j_tpu import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph.graph import ComputationGraph
from deeplearning4j_tpu.nn.graph.vertices import (ElementWiseVertex,
                                                  MergeVertex)
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                          GlobalPoolingLayer, GravesLSTM,
                                          LSTM, OutputLayer, RnnOutputLayer,
                                          SubsamplingLayer)
from deeplearning4j_tpu.optimize.updaters import Sgd
from deeplearning4j_tpu.util.gradcheck import check_gradients

R = np.random.default_rng(21)


def _builder():
    return (NeuralNetConfiguration(seed=12345, updater=Sgd(0.1),
                                   dtype="float64").graph_builder())


@pytest.mark.slow
def test_video_pipeline_rnn_cnn_rnn_chain():
    """Slow lane (ISSUE 14 tier-1 budget reclaim): ~7s, the deepest chain
    in the topology matrix; both preprocessor seams it composes stay
    tier-1-covered (test_rnn_to_cnn_style_pool_then_dense and the
    elementwise-add-over-parallel-rnn-branches chain).

    The time-distributed video pipeline (reference CnnToRnnPreProcessor /
    RnnToCnnPreProcessor seam): recurrent frames -> RnnToCnn (T folds into
    batch) -> conv per frame -> CnnToRnn (restore [B,T,F]) -> LSTM ->
    global pool -> out. Explicit preprocessors, full chain gradient-checked."""
    from deeplearning4j_tpu.nn.preprocessors import (CnnToRnnPreProcessor,
                                                     RnnToCnnPreProcessor)
    B, T, H, W = 4, 3, 4, 4
    g = (_builder()
         .add_inputs("frames")
         .add_layer("c", ConvolutionLayer(n_out=2, kernel_size=(2, 2),
                                          activation="sigmoid"), "frames",
                    preprocessor=RnnToCnnPreProcessor(H, W, 1))
         .add_layer("r", LSTM(n_out=4, activation="tanh"), "c",
                    preprocessor=CnnToRnnPreProcessor(3, 3, 2,
                                                      timestep_length=T))
         .add_layer("gp", GlobalPoolingLayer(pooling_type="avg"), "r")
         .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"), "gp")
         .set_outputs("out")
         .set_input_types(InputType.recurrent(H * W * 1, T)))
    net = ComputationGraph(g.build()).init()
    x = R.normal(size=(B, T, H * W))
    y = np.eye(2)[R.integers(0, 2, B)]
    assert np.asarray(net.output(x)).shape == (B, 2)
    assert check_gradients(net, x, y, print_results=True)


def test_implicit_cnn_to_rnn_is_a_clear_error():
    """Feeding conv activations straight into an RNN layer must fail at
    build time with a message naming the needed preprocessor — the time
    axis of an image is ambiguous (reference InputTypeUtil's CNN->RNN is
    the explicit video seam)."""
    g = (_builder()
         .add_inputs("img")
         .add_layer("c", ConvolutionLayer(n_out=2, kernel_size=(2, 2),
                                          activation="sigmoid"), "img")
         .add_layer("r", LSTM(n_out=4, activation="tanh"), "c")
         .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"), "r")
         .set_outputs("out")
         .set_input_types(InputType.convolutional(4, 4, 1)))
    with pytest.raises(ValueError, match="CnnToRnnPreProcessor"):
        g.build()


def test_rnn_to_cnn_style_pool_then_dense():
    """recurrent input -> GravesLSTM -> global max pool -> dense -> out
    (RnnToFf seam through pooling; reference RnnToFeedForwardPreProcessor
    workflows)."""
    T, V = 4, 3
    g = (_builder()
         .add_inputs("seq")
         .add_layer("l", GravesLSTM(n_out=4, activation="tanh"), "seq")
         .add_layer("gp", GlobalPoolingLayer(pooling_type="max"), "l")
         .add_layer("d", DenseLayer(n_out=5, activation="tanh"), "gp")
         .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"), "d")
         .set_outputs("out")
         .set_input_types(InputType.recurrent(V, T)))
    net = ComputationGraph(g.build()).init()
    x = R.normal(size=(4, T, V))
    y = np.eye(2)[R.integers(0, 2, 4)]
    assert check_gradients(net, x, y, print_results=True)


def test_merge_cnn_branch_with_ff_branch():
    """Two-input graph: a conv image branch merged with a plain FF branch
    (reference multi-input CG tests); both branches gradient-checked
    through the merge."""
    g = (_builder()
         .add_inputs("img", "feat")
         .add_layer("c", ConvolutionLayer(n_out=2, kernel_size=(2, 2),
                                          activation="sigmoid"), "img")
         .add_layer("p", SubsamplingLayer(pooling_type="max",
                                          kernel_size=(2, 2),
                                          stride=(2, 2)), "c")
         .add_layer("fcc", DenseLayer(n_out=6, activation="tanh"), "p")
         .add_layer("fcd", DenseLayer(n_out=6, activation="tanh"), "feat")
         .add_vertex("m", MergeVertex(), "fcc", "fcd")
         .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                       loss="mcxent"), "m")
         .set_outputs("out")
         .set_input_types(InputType.convolutional(4, 4, 1),
                          InputType.feed_forward(5)))
    net = ComputationGraph(g.build()).init()
    x_img = R.normal(size=(4, 4, 4, 1))
    x_feat = R.normal(size=(4, 5))
    y = np.eye(3)[R.integers(0, 3, 4)]
    assert np.asarray(net.output(x_img, x_feat)).shape == (4, 3)
    assert check_gradients(net, [x_img, x_feat], y, print_results=True)


@pytest.mark.slow
def test_elementwise_add_over_parallel_rnn_branches_timeseries_out():
    """Two LSTM branches element-wise added, RnnOutputLayer time-series
    loss — recurrent CG with a vertex combine (reference
    ComputationGraphTestRNN element-wise cases). Slow lane (ISSUE 19
    tier-1 budget reclaim): ElementWiseVertex gradients stay tier-1 in
    test_computation_graph.py and RNN-head CG gradients in
    test_two_outputs_ff_and_rnn_heads below."""
    T, V = 3, 3
    g = (_builder()
         .add_inputs("seq")
         .add_layer("a", LSTM(n_out=4, activation="tanh"), "seq")
         .add_layer("b", GravesLSTM(n_out=4, activation="tanh"), "seq")
         .add_vertex("add", ElementWiseVertex("add"), "a", "b")
         .add_layer("out", RnnOutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"), "add")
         .set_outputs("out")
         .set_input_types(InputType.recurrent(V, T)))
    net = ComputationGraph(g.build()).init()
    x = R.normal(size=(4, T, V))
    y = np.eye(2)[R.integers(0, 2, (4, T))]
    assert np.asarray(net.output(x)).shape == (4, T, 2)
    assert check_gradients(net, x, y, print_results=True)


def test_two_outputs_ff_and_rnn_heads():
    """One recurrent trunk, TWO heads: per-sequence FF head (via pooling)
    AND per-step RNN head — multi-output loss summation gradient-checked
    (reference CG multi-output + ComputationGraph.calcBackpropGradients
    multi-loss accumulation)."""
    T, V = 3, 3
    g = (_builder()
         .add_inputs("seq")
         .add_layer("trunk", LSTM(n_out=4, activation="tanh"), "seq")
         .add_layer("gp", GlobalPoolingLayer(pooling_type="avg"), "trunk")
         .add_layer("cls", OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"), "gp")
         .add_layer("tag", RnnOutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"), "trunk")
         .set_outputs("cls", "tag")
         .set_input_types(InputType.recurrent(V, T)))
    net = ComputationGraph(g.build()).init()
    x = R.normal(size=(4, T, V))
    y_cls = np.eye(2)[R.integers(0, 2, 4)]
    y_tag = np.eye(2)[R.integers(0, 2, (4, T))]
    outs = net.output(x)
    assert np.asarray(outs[0]).shape == (4, 2)
    assert np.asarray(outs[1]).shape == (4, T, 2)
    assert check_gradients(net, x, [y_cls, y_tag], print_results=True)
