"""Fused Pallas LSTM kernel parity tests (the XLA-vs-reference-path parity
discipline of the reference's cuDNN helper tests, CuDNNGradientChecks.java —
here: pallas fused path vs the lax.scan fallback, run in the pallas
interpreter on the CPU test platform)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.pallas_lstm import (fused_lstm,
                                                fused_lstm_applicable)

R = np.random.default_rng(42)


def _scan_ref(xp, h0, c0, Rm):
    H = h0.shape[-1]

    def step(carry, x):
        h_prev, c_prev = carry
        gates = x + h_prev @ Rm
        i = jax.nn.sigmoid(gates[:, :H])
        f = jax.nn.sigmoid(gates[:, H:2 * H])
        o = jax.nn.sigmoid(gates[:, 2 * H:3 * H])
        g = jnp.tanh(gates[:, 3 * H:])
        c = f * c_prev + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    (hT, cT), hs = jax.lax.scan(step, (h0, c0), xp)
    return hs, (hT, cT)


def _inputs(T=6, B=8, H=128):
    xp = jnp.asarray(R.normal(size=(T, B, 4 * H)).astype(np.float32) * 0.3)
    h0 = jnp.asarray(R.normal(size=(B, H)).astype(np.float32) * 0.1)
    c0 = jnp.asarray(R.normal(size=(B, H)).astype(np.float32) * 0.1)
    Rm = jnp.asarray(R.normal(size=(H, 4 * H)).astype(np.float32) * 0.1)
    return xp, h0, c0, Rm


def test_applicability_gate():
    f32 = jnp.float32
    ok = dict(peepholes=None, mask=None, reverse=False, activation="tanh",
              gate_activation="sigmoid")
    assert fused_lstm_applicable(8, 128, f32, **ok)
    assert not fused_lstm_applicable(8, 100, f32, **ok)        # H % 128
    assert not fused_lstm_applicable(7, 128, f32, **ok)        # B % 8
    assert not fused_lstm_applicable(8, 1024, f32, **ok)       # VMEM budget
    assert not fused_lstm_applicable(8, 128, jnp.bfloat16, **ok)
    assert fused_lstm_applicable(
        8, 128, f32, peepholes=(1, 2, 3), mask=None, reverse=False,
        activation="tanh", gate_activation="sigmoid")          # Graves: yes
    assert not fused_lstm_applicable(
        8, 128, f32, peepholes=None, mask=None, reverse=False,
        activation="relu", gate_activation="sigmoid")


def test_forward_matches_scan():
    xp, h0, c0, Rm = _inputs()
    hs1, (hT1, cT1) = fused_lstm(xp, h0, c0, Rm)
    hs2, (hT2, cT2) = _scan_ref(xp, h0, c0, Rm)
    np.testing.assert_allclose(np.asarray(hs1), np.asarray(hs2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(hT1), np.asarray(hT2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(cT1), np.asarray(cT2), atol=1e-6)


def test_backward_matches_scan_autodiff():
    """custom_vjp gradients (incl. final-state cotangents) vs jax.grad of the
    scan — every input gets a nontrivial cotangent."""
    xp, h0, c0, Rm = _inputs()
    w = jnp.asarray(R.normal(size=(6, 8, 128)).astype(np.float32))

    def loss(f):
        def lf(xp, h0, c0, Rm):
            hs, (hT, cT) = f(xp, h0, c0, Rm)
            return (jnp.sum(hs * w) + jnp.sum(jnp.tanh(hT) * 0.3)
                    + jnp.sum(cT * cT) * 0.1)
        return lf

    g1 = jax.grad(loss(fused_lstm), argnums=(0, 1, 2, 3))(xp, h0, c0, Rm)
    g2 = jax.grad(loss(_scan_ref), argnums=(0, 1, 2, 3))(xp, h0, c0, Rm)
    for name, a, b in zip(("dx_proj", "dh0", "dc0", "dR"), g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5,
                                   err_msg=name)


def test_layer_training_identical_with_and_without_fused(monkeypatch):
    """A whole MLN training step is bitwise-insensitive to which LSTM path
    ran (f32 tolerance): loss and updated params agree."""
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd

    def build():
        conf = (NeuralNetConfiguration(seed=7, updater=Sgd(0.1),
                                       dtype="float32")
                .list(LSTM(n_out=128, activation="tanh"),
                      RnnOutputLayer(n_out=5, activation="softmax",
                                     loss="mcxent"))
                .set_input_type(InputType.recurrent(5, 6)).build())
        return MultiLayerNetwork(conf).init()

    x = R.normal(size=(8, 6, 5)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[R.integers(0, 5, (8, 6))]

    results = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("DL4J_TPU_FUSED_LSTM", flag)
        net = build()
        s0 = net.score(x, y)
        net.fit(x, y, epochs=3, batch_size=8)
        results[flag] = (s0, net.score(x, y), np.asarray(net.params_flat()))
    assert np.isclose(results["1"][0], results["0"][0], atol=1e-5)
    assert np.isclose(results["1"][1], results["0"][1], atol=1e-5)
    np.testing.assert_allclose(results["1"][2], results["0"][2], atol=1e-4)
    assert results["1"][1] < results["1"][0]  # actually trained


def test_rnn_time_step_consistent_with_fused(monkeypatch):
    """apply_with_final_state (the tBPTT / streaming carry) returns the same
    final state on both paths."""
    from deeplearning4j_tpu.nn.layers import LSTM
    from deeplearning4j_tpu.nn.inputs import InputType

    layer = LSTM(n_in=5, n_out=128, activation="tanh")
    params, state = layer.init(jax.random.PRNGKey(0),
                               InputType.recurrent(5, 6), jnp.float32)
    x = jnp.asarray(R.normal(size=(8, 6, 5)).astype(np.float32))
    outs = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("DL4J_TPU_FUSED_LSTM", flag)
        hs, (hT, cT) = layer.apply_with_final_state(params, state, x)
        outs[flag] = (np.asarray(hs), np.asarray(hT), np.asarray(cT))
    for a, b in zip(outs["1"], outs["0"]):
        np.testing.assert_allclose(a, b, atol=1e-6)


def _scan_peep_ref(xp, h0, c0, Rm, pi, pf, po):
    H = h0.shape[-1]

    def step(carry, x):
        h_prev, c_prev = carry
        gates = x + h_prev @ Rm
        zi = gates[:, :H] + c_prev * pi
        zf = gates[:, H:2 * H] + c_prev * pf
        i = jax.nn.sigmoid(zi)
        f = jax.nn.sigmoid(zf)
        g = jnp.tanh(gates[:, 3 * H:])
        c = f * c_prev + i * g
        o = jax.nn.sigmoid(gates[:, 2 * H:3 * H] + c * po)
        h = o * jnp.tanh(c)
        return (h, c), h

    (hT, cT), hs = jax.lax.scan(step, (h0, c0), xp)
    return hs, (hT, cT)


def test_peephole_forward_and_backward_match_scan():
    """Graves (peephole) fused kernel parity vs the scan, fwd + all grads
    incl. dpi/dpf/dpo (reference LSTMHelpers peephole terms)."""
    from deeplearning4j_tpu.ops.pallas_lstm import fused_lstm_peephole
    xp, h0, c0, Rm = _inputs()
    pi = jnp.asarray(R.normal(size=(128,)).astype(np.float32) * 0.2)
    pf = jnp.asarray(R.normal(size=(128,)).astype(np.float32) * 0.2)
    po = jnp.asarray(R.normal(size=(128,)).astype(np.float32) * 0.2)

    hs1, (hT1, cT1) = fused_lstm_peephole(xp, h0, c0, Rm, pi, pf, po)
    hs2, (hT2, cT2) = _scan_peep_ref(xp, h0, c0, Rm, pi, pf, po)
    np.testing.assert_allclose(np.asarray(hs1), np.asarray(hs2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(cT1), np.asarray(cT2), atol=1e-6)

    w = jnp.asarray(R.normal(size=hs2.shape).astype(np.float32))

    def loss(f):
        def lf(*args):
            hs, (hT, cT) = f(*args)
            return (jnp.sum(hs * w) + jnp.sum(jnp.tanh(hT) * 0.3)
                    + jnp.sum(cT * cT) * 0.1)
        return lf

    argnums = tuple(range(7))
    g1 = jax.grad(loss(fused_lstm_peephole), argnums=argnums)(
        xp, h0, c0, Rm, pi, pf, po)
    g2 = jax.grad(loss(_scan_peep_ref), argnums=argnums)(
        xp, h0, c0, Rm, pi, pf, po)
    for name, a, b in zip(("dxp", "dh0", "dc0", "dR", "dpi", "dpf", "dpo"),
                          g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5,
                                   err_msg=name)


def test_graves_layer_training_identical_with_and_without_fused(monkeypatch):
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import GravesLSTM, RnnOutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd

    def build():
        conf = (NeuralNetConfiguration(seed=7, updater=Sgd(0.1),
                                       dtype="float32")
                .list(GravesLSTM(n_out=128, activation="tanh"),
                      RnnOutputLayer(n_out=5, activation="softmax",
                                     loss="mcxent"))
                .set_input_type(InputType.recurrent(5, 6)).build())
        return MultiLayerNetwork(conf).init()

    x = R.normal(size=(8, 6, 5)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[R.integers(0, 5, (8, 6))]
    results = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("DL4J_TPU_FUSED_LSTM", flag)
        net = build()
        net.fit(x, y, epochs=3, batch_size=8)
        results[flag] = (net.score(x, y), np.asarray(net.params_flat()))
    assert np.isclose(results["1"][0], results["0"][0], atol=1e-5)
    np.testing.assert_allclose(results["1"][1], results["0"][1], atol=1e-4)
