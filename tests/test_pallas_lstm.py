"""Fused Pallas LSTM kernel parity tests (the XLA-vs-reference-path parity
discipline of the reference's cuDNN helper tests, CuDNNGradientChecks.java —
here: pallas fused path vs the lax.scan fallback, run in the pallas
interpreter on the CPU test platform)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.pallas_lstm import (fused_lstm,
                                                fused_lstm_applicable)

R = np.random.default_rng(42)


def _scan_ref(xp, h0, c0, Rm):
    H = h0.shape[-1]

    def step(carry, x):
        h_prev, c_prev = carry
        gates = x + h_prev @ Rm
        i = jax.nn.sigmoid(gates[:, :H])
        f = jax.nn.sigmoid(gates[:, H:2 * H])
        o = jax.nn.sigmoid(gates[:, 2 * H:3 * H])
        g = jnp.tanh(gates[:, 3 * H:])
        c = f * c_prev + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    (hT, cT), hs = jax.lax.scan(step, (h0, c0), xp)
    return hs, (hT, cT)


def _inputs(T=6, B=8, H=128):
    xp = jnp.asarray(R.normal(size=(T, B, 4 * H)).astype(np.float32) * 0.3)
    h0 = jnp.asarray(R.normal(size=(B, H)).astype(np.float32) * 0.1)
    c0 = jnp.asarray(R.normal(size=(B, H)).astype(np.float32) * 0.1)
    Rm = jnp.asarray(R.normal(size=(H, 4 * H)).astype(np.float32) * 0.1)
    return xp, h0, c0, Rm


def test_applicability_gate():
    f32 = jnp.float32
    ok = dict(peepholes=None, mask=None, reverse=False, activation="tanh",
              gate_activation="sigmoid")
    assert fused_lstm_applicable(8, 128, f32, **ok)
    assert not fused_lstm_applicable(8, 100, f32, **ok)        # H % 128
    assert not fused_lstm_applicable(7, 128, f32, **ok)        # B % 8
    assert not fused_lstm_applicable(8, 1024, f32, **ok)       # VMEM budget
    assert not fused_lstm_applicable(8, 128, jnp.bfloat16, **ok)
    assert fused_lstm_applicable(
        8, 128, f32, peepholes=(1, 2, 3), mask=None, reverse=False,
        activation="tanh", gate_activation="sigmoid")          # Graves: yes
    assert not fused_lstm_applicable(
        8, 128, f32, peepholes=None, mask=None, reverse=False,
        activation="relu", gate_activation="sigmoid")


def test_forward_matches_scan():
    xp, h0, c0, Rm = _inputs()
    hs1, (hT1, cT1) = fused_lstm(xp, h0, c0, Rm)
    hs2, (hT2, cT2) = _scan_ref(xp, h0, c0, Rm)
    np.testing.assert_allclose(np.asarray(hs1), np.asarray(hs2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(hT1), np.asarray(hT2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(cT1), np.asarray(cT2), atol=1e-6)


def test_backward_matches_scan_autodiff():
    """custom_vjp gradients (incl. final-state cotangents) vs jax.grad of the
    scan — every input gets a nontrivial cotangent."""
    xp, h0, c0, Rm = _inputs()
    w = jnp.asarray(R.normal(size=(6, 8, 128)).astype(np.float32))

    def loss(f):
        def lf(xp, h0, c0, Rm):
            hs, (hT, cT) = f(xp, h0, c0, Rm)
            return (jnp.sum(hs * w) + jnp.sum(jnp.tanh(hT) * 0.3)
                    + jnp.sum(cT * cT) * 0.1)
        return lf

    g1 = jax.grad(loss(fused_lstm), argnums=(0, 1, 2, 3))(xp, h0, c0, Rm)
    g2 = jax.grad(loss(_scan_ref), argnums=(0, 1, 2, 3))(xp, h0, c0, Rm)
    for name, a, b in zip(("dx_proj", "dh0", "dc0", "dR"), g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5,
                                   err_msg=name)


def test_layer_training_identical_with_and_without_fused(monkeypatch):
    """A whole MLN training step is bitwise-insensitive to which LSTM path
    ran (f32 tolerance): loss and updated params agree."""
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd

    def build():
        conf = (NeuralNetConfiguration(seed=7, updater=Sgd(0.1),
                                       dtype="float32")
                .list(LSTM(n_out=128, activation="tanh"),
                      RnnOutputLayer(n_out=5, activation="softmax",
                                     loss="mcxent"))
                .set_input_type(InputType.recurrent(5, 6)).build())
        return MultiLayerNetwork(conf).init()

    x = R.normal(size=(8, 6, 5)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[R.integers(0, 5, (8, 6))]

    results = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("DL4J_TPU_FUSED_LSTM", flag)
        net = build()
        s0 = net.score(x, y)
        net.fit(x, y, epochs=3, batch_size=8)
        results[flag] = (s0, net.score(x, y), np.asarray(net.params_flat()))
    assert np.isclose(results["1"][0], results["0"][0], atol=1e-5)
    assert np.isclose(results["1"][1], results["0"][1], atol=1e-5)
    np.testing.assert_allclose(results["1"][2], results["0"][2], atol=1e-4)
    assert results["1"][1] < results["1"][0]  # actually trained


def test_rnn_time_step_consistent_with_fused(monkeypatch):
    """apply_with_final_state (the tBPTT / streaming carry) returns the same
    final state on both paths."""
    from deeplearning4j_tpu.nn.layers import LSTM
    from deeplearning4j_tpu.nn.inputs import InputType

    layer = LSTM(n_in=5, n_out=128, activation="tanh")
    params, state = layer.init(jax.random.PRNGKey(0),
                               InputType.recurrent(5, 6), jnp.float32)
    x = jnp.asarray(R.normal(size=(8, 6, 5)).astype(np.float32))
    outs = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("DL4J_TPU_FUSED_LSTM", flag)
        hs, (hT, cT) = layer.apply_with_final_state(params, state, x)
        outs[flag] = (np.asarray(hs), np.asarray(hT), np.asarray(cT))
    for a, b in zip(outs["1"], outs["0"]):
        np.testing.assert_allclose(a, b, atol=1e-6)


def _scan_peep_ref(xp, h0, c0, Rm, pi, pf, po):
    H = h0.shape[-1]

    def step(carry, x):
        h_prev, c_prev = carry
        gates = x + h_prev @ Rm
        zi = gates[:, :H] + c_prev * pi
        zf = gates[:, H:2 * H] + c_prev * pf
        i = jax.nn.sigmoid(zi)
        f = jax.nn.sigmoid(zf)
        g = jnp.tanh(gates[:, 3 * H:])
        c = f * c_prev + i * g
        o = jax.nn.sigmoid(gates[:, 2 * H:3 * H] + c * po)
        h = o * jnp.tanh(c)
        return (h, c), h

    (hT, cT), hs = jax.lax.scan(step, (h0, c0), xp)
    return hs, (hT, cT)


def test_peephole_forward_and_backward_match_scan():
    """Graves (peephole) fused kernel parity vs the scan, fwd + all grads
    incl. dpi/dpf/dpo (reference LSTMHelpers peephole terms)."""
    from deeplearning4j_tpu.ops.pallas_lstm import fused_lstm_peephole
    xp, h0, c0, Rm = _inputs()
    pi = jnp.asarray(R.normal(size=(128,)).astype(np.float32) * 0.2)
    pf = jnp.asarray(R.normal(size=(128,)).astype(np.float32) * 0.2)
    po = jnp.asarray(R.normal(size=(128,)).astype(np.float32) * 0.2)

    hs1, (hT1, cT1) = fused_lstm_peephole(xp, h0, c0, Rm, pi, pf, po)
    hs2, (hT2, cT2) = _scan_peep_ref(xp, h0, c0, Rm, pi, pf, po)
    np.testing.assert_allclose(np.asarray(hs1), np.asarray(hs2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(cT1), np.asarray(cT2), atol=1e-6)

    w = jnp.asarray(R.normal(size=hs2.shape).astype(np.float32))

    def loss(f):
        def lf(*args):
            hs, (hT, cT) = f(*args)
            return (jnp.sum(hs * w) + jnp.sum(jnp.tanh(hT) * 0.3)
                    + jnp.sum(cT * cT) * 0.1)
        return lf

    argnums = tuple(range(7))
    g1 = jax.grad(loss(fused_lstm_peephole), argnums=argnums)(
        xp, h0, c0, Rm, pi, pf, po)
    g2 = jax.grad(loss(_scan_peep_ref), argnums=argnums)(
        xp, h0, c0, Rm, pi, pf, po)
    for name, a, b in zip(("dxp", "dh0", "dc0", "dR", "dpi", "dpf", "dpo"),
                          g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5,
                                   err_msg=name)


def test_graves_layer_training_identical_with_and_without_fused(monkeypatch):
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import GravesLSTM, RnnOutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd

    def build():
        conf = (NeuralNetConfiguration(seed=7, updater=Sgd(0.1),
                                       dtype="float32")
                .list(GravesLSTM(n_out=128, activation="tanh"),
                      RnnOutputLayer(n_out=5, activation="softmax",
                                     loss="mcxent"))
                .set_input_type(InputType.recurrent(5, 6)).build())
        return MultiLayerNetwork(conf).init()

    x = R.normal(size=(8, 6, 5)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[R.integers(0, 5, (8, 6))]
    results = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("DL4J_TPU_FUSED_LSTM", flag)
        net = build()
        net.fit(x, y, epochs=3, batch_size=8)
        results[flag] = (net.score(x, y), np.asarray(net.params_flat()))
    assert np.isclose(results["1"][0], results["0"][0], atol=1e-5)
    np.testing.assert_allclose(results["1"][1], results["0"][1], atol=1e-4)


def test_masked_forward_and_backward_match_scan():
    """Masked fused path parity vs the masked scan (variable-length
    sequences; masked steps carry state unchanged), plain AND peephole."""
    from deeplearning4j_tpu.ops.pallas_lstm import (fused_lstm,
                                                    fused_lstm_peephole)
    T, B, H = 6, 8, 128
    xp, h0, c0, Rm = _inputs(T, B, H)
    lens = R.integers(2, T + 1, B)
    mask = jnp.asarray((np.arange(T)[None, :] < lens[:, None])
                       .astype(np.float32).T)          # [T, B]
    pi = jnp.asarray(R.normal(size=(H,)).astype(np.float32) * 0.2)
    pf = jnp.asarray(R.normal(size=(H,)).astype(np.float32) * 0.2)
    po = jnp.asarray(R.normal(size=(H,)).astype(np.float32) * 0.2)

    def scan_masked(xp, h0, c0, Rm, peep=None):
        def step(carry, inp):
            h_prev, c_prev = carry
            x, m = inp
            m = m[:, None]
            gates = x + h_prev @ Rm
            zi, zf = gates[:, :H], gates[:, H:2 * H]
            zo, zg = gates[:, 2 * H:3 * H], gates[:, 3 * H:]
            if peep is not None:
                zi = zi + c_prev * peep[0]
                zf = zf + c_prev * peep[1]
            i = jax.nn.sigmoid(zi)
            f = jax.nn.sigmoid(zf)
            g = jnp.tanh(zg)
            c = f * c_prev + i * g
            if peep is not None:
                zo = zo + c * peep[2]
            o = jax.nn.sigmoid(zo)
            h = o * jnp.tanh(c)
            h = m * h + (1 - m) * h_prev
            c = m * c + (1 - m) * c_prev
            return (h, c), h
        (hT, cT), hs = jax.lax.scan(step, (h0, c0), (xp, mask))
        return hs, (hT, cT)

    for label, fused_fn, scan_fn, args in [
            ("plain", lambda *a: fused_lstm(*a, mask=mask),
             lambda *a: scan_masked(*a), (xp, h0, c0, Rm)),
            ("peep", lambda *a: fused_lstm_peephole(*a, mask=mask),
             lambda xp, h0, c0, Rm, pi, pf, po: scan_masked(
                 xp, h0, c0, Rm, (pi, pf, po)),
             (xp, h0, c0, Rm, pi, pf, po))]:
        hs1, (hT1, cT1) = fused_fn(*args)
        hs2, (hT2, cT2) = scan_fn(*args)
        np.testing.assert_allclose(np.asarray(hs1), np.asarray(hs2),
                                   atol=1e-6, err_msg=label)
        np.testing.assert_allclose(np.asarray(cT1), np.asarray(cT2),
                                   atol=1e-6, err_msg=label)
        w = jnp.asarray(R.normal(size=hs2.shape).astype(np.float32))

        def loss(f):
            def lf(*a):
                hs, (hT, cT) = f(*a)
                return (jnp.sum(hs * w) + jnp.sum(jnp.tanh(hT))
                        + jnp.sum(cT * cT) * 0.1)
            return lf
        an = tuple(range(len(args)))
        g1 = jax.grad(loss(fused_fn), argnums=an)(*args)
        g2 = jax.grad(loss(scan_fn), argnums=an)(*args)
        for k, (a, b) in enumerate(zip(g1, g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5, err_msg=f"{label} arg{k}")


def test_masked_layer_training_identical_with_and_without_fused(monkeypatch):
    """Whole-net masked training parity between fused and scan paths."""
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator

    def build():
        conf = (NeuralNetConfiguration(seed=7, updater=Sgd(0.1),
                                       dtype="float32")
                .list(LSTM(n_out=128, activation="tanh"),
                      RnnOutputLayer(n_out=5, activation="softmax",
                                     loss="mcxent"))
                .set_input_type(InputType.recurrent(5, 6)).build())
        return MultiLayerNetwork(conf).init()

    x = R.normal(size=(8, 6, 5)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[R.integers(0, 5, (8, 6))]
    lens = R.integers(2, 7, 8)
    m = (np.arange(6)[None, :] < lens[:, None]).astype(np.float32)
    it = ListDataSetIterator([DataSet(x, y, m, m)], batch_size=8)
    results = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("DL4J_TPU_FUSED_LSTM", flag)
        net = build()
        net.fit(iterator=it, epochs=3)
        results[flag] = np.asarray(net.params_flat())
    np.testing.assert_allclose(results["1"], results["0"], atol=1e-4)


def test_bidirectional_layer_fused_matches_scan(monkeypatch):
    """GravesBidirectionalLSTM (fwd + reverse halves) fused-vs-scan parity:
    the reverse direction runs fused via flip(inputs) -> forward kernel ->
    flip(outputs). Covers masked and unmasked."""
    from deeplearning4j_tpu.nn.layers import GravesBidirectionalLSTM
    from deeplearning4j_tpu.nn.inputs import InputType

    layer = GravesBidirectionalLSTM(n_in=5, n_out=128, activation="tanh")
    params, state = layer.init(jax.random.PRNGKey(3),
                               InputType.recurrent(5, 6), jnp.float32)
    x = jnp.asarray(R.normal(size=(8, 6, 5)).astype(np.float32))
    lens = R.integers(2, 7, 8)
    m = jnp.asarray((np.arange(6)[None, :] < lens[:, None]).astype(np.float32))
    for mask in (None, m):
        outs = {}
        for flag in ("1", "0"):
            monkeypatch.setenv("DL4J_TPU_FUSED_LSTM", flag)
            out, _ = layer.apply(params, state, x, mask=mask)
            outs[flag] = np.asarray(out)
        np.testing.assert_allclose(outs["1"], outs["0"], atol=1e-5,
                                   err_msg=f"mask={'yes' if mask is not None else 'no'}")

    # grads too (the flipped reverse VJP)
    def loss(p, flag):
        import os
        os.environ["DL4J_TPU_FUSED_LSTM"] = flag
        out, _ = layer.apply(p, state, x, mask=m)
        return jnp.sum(out * out)
    g1 = jax.grad(lambda p: loss(p, "1"))(params)
    g0 = jax.grad(lambda p: loss(p, "0"))(params)
    import os; os.environ.pop("DL4J_TPU_FUSED_LSTM", None)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g0[k]),
                                   atol=3e-4, err_msg=k)


@pytest.mark.slow
def test_bf16_forward_and_backward_close_to_f32():
    """bf16 I/O fused path: compute stays f32 in-kernel (f32 scratch
    carries + f32 accumulators), so outputs/grads track the f32 kernel to
    bf16 rounding, not bf16-compounded error. Slow lane (ISSUE 19 tier-1
    budget reclaim, PR 18 precedent for bf16 closeness variants): the
    f32 fused==scan parity pins (test_bidirectional_layer_fused_matches_
    scan and the forward/backward parity tests above) stay tier-1."""
    from deeplearning4j_tpu.ops.pallas_lstm import (fused_lstm,
                                                    fused_lstm_applicable)
    assert fused_lstm_applicable(16, 128, jnp.bfloat16, peepholes=None,
                                 mask=None, reverse=False, activation="tanh",
                                 gate_activation="sigmoid")
    assert not fused_lstm_applicable(8, 128, jnp.bfloat16, peepholes=None,
                                     mask=None, reverse=False,
                                     activation="tanh",
                                     gate_activation="sigmoid")  # B%16
    T, B, H = 6, 16, 128
    xp, h0, c0, Rm = (jnp.asarray(R.normal(size=s).astype(np.float32) * sc)
                      for s, sc in [((T, B, 4 * H), 0.3), ((B, H), 0.1),
                                    ((B, H), 0.1), ((H, 4 * H), 0.1)])
    bf = jnp.bfloat16
    hs32, (hT32, cT32) = fused_lstm(xp, h0, c0, Rm)
    hs16, (hT16, cT16) = fused_lstm(xp.astype(bf), h0.astype(bf),
                                    c0.astype(bf), Rm.astype(bf))
    assert hs16.dtype == bf
    np.testing.assert_allclose(np.asarray(hs16, np.float32),
                               np.asarray(hs32), atol=0.05)

    def loss(f32_mode):
        def lf(xp_, R_):
            hs, (hT, cT) = fused_lstm(xp_, h0.astype(xp_.dtype),
                                      c0.astype(xp_.dtype), R_)
            return jnp.sum((hs.astype(jnp.float32)) ** 2)
        return lf
    g32 = jax.grad(loss(True), argnums=1)(xp, Rm)
    g16 = jax.grad(loss(False), argnums=1)(xp.astype(bf), Rm.astype(bf))
    assert g16.dtype == bf
    # relative agreement on the dominant gradient entries
    denom = np.maximum(np.abs(np.asarray(g32)), 1e-2)
    rel = np.abs(np.asarray(g16, np.float32) - np.asarray(g32)) / denom
    assert float(rel.mean()) < 0.05, float(rel.mean())


def test_bf16_layer_runs_fused(monkeypatch):
    """A bf16 LSTM net trains on the fused path and tracks the scan path."""
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd

    def build():
        conf = (NeuralNetConfiguration(seed=7, updater=Sgd(0.1),
                                       dtype="bfloat16")
                .list(LSTM(n_out=128, activation="tanh"),
                      RnnOutputLayer(n_out=5, activation="softmax",
                                     loss="mcxent"))
                .set_input_type(InputType.recurrent(5, 6)).build())
        return MultiLayerNetwork(conf).init()

    x = R.normal(size=(16, 6, 5)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[R.integers(0, 5, (16, 6))]
    scores = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("DL4J_TPU_FUSED_LSTM", flag)
        net = build()
        s0 = net.score(x, y)
        net.fit(x, y, epochs=3, batch_size=16)
        scores[flag] = (s0, net.score(x, y))
    assert scores["1"][1] < scores["1"][0]
    assert np.isclose(scores["1"][1], scores["0"][1], rtol=0.05)


def test_masked_bf16_matches_f32_masked():
    """The masked bf16 fused path (reachable in production: bf16 net +
    sequence masks) tracks the masked f32 kernel to bf16 rounding."""
    from deeplearning4j_tpu.ops.pallas_lstm import (fused_lstm,
                                                    fused_lstm_applicable)
    T, B, H = 6, 16, 128
    assert fused_lstm_applicable(B, H, jnp.bfloat16, peepholes=None,
                                 mask=object(), reverse=False,
                                 activation="tanh",
                                 gate_activation="sigmoid")
    xp, h0, c0, Rm = (jnp.asarray(R.normal(size=s).astype(np.float32) * sc)
                      for s, sc in [((T, B, 4 * H), 0.3), ((B, H), 0.1),
                                    ((B, H), 0.1), ((H, 4 * H), 0.1)])
    lens = R.integers(2, T + 1, B)
    mask = jnp.asarray((np.arange(T)[None, :] < lens[:, None])
                       .astype(np.float32).T)
    bf = jnp.bfloat16
    hs32, (hT32, cT32) = fused_lstm(xp, h0, c0, Rm, mask=mask)
    hs16, (hT16, cT16) = fused_lstm(xp.astype(bf), h0.astype(bf),
                                    c0.astype(bf), Rm.astype(bf),
                                    mask=mask.astype(bf))
    assert hs16.dtype == bf
    np.testing.assert_allclose(np.asarray(hs16, np.float32),
                               np.asarray(hs32), atol=0.05)
    # masked steps still carry the (bf16-rounded) previous state exactly
    g16 = jax.grad(lambda R_: jnp.sum(
        fused_lstm(xp.astype(bf), h0.astype(bf), c0.astype(bf), R_,
                   mask=mask.astype(bf))[0].astype(jnp.float32) ** 2))(
        Rm.astype(bf))
    assert g16.dtype == bf and np.isfinite(np.asarray(g16, np.float32)).all()
