"""Per-example prediction metadata + eval JSON serde.

Reference workflow: eval/meta/Prediction.java + Evaluation.java:297-361
(metadata-aware eval), :1490 (getPredictionErrors), :1567
(getPredictionByPredictedClass); BaseEvaluation JSON round-trip.
"""
import json

import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.eval import (Evaluation, Prediction, ROC, ROCBinary,
                                     ROCMultiClass)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam

R = np.random.default_rng(7)


def _probs(rows):
    p = np.asarray(rows, float)
    return p / p.sum(axis=1, keepdims=True)


def test_meta_confusion_and_getters():
    e = Evaluation()
    labels = np.eye(3)[[0, 0, 1, 2, 2]]
    preds = _probs([[.8, .1, .1],    # 0 -> 0 correct
                    [.1, .7, .2],    # 0 -> 1 WRONG (p=.7)
                    [.2, .6, .2],    # 1 -> 1 correct
                    [.9, .05, .05],  # 2 -> 0 WRONG (p=.9)
                    [.1, .2, .7]])   # 2 -> 2 correct
    meta = [f"rec{i}" for i in range(5)]
    e.eval(labels, preds, record_meta_data=meta)

    errors = e.get_prediction_errors()
    assert [(p.actual_class, p.predicted_class, p.record_meta_data)
            for p in errors] == [(0, 1, "rec1"), (2, 0, "rec3")]

    by_actual = e.get_predictions_by_actual_class(2)
    assert sorted(p.record_meta_data for p in by_actual) == ["rec3", "rec4"]
    by_pred = e.get_prediction_by_predicted_class(0)
    assert sorted(p.record_meta_data for p in by_pred) == ["rec0", "rec3"]
    cell = e.get_predictions(2, 0)
    assert [p.record_meta_data for p in cell] == ["rec3"]

    # worst-k: most-confidently-wrong first
    worst = e.get_worst_predictions(1)
    assert worst[0].record_meta_data == "rec3"
    assert worst[0].probability == pytest.approx(0.9)

    # without metadata the getters return None (reference contract)
    e2 = Evaluation()
    e2.eval(labels, preds)
    assert e2.get_prediction_errors() is None
    assert e2.get_predictions_by_actual_class(0) is None


def test_meta_with_mask_and_merge():
    e = Evaluation()
    labels = np.eye(2)[[0, 1, 1]]
    preds = _probs([[.9, .1], [.8, .2], [.3, .7]])
    mask = np.asarray([1, 1, 0])
    e.eval(labels, preds, mask=mask, record_meta_data=["a", "b", "c"])
    # masked-out example "c" is dropped everywhere
    assert e.count == 2
    assert [p.record_meta_data for p in e.get_prediction_errors()] == ["b"]

    other = Evaluation()
    other.eval(np.eye(2)[[0]], _probs([[.2, .8]]), record_meta_data=["d"])
    e.merge(other)
    assert sorted(p.record_meta_data for p in e.get_prediction_errors()) == \
        ["b", "d"]


def test_meta_timeseries_rejected():
    e = Evaluation()
    with pytest.raises(ValueError, match="per-example"):
        e.eval(np.zeros((2, 3, 4)), np.zeros((2, 3, 4)),
               record_meta_data=["a", "b"])


def test_fit_evaluate_worst_k_workflow():
    """The end-to-end debugging workflow: fit, evaluate(iterator) with
    metadata-carrying DataSets, pull the worst-k predictions."""
    n, d, c = 120, 6, 3
    x = R.normal(size=(n, d)).astype(np.float32)
    w = R.normal(size=(d, c))
    y_idx = np.argmax(x @ w + 0.3 * R.normal(size=(n, c)), axis=1)
    y = np.eye(c, dtype=np.float32)[y_idx]

    conf = (NeuralNetConfiguration(seed=1, updater=Adam(1e-2), dtype="float32")
            .list(DenseLayer(n_out=16, activation="relu"),
                  OutputLayer(n_out=c, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(d)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(x, y, epochs=30, batch_size=32)

    batches = [DataSet(x[i:i + 40], y[i:i + 40],
                       metadata=[{"row": j} for j in range(i, i + 40)])
               for i in range(0, n, 40)]
    e = net.evaluate(iter(batches))
    assert e.accuracy() > 0.5
    errors = e.get_prediction_errors()
    assert errors is not None
    n_err = int(e.confusion.sum() - np.trace(e.confusion))
    assert len(errors) == n_err
    worst = e.get_worst_predictions(5)
    assert len(worst) == min(5, n_err)
    # ranked descending by wrong-class confidence, metadata identifies rows
    probs = [p.probability for p in worst]
    assert probs == sorted(probs, reverse=True)
    for p in worst:
        assert 0 <= p.record_meta_data["row"] < n
        assert p.actual_class != p.predicted_class
        # the metadata points back at the actual example
        assert y_idx[p.record_meta_data["row"]] == p.actual_class


def test_evaluation_json_round_trip():
    e = Evaluation(top_n=2)
    labels = np.eye(3)[[0, 1, 2, 1]]
    preds = _probs([[.6, .3, .1], [.2, .5, .3], [.1, .2, .7], [.6, .3, .1]])
    e.eval(labels, preds, record_meta_data=[{"id": i} for i in range(4)])
    e2 = Evaluation.from_json(e.to_json())
    assert np.array_equal(e2.confusion, e.confusion)
    assert e2.count == e.count and e2.top_n == 2
    assert e2.accuracy() == e.accuracy()
    assert [(p.actual_class, p.predicted_class, p.record_meta_data)
            for p in e2.get_prediction_errors()] == \
        [(p.actual_class, p.predicted_class, p.record_meta_data)
         for p in e.get_prediction_errors()]
    # round-tripped object keeps accumulating
    e2.eval(labels, preds)
    assert e2.count == 8


def test_roc_json_round_trip():
    y = (R.random(200) > 0.5).astype(float)
    s = np.clip(y * 0.6 + R.random(200) * 0.5, 0, 1)
    r = ROC()
    r.eval(y, s)
    r2 = ROC.from_json(r.to_json())
    assert r2.calculate_auc() == pytest.approx(r.calculate_auc())
    assert r2.calculate_auprc() == pytest.approx(r.calculate_auprc())

    labels = np.stack([y, 1 - y], axis=1)
    scores = np.stack([s, 1 - s], axis=1)
    for cls in (ROCBinary, ROCMultiClass):
        m = cls()
        m.eval(labels, scores)
        m2 = cls.from_json(m.to_json())
        assert m2.calculate_average_auc() == \
            pytest.approx(m.calculate_average_auc())
    # type tag is checked
    with pytest.raises(ValueError, match="payload"):
        ROCBinary.from_json(r.to_json())


def test_prediction_repr():
    p = Prediction(1, 2, "rec9", 0.93)
    assert "actual=1" in repr(p) and "rec9" in repr(p)


def test_evaluate_with_metadata_on_timeseries_does_not_crash():
    """A metadata-carrying DataSet with [N,T,C] labels must still evaluate
    (records skipped — they're per-example), not raise."""
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer

    conf = (NeuralNetConfiguration(seed=1, updater=Adam(1e-2),
                                   dtype="float32")
            .list(LSTM(n_out=8, activation="tanh"),
                  RnnOutputLayer(n_out=3, activation="softmax",
                                 loss="mcxent"))
            .set_input_type(InputType.recurrent(3, 4)).build())
    net = MultiLayerNetwork(conf).init()
    x = R.normal(size=(5, 4, 3)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[R.integers(0, 3, (5, 4))]
    ds = DataSet(x, y, metadata=[f"seq{i}" for i in range(5)])
    e = net.evaluate(iter([ds]))
    assert e.count == 20                       # 5 sequences x 4 steps
    assert e.get_prediction_errors() is None   # no per-example records


def test_meta_mask_length_mismatch_raises():
    """Metadata shorter than the PRE-mask row count must raise, not be
    zip-truncated into misattributed records (advisor r4 finding)."""
    e = Evaluation()
    labels = np.eye(2)[[0, 1, 1]]
    preds = _probs([[.9, .1], [.8, .2], [.3, .7]])
    mask = np.asarray([1, 1, 0])
    with pytest.raises(ValueError, match="pre-mask"):
        e.eval(labels, preds, mask=mask, record_meta_data=["a", "b"])
