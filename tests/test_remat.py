"""Gradient checkpointing (rematerialization): flag-on outputs and gradients
must equal flag-off (jax.checkpoint trades FLOPs for HBM without changing
math)."""
import numpy as np

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.nn.graph.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                          GravesLSTM, OutputLayer,
                                          RnnOutputLayer)
from deeplearning4j_tpu.optimize.updaters import Sgd

R = np.random.default_rng(51)


def test_mln_remat_matches_plain():
    def build(remat):
        conf = (NeuralNetConfiguration(seed=5, updater=Sgd(0.1), dtype="float32",
                                       gradient_checkpointing=remat)
                .list(DenseLayer(n_in=6, n_out=16, activation="tanh"),
                      DenseLayer(n_out=16, activation="relu"),
                      OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    a, b = build(False), build(True)
    b.set_params_flat(a.params_flat())
    x = R.normal(size=(16, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[R.integers(0, 3, 16)]
    np.testing.assert_allclose(np.asarray(a.output(x)), np.asarray(b.output(x)),
                               atol=1e-6)
    a.fit(x, y, epochs=3, batch_size=16)
    b.fit(x, y, epochs=3, batch_size=16)
    np.testing.assert_allclose(np.asarray(a.params_flat()),
                               np.asarray(b.params_flat()), atol=1e-5)


def test_cg_remat_matches_plain():
    def build(remat):
        g = (NeuralNetConfiguration(seed=7, updater=Sgd(0.1), dtype="float32",
                                    gradient_checkpointing=remat)
             .graph_builder()
             .add_inputs("in")
             .add_layer("l1", GravesLSTM(n_out=8, activation="tanh"), "in")
             .add_layer("out", RnnOutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "l1")
             .set_outputs("out")
             .set_input_types(InputType.recurrent(3, 6)))
        return ComputationGraph(g.build()).init()

    a, b = build(False), build(True)
    b.set_params_flat(a.params_flat())
    x = R.normal(size=(4, 6, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[R.integers(0, 2, (4, 6))]
    a.fit(x, y, epochs=3, batch_size=4)
    b.fit(x, y, epochs=3, batch_size=4)
    np.testing.assert_allclose(np.asarray(a.params_flat()),
                               np.asarray(b.params_flat()), atol=1e-5)
