"""Copy-on-write prefix-cache sharing (ISSUE 14 tentpole, cache leg).

Pins:
  - bit-exactness: greedy decode through a CACHED prefix (block-aligned
    full match -> COW + one-step replay; partial match -> suffix replay)
    matches cache-free naive decode token-for-token, f32 AND bf16,
    including divergence on the first token after a shared prefix and COW
    under concurrent continuous-batched admission;
  - allocator hardening: freeing an unallocated block, double-freeing, or
    freeing a block with a live refcount raises; the scheduler's quiesce
    invariant (allocated == cached) catches leaks;
  - LRU eviction under pool pressure runs BEFORE BlockPoolExhaustedError;
  - cohort pinning: a hot-swap never serves old-params cached K/V to
    new-params admissions;
  - tracing: a cached-prefix request's timeline shows generation.prefix_hit
    and NO prefill span (the satellite's trace2timeline fixture).
"""
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.models.decode import (TransformerDecodeSpec,
                                              naive_generate)
from deeplearning4j_tpu.models.zoo_extra import transformer_lm
from deeplearning4j_tpu.serving import GenerationEngine
from deeplearning4j_tpu.serving.generation import BlockAllocator, PrefixCache
from deeplearning4j_tpu.serving.generation.prefix import _block_hashes

R = np.random.default_rng(1234)


def _lm(seed=7, vocab=53, d_model=32, n_heads=2, n_blocks=2, max_length=64,
        dtype="float32"):
    return transformer_lm(vocab_size=vocab, d_model=d_model,
                          n_heads=n_heads, n_blocks=n_blocks,
                          max_length=max_length, seed=seed, dtype=dtype,
                          token_input=True).init()


# ------------------------------------------------------- allocator hardening
def test_block_allocator_refcounts_and_hardening():
    a = BlockAllocator(6)                      # ids 1..5 usable
    got = a.alloc(3)
    assert a.allocated == frozenset(got)
    with pytest.raises(ValueError):
        a.free([got[0], got[0]])               # double free in one call
    # refcounted blocks refuse free until released
    a.incref(got[1])
    a.incref(got[1])
    with pytest.raises(ValueError):
        a.free([got[1]])
    assert a.decref(got[1]) == 1
    with pytest.raises(ValueError):
        a.free([got[1]])                       # still one ref
    assert a.decref(got[1]) == 0
    a.free([got[1]])
    # freeing an id this allocator never handed out
    free_id = next(b for b in range(1, 6) if b not in a.allocated)
    with pytest.raises(ValueError):
        a.free([free_id])
    with pytest.raises(ValueError):
        a.incref(free_id)                      # incref needs allocation
    with pytest.raises(ValueError):
        a.decref(got[2])                       # decref below zero
    with pytest.raises(ValueError):
        a.free([0])                            # trash block


def test_block_hash_chain_properties():
    p = np.arange(20, dtype=np.int32)
    h8 = _block_hashes(p, 8)
    assert len(h8) == 2                        # only FULL blocks hash
    assert _block_hashes(p[:7], 8) == []
    # chain property: same first block -> same h0; any earlier token
    # change reaches every later hash
    q = p.copy()
    q[3] = 99
    hq = _block_hashes(q, 8)
    assert hq[0] != h8[0] and hq[1] != h8[1]
    r = p.copy()
    r[12] = 99
    hr = _block_hashes(r, 8)
    assert hr[0] == h8[0] and hr[1] != h8[1]


def test_prefix_cache_unit_match_register_release_evict():
    a = BlockAllocator(12)
    pc = PrefixCache(a, 4)
    prompt = np.arange(12, dtype=np.int32)     # 3 full blocks
    blocks = a.alloc(4)                        # 3 prompt + 1 decode block
    managed = pc.register(prompt, np.array(blocks, np.int32), blocks)
    assert managed == blocks[:3]               # full blocks only
    assert all(a.refcount(b) == 1 for b in managed)
    assert pc.shared_blocks == 3 and pc.lru_blocks == 0
    # owner releases -> blocks park in LRU, still allocated
    pc.release(managed)
    assert pc.lru_blocks == 3
    assert a.refcount(managed[0]) == 0
    assert set(managed) <= set(a.allocated)
    # a shorter prompt with the same prefix matches 1 block and revives it
    shared, matched = pc.match(np.arange(6, dtype=np.int32))
    assert (shared, matched) == ([managed[0]], 4)
    assert pc.lru_blocks == 2 and a.refcount(managed[0]) == 1
    # evictable_for excludes blocks THIS prompt would revive
    assert pc.evictable_for(prompt) == 0       # both LRU blocks match
    assert pc.evictable_for(np.full(12, 7, np.int32)) == 2
    pc.release(shared)
    # eviction is oldest-first, children follow their parent: evicting the
    # chain head frees ALL three (descendants can't outlive the parent)
    freed0 = a.free_blocks
    n = pc.ensure_free(freed0 + 3)
    assert n == 3 and pc.cached_blocks == 0
    assert a.free_blocks == freed0 + 3
    assert pc.evictions == 3
    # the same prompt now misses
    assert pc.probe(prompt) == 0


# ------------------------------------------- shared engine + exactness pins
@pytest.fixture(scope="module")
def cache_lm():
    """One warmed f32 engine (block 8, slots 4, prefix cache ON by
    default) shared by the read-only pins below."""
    net = _lm()
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=64,
                           decode_slots=4, prefill_batches=(1, 2),
                           prompt_rungs=(64,))
    yield net, TransformerDecodeSpec(net), eng
    eng.stop()


def test_cached_prefix_bit_identical_f32(cache_lm):
    """THE pin: repeated prompts hit the cache (block-aligned -> COW +
    single-step replay; partial -> suffix replay) and stay token-for-token
    identical to cache-free naive decode."""
    net, spec, eng = cache_lm
    p16 = R.integers(1, 53, size=16).tolist()      # aligned: COW on repeat
    p13 = R.integers(1, 53, size=13).tolist()      # partial match on repeat
    want16 = naive_generate(net, p16, 10, pad_to=64, spec=spec)
    want13 = naive_generate(net, p13, 10, pad_to=64, spec=spec)
    m0 = eng.metrics()["lm"]["prefix"]
    for _ in range(3):
        assert eng.generate(p16, max_tokens=10)[0] == want16
        assert eng.generate(p13, max_tokens=10)[0] == want13
    m1 = eng.metrics()["lm"]["prefix"]
    assert m1["hits"] - m0["hits"] >= 4            # repeats all hit
    assert m1["cow_copies"] - m0["cow_copies"] >= 2
    assert m1["tokens_saved"] > m0["tokens_saved"]
    # cached TTFT is recorded for hit admissions
    assert m1["ttft_cached_ms"]["p50"] > 0


def test_divergent_continuation_after_shared_prefix(cache_lm):
    """Acceptance pin: two prompts sharing a block-aligned prefix but
    diverging right after it produce EXACTLY their own naive decodes —
    the shared blocks feed both, the divergent suffix replays privately."""
    net, spec, eng = cache_lm
    common = R.integers(1, 53, size=16).tolist()
    a = common + R.integers(1, 53, size=3).tolist()
    b = common + R.integers(1, 53, size=5).tolist()
    assert a[16:] != b[16:19]
    want_a = naive_generate(net, a, 8, pad_to=64, spec=spec)
    want_b = naive_generate(net, b, 8, pad_to=64, spec=spec)
    eng.generate(common, max_tokens=4)              # seed the cache
    got_a, _ = eng.generate(a, max_tokens=8)
    got_b, _ = eng.generate(b, max_tokens=8)
    assert got_a == want_a
    assert got_b == want_b


def test_cow_under_concurrent_admission(cache_lm):
    """Acceptance pin: block-aligned full-match admissions (each COWs the
    final shared block) landing WHILE other slots decode perturb nothing."""
    net, spec, eng = cache_lm
    p16 = R.integers(1, 53, size=16).tolist()
    p9 = R.integers(1, 53, size=9).tolist()
    want16 = naive_generate(net, p16, 8, pad_to=64, spec=spec)
    want9 = naive_generate(net, p9, 8, pad_to=64, spec=spec)
    eng.generate(p16, max_tokens=2)                 # cache both prefixes
    eng.generate(p9, max_tokens=2)
    cow0 = eng.metrics()["lm"]["prefix"]["cow_copies"]
    outs = {}

    def client(i):
        p, want = (p16, want16) if i % 2 == 0 else (p9, want9)
        st = eng.generate(p, max_tokens=8, stream=True)
        outs[i] = (list(st), want)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(8):
        got, want = outs[i]
        assert got == want, f"client {i} diverged under concurrent COW"
    assert eng.metrics()["lm"]["prefix"]["cow_copies"] - cow0 >= 4


def test_short_match_on_long_prompt_admits_as_miss(cache_lm):
    """Replay-budget guard: a cached match whose unmatched suffix exceeds
    ``prefix_max_replay`` (default 2 blocks) admits as a plain MISS —
    teacher-forcing a long suffix one token per decode dispatch would
    cost far more than the batched prefill it 'saves'. Output stays
    exact either way; the pin is that it took the prefill path."""
    net, spec, eng = cache_lm
    seed_p = R.integers(1, 53, size=8).tolist()        # caches one block
    eng.generate(seed_p, max_tokens=2)
    long_p = seed_p + R.integers(1, 53, size=32).tolist()   # suffix 32 > 16
    m0 = eng.metrics()["lm"]["prefix"]
    want = naive_generate(net, long_p, 6, pad_to=64, spec=spec)
    assert eng.generate(long_p, max_tokens=6)[0] == want
    m1 = eng.metrics()["lm"]["prefix"]
    assert m1["hits"] == m0["hits"], \
        "a 1-block match on a 40-token prompt must not replay 32 tokens"
    assert m1["misses"] == m0["misses"] + 1
    # within-budget suffix still hits: 8 shared + 8 new tokens (suffix 8)
    mid_p = seed_p + R.integers(1, 53, size=8).tolist()
    want = naive_generate(net, mid_p, 6, pad_to=64, spec=spec)
    assert eng.generate(mid_p, max_tokens=6)[0] == want
    assert eng.metrics()["lm"]["prefix"]["hits"] == m1["hits"] + 1


def test_quiesce_invariant_catches_leak(cache_lm):
    """The scheduler's quiesce assertion: allocated == cached when no
    requests are live; a leaked block (allocated outside any table or the
    cache) raises. Regression for silent pool leaks."""
    _, _, eng = cache_lm
    rt = eng._get("lm")
    # self-sufficient under any test order (reversed-order harness runs
    # this before the traffic-generating pins): ensure a cohort exists
    eng.generate([2, 4, 6], max_tokens=2)
    deadline = time.monotonic() + 10.0
    while rt.in_flight or rt.queue_depth:
        assert time.monotonic() < deadline
        time.sleep(0.005)
    rt._check_quiesce()                             # clean after traffic
    coh = rt._cohorts[-1]
    leak = coh.allocator.alloc(1)
    with pytest.raises(RuntimeError, match="leaked"):
        rt._check_quiesce()
    coh.allocator.free(leak)
    rt._check_quiesce()


@pytest.mark.slow   # bf16 variant; tier-1 keeps the f32 pin
# (test_cached_prefix_bit_identical_f32) and the core bf16 decode pin
# (test_generation.py::test_paged_greedy_bit_identical_dtypes_and_embeds)
def test_cached_prefix_bit_identical_bf16():
    """Same exactness pin in bf16 (COW + partial-match replay)."""
    net = _lm(seed=11, vocab=37, d_model=16, n_blocks=1, max_length=32,
              dtype="bfloat16")
    spec = TransformerDecodeSpec(net)
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=32,
                           decode_slots=2, prefill_batches=(1,),
                           prompt_rungs=(32,))
    try:
        p8 = R.integers(1, 37, size=8).tolist()
        p11 = R.integers(1, 37, size=11).tolist()
        want8 = naive_generate(net, p8, 8, pad_to=32, spec=spec)
        want11 = naive_generate(net, p11, 8, pad_to=32, spec=spec)
        for _ in range(2):
            assert eng.generate(p8, max_tokens=8)[0] == want8
            assert eng.generate(p11, max_tokens=8)[0] == want11
        snap = eng.metrics()["lm"]["prefix"]
        assert snap["hits"] >= 2 and snap["cow_copies"] >= 1
    finally:
        eng.stop()


def test_eviction_under_pool_pressure_before_exhaustion():
    """A pool too small for live blocks + cached LRU evicts refcount-0
    cached blocks instead of raising BlockPoolExhaustedError; the evicted
    prefix then misses again."""
    net = _lm(seed=41, vocab=29, d_model=16, n_blocks=1, max_length=32)
    spec = TransformerDecodeSpec(net)
    # 5 usable blocks; each 8-token prompt + 8 new = 2 blocks (+1 COW on
    # a repeat). Two distinct cached prompts fill 2 LRU blocks.
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=32,
                           decode_slots=1, prefill_batches=(1,),
                           prompt_rungs=(32,), num_blocks=6)
    try:
        pa = R.integers(1, 29, size=8).tolist()
        pb = R.integers(1, 29, size=8).tolist()
        pc_ = R.integers(1, 29, size=8).tolist()
        for p in (pa, pb):
            want = naive_generate(net, p, 8, pad_to=32, spec=spec)
            assert eng.generate(p, max_tokens=8)[0] == want
        m = eng.metrics()["lm"]["prefix"]
        assert m["cached_lru_blocks"] >= 2
        # a third distinct prompt needs 4 blocks (8+24 -> 4) with only 3
        # free: the LRU must yield a block instead of a 429
        want = naive_generate(net, pc_, 24, pad_to=32, spec=spec)
        assert eng.generate(pc_, max_tokens=24)[0] == want
        m = eng.metrics()["lm"]["prefix"]
        assert m["evictions"] >= 1
        rt = eng._get("lm")
        deadline = time.monotonic() + 10.0
        while rt.in_flight or rt.queue_depth:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        rt._check_quiesce()
    finally:
        eng.stop()


def test_prefix_cache_opt_out():
    net = _lm(seed=53, vocab=29, d_model=16, n_blocks=1, max_length=32)
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=32,
                           decode_slots=2, prefill_batches=(1,),
                           prompt_rungs=(32,), prefix_cache=False)
    try:
        assert eng.models()["lm"]["prefix_cache"] is False
        p = R.integers(1, 29, size=8).tolist()
        a, _ = eng.generate(p, max_tokens=4)
        b, _ = eng.generate(p, max_tokens=4)
        assert a == b
        snap = eng.metrics()["lm"]["prefix"]
        assert snap["hits"] == 0 and snap["misses"] == 0
    finally:
        eng.stop()


def test_hot_swap_does_not_share_prefix_across_cohorts():
    """Cohort pinning: cached blocks hold OLD-params K/V; after hot_swap
    the same prompt must MISS in the new cohort and produce new-params
    tokens (a cross-cohort hit would emit a params mixture)."""
    net_a = _lm(seed=7)
    net_b = _lm(seed=8)
    spec_a, spec_b = TransformerDecodeSpec(net_a), TransformerDecodeSpec(net_b)
    p = R.integers(1, 53, size=16).tolist()
    want_a = naive_generate(net_a, p, 8, pad_to=64, spec=spec_a)
    want_b = naive_generate(net_b, p, 8, pad_to=64, spec=spec_b)
    assert want_a != want_b
    eng = GenerationEngine(net_a, model_name="lm", block_len=8,
                           max_seq_len=64, decode_slots=2,
                           prefill_batches=(1,), prompt_rungs=(64,))
    try:
        assert eng.generate(p, max_tokens=8)[0] == want_a    # cached (old)
        assert eng.generate(p, max_tokens=8)[0] == want_a    # hit (old)
        hits_before = eng.metrics()["lm"]["prefix"]["hits"]
        assert hits_before >= 1
        eng.hot_swap("lm", net_b)
        assert eng.generate(p, max_tokens=8)[0] == want_b, \
            "post-swap admission must not reuse old-cohort cached K/V"
    finally:
        eng.stop()


# ------------------------------------------------------------------ tracing
def test_prefix_hit_trace_timeline(tmp_path):
    """Satellite pin: a cached-prefix request's trace shows
    generation.prefix_hit stamped with the trace id and NO prefill span —
    trace2timeline reconstructs the request visibly skipping prefill."""
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.trace2summary import load_events
    from tools.trace2timeline import timeline
    from deeplearning4j_tpu.telemetry import get_registry
    from deeplearning4j_tpu.telemetry.tracecontext import (new_trace_context,
                                                           use_trace_context)
    net = _lm(seed=67, vocab=29, d_model=16, n_blocks=1, max_length=32)
    eng = GenerationEngine(net, model_name="lm", block_len=8, max_seq_len=32,
                           decode_slots=2, prefill_batches=(1,),
                           prompt_rungs=(32,))
    try:
        p = R.integers(1, 29, size=16).tolist()
        eng.generate(p, max_tokens=4)                   # seed (miss)
        ctx = new_trace_context()
        with use_trace_context(ctx):
            toks, _ = eng.generate(p, max_tokens=4)     # hit
        assert len(toks) == 4
        path = get_registry().write_trace_jsonl(
            str(tmp_path / "t.jsonl"), trace_id=ctx.trace_id)
        names = [json.loads(ln)["name"] for ln in open(path)]
        assert "generation.prefix_hit" in names
        assert "generation.prefill" not in names, \
            "a cached-prefix request must SKIP prefill"
        assert names.count("generation.decode_step") >= 4
        rows = timeline(load_events(path), ctx.trace_id)
        order = [r["name"] for r in rows]
        assert order.index("generation.submit") \
            < order.index("generation.prefix_hit") \
            < order.index("generation.decode_step") \
            < order.index("generation.finish")
        hit = next(r for r in rows if r["name"] == "generation.prefix_hit")
        assert "matched_tokens=16" in hit["detail"]
        assert "cow=1" in hit["detail"]
    finally:
        eng.stop()


# -------------------------------------------------------------------- bench
@pytest.mark.bench_smoke
def test_prefix_cache_bench_smoke():
    """Tier-1 guard for the generate_tokens_per_sec prefix sub-rows
    (ISSUE 14 acceptance): cached-prefix TTFT p50 <= 0.25x uncached on the
    paired best-of ratio, with full hit rate on the shared-prompt windows.
    Shared-CI CPU timings swing, so THREE consecutive failing attempts are
    required to fail (the adjacent hit/miss windows already share any
    co-tenant burst; retries cover burst EDGES landing between windows)."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    row = None
    for _ in range(3):
        row = bench._bench_prefix_cache(duration=0.8, repeats=2)
        assert row["prefix_hit_rate"] >= 0.9
        assert row["prefix_cow_copies"] >= 1
        assert row["ttft_cached_p50_ms"] > 0
        if row["ttft_cached_vs_uncached"] <= 0.25:
            return
    pytest.fail(f"cached TTFT not <= 0.25x uncached in 3 attempts: {row}")
