"""FleetRouter over in-process replicas: routing, failover, membership.

Two real ServingHTTPServer+GenerationEngine replicas (same seed -> same
weights) registered by URL — everything the router does above the
process layer is pinned here without spawning subprocesses: affinity
concentration vs round-robin spread, the DEAD_AFTER=3 mark-dead
discipline, pre-first-token failover idempotency (the replayed request's
tokens are EXACTLY the single-replica greedy sequence, with the
``fleet.retry`` trace marker), non-retryable error passthrough, and
drain-then-remove scale-in. The subprocess/chaos half lives in
tests/test_fleet_process.py.
"""
import socket

import pytest

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.models.decode import (TransformerDecodeSpec,
                                              naive_generate)
from deeplearning4j_tpu.models.zoo_extra import transformer_lm
from deeplearning4j_tpu.serving import GenerationEngine, ServingHTTPServer
from deeplearning4j_tpu.serving.fleet import (DEAD_AFTER, FleetHTTPError,
                                              FleetRouter,
                                              NoReadyReplicaError)
from deeplearning4j_tpu.telemetry import MetricsRegistry

PROMPT = list(range(1, 17))     # two full 8-token blocks


@pytest.fixture
def fresh_registry():
    reg = MetricsRegistry(enabled=True)
    prev = telemetry.set_registry(reg)
    try:
        yield reg
    finally:
        telemetry.set_registry(prev)


@pytest.fixture(scope="module")
def pair():
    """Two live replicas with identical weights + the reference net."""
    net = transformer_lm(vocab_size=29, d_model=16, n_heads=2, n_blocks=1,
                         max_length=32, seed=7, dtype="float32",
                         token_input=True).init()
    servers, engines, urls = [], [], []
    for _ in range(2):
        eng = GenerationEngine(net, model_name="lm", block_len=8,
                               max_seq_len=32, decode_slots=2,
                               prefill_batches=(1,), prompt_rungs=(32,))
        srv = ServingHTTPServer(generation=eng)
        urls.append(f"http://127.0.0.1:{srv.start()}")
        servers.append(srv)
        engines.append(eng)
    yield {"urls": urls, "net": net, "spec": TransformerDecodeSpec(net)}
    for srv, eng in zip(servers, engines):
        srv.stop()
        eng.stop(drain=False, timeout=5.0)


def _router(pair, policy="affinity", **kw):
    r = FleetRouter(policy=policy, **kw)
    for url in pair["urls"]:
        r.add_url(url)
    return r


def _dead_url():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"http://127.0.0.1:{port}"


# ---------------------------------------------------------------- routing
def test_block_len_adopted_from_replica_steering(pair):
    router = _router(pair)
    try:
        assert router.block_len == 8        # the engines', not the default
        assert router.ready_count() == 2
    finally:
        router.close()


def test_affinity_concentrates_repeated_prefixes(pair):
    router = _router(pair)
    try:
        hit = set()
        for _ in range(6):
            status, body = router.generate_blocking(
                {"prompt": PROMPT, "max_tokens": 4})
            assert status == 200 and body["reason"] == "length"
            hit.add(body["replica"])
        # the whole point: every repeat landed on the SAME cache
        assert len(hit) == 1
        router.poll_once()                  # refresh steering snapshots
        m = router.metrics()
        assert m["aggregate_prefix_hit_rate"] > 0.3
        assert m["affinity"]["entries"] >= 2
        assert m["requests"] == 6 and m["retries"] == 0
    finally:
        router.close()


def test_affinity_spreads_distinct_prefixes(pair):
    """Unseen prefixes rendezvous across the fleet — N replicas must be
    N caches, not N copies. Deterministic given ids + prompts."""
    router = _router(pair)
    try:
        firsts = {router.candidates([t, t + 1] * 8)[0][0]
                  for t in range(1, 13)}
        assert firsts == {"r0", "r1"}
    finally:
        router.close()


def test_round_robin_alternates(pair):
    router = _router(pair, policy="round_robin")
    try:
        seen = []
        for _ in range(4):
            status, body = router.generate_blocking(
                {"prompt": PROMPT, "max_tokens": 2})
            assert status == 200
            seen.append(body["replica"])
        assert set(seen) == {"r0", "r1"}
        assert seen[0] != seen[1] and seen[1] != seen[2]
    finally:
        router.close()


def test_least_loaded_orders_by_queue_and_in_flight(pair):
    router = _router(pair, policy="least_loaded")
    try:
        with router._lock:
            router._replicas["r0"].steering = {"queue_depth": 5,
                                               "in_flight": 2}
            router._replicas["r1"].steering = {"queue_depth": 0,
                                               "in_flight": 1}
        ids, reason = router.candidates(PROMPT)
        assert ids == ["r1", "r0"] and reason == "least_loaded"
    finally:
        router.close()


# ----------------------------------------------------------- mark-dead
def test_replica_dead_after_three_transport_failures(pair, fresh_registry):
    router = FleetRouter(policy="affinity", block_len=8)
    try:
        rid = router.add_url(_dead_url())   # poll #1 fails inside add_url
        router.affinity.record([b"h0", b"h1"], rid)
        assert router.replicas()[0]["state"] != "dead"
        router.poll_replica(rid)            # strike 2
        assert router.replicas()[0]["state"] != "dead"
        router.poll_replica(rid)            # strike 3 -> dead
        row = router.replicas()[0]
        assert row["state"] == "dead"
        assert row["consecutive_failures"] == DEAD_AFTER
        m = router.metrics()
        assert m["replica_deaths"] == 1
        # its cache died with it: affinity entries dropped
        assert rid not in m["affinity"]["entries_per_replica"]
        assert any(e["name"] == "fleet.replica_dead"
                   for e in fresh_registry.trace_events())
    finally:
        router.close()


@pytest.mark.bench_smoke
def test_dead_after_discipline_is_pinned():
    """bench.py's fleet chaos row and the router tests both assume the
    3-consecutive-failure mark-dead discipline — a change here must be a
    deliberate one."""
    assert DEAD_AFTER == 3


# ------------------------------------------------------------- failover
def test_pre_first_token_failover_is_idempotent(pair, fresh_registry):
    """Affinity points at a dead replica; the replay on the survivor must
    produce EXACTLY the single-replica greedy sequence — never a partial,
    spliced, or double-emitted stream — and must land the fleet.retry
    trace marker plus a retries count on the done line."""
    router = _router(pair)
    try:
        ghost = router.add_url(_dead_url(), replica_id="ghost")
        with router._lock:
            router._replicas[ghost].state = "ready"     # lie: looks alive
        chain_prompt = PROMPT
        from deeplearning4j_tpu.serving.fleet.affinity import prompt_chain
        router.affinity.record(prompt_chain(chain_prompt, 8), ghost)
        assert router.candidates(chain_prompt)[0][0] == ghost

        want = naive_generate(pair["net"], chain_prompt, 6, pad_to=32,
                              spec=pair["spec"])
        lines = list(router.stream_generate(
            {"prompt": chain_prompt, "max_tokens": 6}))
        toks = [l["token"] for l in lines if "token" in l]
        assert toks == want
        done = lines[-1]
        assert done["done"] and done["reason"] == "length"
        assert done["replica"] in ("r0", "r1")
        assert done["retries"] >= 1
        names = [e["name"] for e in fresh_registry.trace_events()]
        assert "fleet.retry" in names
        assert "fleet.route" in names
        assert router.metrics()["retries"] >= 1
    finally:
        router.close()


def test_blocking_failover_matches_naive(pair, fresh_registry):
    router = _router(pair)
    try:
        ghost = router.add_url(_dead_url(), replica_id="ghost")
        with router._lock:
            router._replicas[ghost].state = "ready"
        from deeplearning4j_tpu.serving.fleet.affinity import prompt_chain
        router.affinity.record(prompt_chain(PROMPT, 8), ghost)
        want = naive_generate(pair["net"], PROMPT, 5, pad_to=32,
                              spec=pair["spec"])
        status, body = router.generate_blocking(
            {"prompt": PROMPT, "max_tokens": 5})
        assert status == 200
        assert body["tokens"] == want
        assert body["retries"] >= 1
    finally:
        router.close()


def test_non_retryable_replica_error_passes_through(pair):
    router = _router(pair)
    try:
        with pytest.raises(FleetHTTPError) as ei:
            list(router.stream_generate({"prompt": PROMPT,
                                         "max_tokens": 2}, "nope"))
        assert ei.value.status == 404
        status, body = router.generate_blocking(
            {"prompt": PROMPT, "max_tokens": 2}, "nope")
        assert status == 404 and "error" in body
    finally:
        router.close()


def test_empty_fleet_rejects_cleanly():
    router = FleetRouter(policy="affinity", block_len=8)
    try:
        with pytest.raises(NoReadyReplicaError):
            list(router.stream_generate({"prompt": PROMPT,
                                         "max_tokens": 2}))
        status, body = router.generate_blocking({"prompt": PROMPT,
                                                 "max_tokens": 2})
        assert status == 503 and body["kind"] == "NoReadyReplica"
        status, _ = router.forward_json("GET", "/health")
        assert status == 503
        assert router.metrics()["rejected"] >= 2
    finally:
        router.close()


# --------------------------------------------------------------- scale-in
def test_drain_replica_removes_from_membership(pair):
    router = _router(pair)
    try:
        assert router.drain_replica("r0", timeout=5.0) is True
        assert [r["id"] for r in router.replicas()] == ["r1"]
        ids, _ = router.candidates(PROMPT)
        assert ids == ["r1"]
        status, body = router.generate_blocking(
            {"prompt": PROMPT, "max_tokens": 2})
        assert status == 200 and body["replica"] == "r1"
    finally:
        router.close()


def test_forward_json_reaches_a_replica(pair):
    router = _router(pair)
    try:
        status, body = router.forward_json("GET", "/health")
        assert status == 200
        assert "steering" in body
    finally:
        router.close()
