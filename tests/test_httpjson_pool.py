"""util/httpjson.py HTTPClient: keep-alive pooling regression surface.

The fleet router forwards every request through this client, so the pool
invariants are load-bearing serving behavior, not plumbing detail: a
sequential caller must ride ONE socket (the socket-reuse pin), a stale
pooled connection must cost one silent retry (never a caller-visible
error), a fresh-connection failure must propagate (it is real), and only
fully-read streams may return their connection to the pool.
"""
import http.server
import json
import socket
import threading

import pytest

from deeplearning4j_tpu.util.httpjson import HTTPClient


class _Handler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"       # keep-alive is the point

    def setup(self):
        super().setup()
        with self.server.lock:
            self.server.connections += 1
            self.server.sockets.append(self.connection)

    def _reply(self, obj, status=200):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):   # noqa: N802
        with self.server.lock:
            self.server.hits += 1
            hits = self.server.hits
        self._reply({"path": self.path, "hits": hits})

    def do_POST(self):  # noqa: N802
        n = int(self.headers.get("Content-Length", 0))
        self._reply({"echo": json.loads(self.rfile.read(n) or b"{}")})

    def log_message(self, *a):
        pass


def _serve(port=0):
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", port), _Handler)
    srv.connections = 0
    srv.hits = 0
    srv.lock = threading.Lock()
    srv.sockets = []
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


def _stop(srv):
    """Stop the listener AND force-close accepted keep-alive sockets —
    shutdown() alone leaves handler threads serving pooled connections."""
    srv.shutdown()
    srv.server_close()
    with srv.lock:
        socks = list(srv.sockets)
    for s in socks:
        try:
            s.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        s.close()


def test_sequential_requests_reuse_one_socket():
    srv, base = _serve()
    client = HTTPClient(max_per_host=4, timeout=5.0)
    try:
        for i in range(6):
            status, body = client.request_json("GET", base + f"/r{i}")
            assert status == 200 and body["path"] == f"/r{i}"
        stats = client.stats()
        # the pin: one TCP handshake for the whole sequence
        assert stats["connections_created"] == 1
        assert stats["reused"] == 5
        assert stats["pooled_idle"] == 1
        assert srv.connections == 1     # server agrees: one accept()
    finally:
        client.close()
        _stop(srv)


def test_stale_pooled_connection_retried_once():
    """Server restart invalidates the pooled socket; the next request
    must succeed on a silent fresh-connection retry."""
    srv, base = _serve()
    port = srv.server_address[1]
    client = HTTPClient(max_per_host=2, timeout=5.0)
    try:
        status, _ = client.request_json("GET", base + "/warm")
        assert status == 200
        assert client.stats()["pooled_idle"] == 1
        _stop(srv)                      # pooled socket is now stale
        srv, base = _serve(port)        # same port, new listener
        status, body = client.request_json("GET", base + "/after")
        assert status == 200 and body["path"] == "/after"
        # exactly one extra connection: the stale one was retried, the
        # failure never reached the caller
        assert client.stats()["connections_created"] == 2
    finally:
        client.close()
        _stop(srv)


def test_fresh_connection_failure_propagates():
    # grab a port nothing listens on
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    client = HTTPClient(timeout=2.0)
    try:
        with pytest.raises(OSError):
            client.request_json("GET", f"http://127.0.0.1:{port}/x")
    finally:
        client.close()


def test_stream_read_to_eof_returns_connection_to_pool():
    srv, base = _serve()
    client = HTTPClient(timeout=5.0)
    try:
        with client.stream("GET", base + "/s") as resp:
            assert resp.status == 200
            resp.read()                 # fully consumed
        assert client.stats()["pooled_idle"] == 1
        client.request_json("GET", base + "/again")
        assert client.stats()["connections_created"] == 1
    finally:
        client.close()
        _stop(srv)


def test_abandoned_stream_closes_socket():
    srv, base = _serve()
    client = HTTPClient(timeout=5.0)
    try:
        with client.stream("GET", base + "/s"):
            pass                        # body never read: suspect socket
        assert client.stats()["pooled_idle"] == 0
        client.request_json("GET", base + "/next")
        assert client.stats()["connections_created"] == 2
    finally:
        client.close()
        _stop(srv)
