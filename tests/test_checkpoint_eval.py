"""Stage-4 tests: model-zip round trip, ROC/regression metrics, early
stopping, transfer learning (SURVEY.md §7 stage 4; mirrors reference
regressiontest/, eval/, earlystopping/, transferlearning tests)."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingTrainer,
    InMemoryModelSaver, InvalidScoreIterationTerminationCondition,
    MaxEpochsTerminationCondition, ScoreImprovementEpochTerminationCondition)
from deeplearning4j_tpu.eval.regression import RegressionEvaluation
from deeplearning4j_tpu.eval.roc import ROC, ROCMultiClass
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.transfer import (FineTuneConfiguration,
                                            TransferLearning,
                                            TransferLearningHelper)
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd
from deeplearning4j_tpu.util.serialization import (restore_model,
                                                   restore_multilayer_network,
                                                   write_model)


def _toy_net(seed=3, updater=None):
    conf = (NeuralNetConfiguration(seed=seed, updater=updater or Adam(1e-2))
            .list(DenseLayer(n_in=4, n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _toy_data(n=64, seed=0):
    r = np.random.default_rng(seed)
    x = r.normal(size=(n, 4)).astype(np.float32)
    yi = (x.sum(-1) > 0).astype(int) + (x[:, 0] > 1).astype(int)
    return x, np.eye(3, dtype=np.float32)[yi]


def test_model_zip_round_trip(tmp_path):
    net = _toy_net()
    x, y = _toy_data()
    net.fit(x, y, epochs=3, batch_size=32)
    path = str(tmp_path / "model.zip")
    write_model(net, path)
    restored = restore_multilayer_network(path)
    assert np.allclose(np.asarray(net.output(x)), np.asarray(restored.output(x)),
                       atol=1e-6)
    # updater state restored: continued training matches exactly
    net.fit(x, y, epochs=1, batch_size=32)
    restored.fit(x, y, epochs=1, batch_size=32)
    assert np.allclose(np.asarray(net.params_flat()),
                       np.asarray(restored.params_flat()), atol=1e-6)


def test_restore_model_guesser(tmp_path):
    net = _toy_net()
    path = str(tmp_path / "m.zip")
    write_model(net, path)
    m = restore_model(path)
    assert isinstance(m, MultiLayerNetwork)
    # bare config json restores an (untrained) net
    jpath = str(tmp_path / "conf.json")
    with open(jpath, "w") as f:
        f.write(net.conf.to_json())
    m2 = restore_model(jpath)
    assert m2.num_params() == net.num_params()


def test_roc_auc():
    roc = ROC()
    labels = np.array([0, 0, 1, 1])
    scores = np.array([0.1, 0.4, 0.35, 0.8])
    roc.eval(labels, scores)
    assert roc.calculate_auc() == pytest.approx(0.75)
    # perfect separation
    roc2 = ROC()
    roc2.eval(np.array([0, 0, 1, 1]), np.array([0.1, 0.2, 0.8, 0.9]))
    assert roc2.calculate_auc() == pytest.approx(1.0)
    assert roc2.calculate_auprc() == pytest.approx(1.0)


def test_roc_multiclass():
    r = ROCMultiClass()
    labels = np.eye(3)[[0, 1, 2, 0, 1, 2]]
    preds = np.array([[0.8, 0.1, 0.1], [0.1, 0.8, 0.1], [0.1, 0.1, 0.8],
                      [0.6, 0.3, 0.1], [0.3, 0.6, 0.1], [0.2, 0.2, 0.6]])
    r.eval(labels, preds)
    assert r.calculate_average_auc() == pytest.approx(1.0)


def test_regression_evaluation():
    re = RegressionEvaluation(["a", "b"])
    y = np.array([[1.0, 2.0], [2.0, 4.0], [3.0, 6.0]])
    p = y + np.array([[0.1, -0.2], [-0.1, 0.2], [0.1, -0.2]])
    re.eval(y, p)
    assert re.mean_squared_error(0) == pytest.approx(0.01)
    assert re.mean_absolute_error(1) == pytest.approx(0.2)
    assert re.correlation_r2(0) > 0.99
    assert "RMSE" in re.stats()


def test_early_stopping_patience():
    x, y = _toy_data(128)
    train_it = ListDataSetIterator(features=x, labels=y, batch_size=32)
    val_it = ListDataSetIterator(features=x, labels=y, batch_size=64)
    net = _toy_net(updater=Adam(1e-2))
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(val_it),
        model_saver=InMemoryModelSaver(),
        epoch_termination_conditions=[
            MaxEpochsTerminationCondition(30),
            ScoreImprovementEpochTerminationCondition(3, 1e-5)],
        iteration_termination_conditions=[InvalidScoreIterationTerminationCondition()])
    result = EarlyStoppingTrainer(cfg, net, train_it).fit()
    assert result.total_epochs <= 30
    assert result.best_model is not None
    assert result.best_model_score <= min(result.score_vs_epoch.values()) + 1e-9


def test_transfer_learning_freeze_and_replace():
    x, y = _toy_data(96)
    net = _toy_net()
    net.fit(x, y, epochs=5, batch_size=32)
    frozen_w_before = np.asarray(net.params[0]["W"])

    new_net = (TransferLearning(net)
               .fine_tune_configuration(FineTuneConfiguration(updater=Sgd(0.05)))
               .set_feature_extractor(0)
               .n_out_replace(1, 3, weight_init="xavier")
               .build())
    assert new_net.layers[0].frozen
    # layer-0 weights carried over
    assert np.allclose(np.asarray(new_net.params[0]["W"]), frozen_w_before)
    new_net.fit(x, y, epochs=3, batch_size=32)
    # frozen layer unchanged by training, head did change
    assert np.allclose(np.asarray(new_net.params[0]["W"]), frozen_w_before)
    assert not np.allclose(np.asarray(new_net.params[1]["W"]),
                           np.asarray(net.params[1]["W"])[:, :3])


def test_transfer_learning_helper_featurize():
    net = _toy_net()
    new_net = TransferLearning(net).set_feature_extractor(0).build()
    helper = TransferLearningHelper(new_net)
    x, _ = _toy_data(16)
    feats = np.asarray(helper.featurize(x))
    assert feats.shape == (16, 8)
    tail = helper.unfrozen_network()
    out = np.asarray(tail.output(feats))
    assert np.allclose(out, np.asarray(new_net.output(x)), atol=1e-6)


def test_remove_and_add_layers():
    net = _toy_net()
    new_net = (TransferLearning(net)
               .remove_output_layer()
               .add_layer(DenseLayer(n_out=6, activation="relu"))
               .add_layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
               .build())
    assert len(new_net.layers) == 3
    x, _ = _toy_data(8)
    assert np.asarray(new_net.output(x)).shape == (8, 2)
