"""Performance observability (ISSUE 15): live cost-model accounting,
roofline/MFU gauges, memory profiler, and the perf-regression watchdog.

Pinned here:
- the shared cost model (normalization / implied MFU / roofline
  classification) that bench.py now delegates to;
- ProgramCostIndex capture for Solver step/window programs (one lower(),
  ZERO extra backend compiles), serving bucket programs and the fold
  into perf.<path>.mfu/.achieved_tflops/.roofline gauges;
- the acceptance contracts: zero host syncs + zero steady-state
  recompiles with FULL perf accounting enabled (K=1 and fused), and
  tools/perf_report.py MFU agreeing with bench.py's independently
  computed MFU for the same program;
- step-time decomposition histograms, memory profiler (+ the
  device_memory_gauges live-arrays CPU fallback regression), flight
  recorder perf/memory inclusion, PerfBaseline trajectory loading,
  ThroughputSLO breach/recovery, PerformanceListener mfu keys,
  dashboard Performance card (i18n'd), and the
  perf_accounting_overhead_pct bench guard.
"""
import json
import math

import numpy as np
import pytest

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.telemetry import (HostSyncDetector, MetricsRegistry,
                                          RecompileDetector, SLOWatchdog,
                                          ThroughputSLO, set_slo_watchdog)
from deeplearning4j_tpu.telemetry.perf import (PerfBaseline,
                                               ProgramCostIndex,
                                               classify_roofline,
                                               get_cost_index, implied_mfu,
                                               normalize_cost_analysis,
                                               roofline_dt, set_cost_index,
                                               write_perf_dump)


@pytest.fixture
def fresh_registry():
    reg = MetricsRegistry(enabled=True)
    prev = telemetry.set_registry(reg)
    try:
        yield reg
    finally:
        telemetry.set_registry(prev)


@pytest.fixture(autouse=True)
def fast_capture(monkeypatch):
    """Capture train-step program cost on the FIRST dispatch: the
    production default defers the capturing lower() until a program has
    run 256 steps (a full retrace is too expensive for short exploratory
    fits), but these tests run tiny fits on purpose. The threshold
    semantics themselves are pinned in
    test_capture_deferred_until_warmup_threshold."""
    monkeypatch.setenv("DL4J_TPU_PERF_CAPTURE_AFTER", "1")


@pytest.fixture
def fresh_index():
    idx = ProgramCostIndex()
    prev = set_cost_index(idx)
    try:
        yield idx
    finally:
        set_cost_index(prev)


@pytest.fixture
def recorder(fresh_registry, tmp_path):
    from deeplearning4j_tpu.telemetry import (FlightRecorder,
                                              set_flight_recorder)
    rec = FlightRecorder(directory=str(tmp_path / "fr"), min_interval_s=0.0)
    prev = set_flight_recorder(rec)
    try:
        yield rec
    finally:
        set_flight_recorder(prev)


def _tiny_net(seed=12, n_in=8, n_out=3):
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd
    conf = (NeuralNetConfiguration(seed=seed, updater=Sgd(0.1))
            .list(DenseLayer(n_in=n_in, n_out=16, activation="tanh"),
                  OutputLayer(n_out=n_out, activation="softmax",
                              loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _toy(n=32, n_in=8, n_out=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, n_in)).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, n)]
    return x, y


def _it(x, y, bs=4):
    from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
    return ListDataSetIterator(features=x, labels=y, batch_size=bs)


# ------------------------------------------------------- shared cost model
def test_normalize_cost_analysis_variants():
    assert normalize_cost_analysis({"flops": 5.0}) == {"flops": 5.0}
    assert normalize_cost_analysis([{"flops": 5.0}]) == {"flops": 5.0}
    assert normalize_cost_analysis([]) == {}
    assert normalize_cost_analysis(None) == {}
    assert normalize_cost_analysis(42) == {}


def test_bench_delegates_to_shared_cost_model():
    """Satellite: bench's helpers ARE the shared implementation (same
    numbers, one normalization) — bench rows and live gauges can never
    disagree."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    assert bench._cost_analysis(_FakeCompiled([{"flops": 7.0}])) == \
        {"flops": 7.0}
    # same formula, bench's module peak as the denominator
    assert bench._implied_mfu(1e12, 1.0) == pytest.approx(
        implied_mfu(1e12, 1.0, peak=bench.PEAK_TFLOPS))
    assert bench._roofline_dt(1e12) == pytest.approx(
        roofline_dt(1e12, peak=bench.PEAK_TFLOPS,
                    mfu_ceiling=bench.MAX_PLAUSIBLE_MFU))


class _FakeCompiled:
    def __init__(self, ca):
        self._ca = ca

    def cost_analysis(self):
        return self._ca


def test_classify_roofline_bounds(monkeypatch):
    monkeypatch.setenv("BENCH_PEAK_TFLOPS", "100.0")
    monkeypatch.setenv("BENCH_HBM_GBPS", "1000")
    # ridge = 100e12 / 1000e9 = 100 flops/byte
    lo = classify_roofline(flops=1e6, bytes_accessed=1e6)     # intensity 1
    hi = classify_roofline(flops=1e9, bytes_accessed=1e6)     # intensity 1000
    assert lo["bound"] == "memory" and hi["bound"] == "compute"
    assert lo["attainable_tflops"] == pytest.approx(1.0)      # bw-limited
    assert hi["attainable_tflops"] == pytest.approx(100.0)    # peak-capped
    assert classify_roofline(None, 1e6)["bound"] == "unknown"


# ------------------------------------------------------------- cost index
def test_cost_index_register_and_fold_math(fresh_registry, fresh_index):
    reg, idx = fresh_registry, fresh_index
    idx.register("prog", flops_per_step=2e9, bytes_per_step=1e6,
                 steps_per_call=4, timing_metric="t_ms")
    # 4 calls of 8ms each, 4 steps per call -> 2ms/step
    for _ in range(4):
        reg.histogram("t_ms").observe(8.0)
    rows = {r["path"]: r for r in idx.fold(reg)}
    r = rows["prog"]
    assert r["step_ms"] == pytest.approx(2.0)
    # 2e9 flops / 2ms = 1 TFLOP/s
    assert r["achieved_tflops"] == pytest.approx(1.0, rel=1e-6)
    assert r["mfu"] == pytest.approx(
        1.0 / float(__import__("os").environ.get("BENCH_PEAK_TFLOPS",
                                                 "197.0")), rel=1e-3)
    assert reg.gauge_if_exists("perf.prog.mfu") is not None
    assert reg.gauge_if_exists("perf.prog.step_ms").value == \
        pytest.approx(2.0)
    # delta folding: no new observations -> last row kept, not recomputed
    again = {r2["path"]: r2 for r2 in idx.fold(reg)}
    assert again["prog"]["step_ms"] == pytest.approx(2.0)
    # fresh observations at a new rate move the fold
    for _ in range(2):
        reg.histogram("t_ms").observe(16.0)
    moved = {r3["path"]: r3 for r3 in idx.fold(reg)}
    assert moved["prog"]["step_ms"] == pytest.approx(4.0)


def test_cost_index_cost_only_entry_and_failures(fresh_registry,
                                                 fresh_index):
    idx = fresh_index
    assert idx.register("nothing") is None          # no cost at all
    assert fresh_registry.counter("perf.cost_capture_failures").value == 1
    e = idx.register("pallas_prog", flops_per_step=5e9)   # analytic
    assert e.source == "analytic"
    row = [r for r in idx.fold(fresh_registry)
           if r["path"] == "pallas_prog"][0]
    assert row["mfu"] is None and row["flops_per_step"] == 5e9


# ------------------------------------------------ solver capture + gauges
def test_solver_fused_fit_captures_cost_and_folds(fresh_registry,
                                                  fresh_index):
    from deeplearning4j_tpu.optimize.listeners import PerformanceListener
    x, y = _toy(n=32)
    net = _tiny_net()
    perf_l = PerformanceListener(frequency=2)
    net.set_listeners(perf_l)
    net.fit(iterator=_it(x, y), epochs=2, steps_per_dispatch=4,
            async_prefetch=False)
    e = fresh_index.get("fit/epoch/window")
    assert e is not None and e.flops_per_step > 0
    assert e.steps_per_call == 4 and e.source == "lowered"
    snap = fresh_registry.snapshot()
    assert "perf.fit/epoch/window.mfu" in snap["gauges"]
    assert "perf.fit/epoch/window.roofline_compute_bound" in snap["gauges"]
    # step-time decomposition flushed at the epoch boundary
    for part in ("compute_ms", "input_wait_ms", "host_ms"):
        assert snap["histograms"][f"perf.step.{part}"]["count"] > 0
    # PerformanceListener satellite: mfu/achieved_tflops history keys
    # sourced from the cost index at window-aligned report points
    recs = [r for r in perf_l.history if "mfu" in r]
    assert recs, f"no mfu keys in history: {perf_l.history}"
    assert recs[-1]["achieved_tflops"] > 0
    assert 0 < recs[-1]["mfu"] < 1.0
    assert "train.windowed_steps_per_sec" in snap["gauges"]


def test_solver_per_step_fit_captures_cost(fresh_registry, fresh_index):
    x, y = _toy(n=16)
    net = _tiny_net()
    net.fit(iterator=_it(x, y), epochs=1, steps_per_dispatch=1,
            async_prefetch=False)
    e = fresh_index.get("fit/epoch/step")
    assert e is not None and e.flops_per_step > 0
    assert e.steps_per_call == 1


def test_capture_deferred_until_warmup_threshold(fresh_registry,
                                                 fresh_index, monkeypatch):
    """The capturing lower() is a full retrace (~0.1s for a toy net,
    seconds for a real one): a fit SHORTER than the warm-up threshold
    must never pay it, a fit that crosses the threshold captures once."""
    monkeypatch.setenv("DL4J_TPU_PERF_CAPTURE_AFTER", "32")
    x, y = _toy(n=32)
    net = _tiny_net()
    # 8 batches/epoch, 2 windows of K=4 -> 8 steps: below the threshold
    net.fit(iterator=_it(x, y), epochs=1, steps_per_dispatch=4,
            async_prefetch=False)
    assert fresh_index.get("fit/epoch/window") is None
    # 3 more epochs cross 32 cumulative steps -> exactly one capture
    net.fit(iterator=_it(x, y), epochs=3, steps_per_dispatch=4,
            async_prefetch=False)
    assert fresh_index.get("fit/epoch/window") is not None


def test_accounting_kill_switch(fresh_registry, fresh_index, monkeypatch):
    monkeypatch.setenv("DL4J_TPU_PERF_ACCOUNTING", "0")
    x, y = _toy(n=16)
    net = _tiny_net()
    net.fit(iterator=_it(x, y), epochs=1, steps_per_dispatch=2,
            async_prefetch=False)
    assert fresh_index.paths() == []
    assert fresh_registry.snapshot()["histograms"].get(
        "perf.step.compute_ms") is None


# ------------------------------------------------- acceptance: sync/compile
def test_accounting_zero_syncs_zero_recompiles(fresh_registry, fresh_index):
    """ISSUE 15 acceptance: the zero-host-sync and zero-steady-state-
    recompile pins hold with FULL perf accounting enabled — K=1 and
    fused. Cost capture is an abstract lower() (a trace, not a backend
    compile, not a device read), so the steady-state epoch stays clean
    under the tripwire, the detector AND the process compile counter."""
    from deeplearning4j_tpu.telemetry import xla_compile_count
    x, y = _toy(n=32)
    for k in (1, 4):
        net = _tiny_net(seed=100 + k)
        net.fit(iterator=_it(x, y), epochs=1, steps_per_dispatch=k,
                async_prefetch=False)        # warm epoch: compiles+capture
        before = xla_compile_count()
        with RecompileDetector(allowed=0, warn=False) as rd, \
                HostSyncDetector(action="count") as hs:
            net.fit(iterator=_it(x, y), epochs=1, steps_per_dispatch=k,
                    async_prefetch=False)
        assert rd.count == 0, f"K={k}: recompiled {rd.events}"
        assert hs.count == 0, \
            f"K={k}: syncs at {[e['span_path'] for e in hs.events]}"
        assert xla_compile_count() == before
        # the steady-state epoch still folded fresh gauges
        path = "fit/epoch/window" if k > 1 else "fit/epoch/step"
        assert fresh_index.get(path) is not None


# ------------------------------------------------------- serving capture
def test_serving_bucket_programs_registered(fresh_registry, fresh_index):
    from deeplearning4j_tpu.serving import InferenceEngine
    from deeplearning4j_tpu.telemetry import xla_compile_count
    net = _tiny_net(n_in=8)
    eng = InferenceEngine(net, feature_shape=(8,), buckets=(2, 4),
                          batch_window_ms=0.2)
    try:
        assert fresh_index.get("serving.default.bucket2") is not None
        assert fresh_index.get("serving.default.bucket4").items_per_step \
            == 4.0
        before = xla_compile_count()
        rng = np.random.default_rng(3)
        for _ in range(8):
            eng.predict(rng.normal(size=(2, 8)).astype(np.float32))
        assert xla_compile_count() == before      # accounting adds none
        rows = {r["path"]: r for r in fresh_index.fold(fresh_registry)}
        r2 = rows["serving.default.bucket2"]
        assert r2["source"] == "compiled" and r2["flops_per_step"] > 0
        assert r2["step_ms"] is not None          # dispatch_ms histogram
        assert fresh_registry.gauge_if_exists(
            "perf.serving.default.bucket2.mfu") is not None
    finally:
        eng.stop(drain=False)


# ------------------------------------------------------ memory profiler
def test_memprof_snapshot_groups_and_owner(fresh_registry):
    import jax.numpy as jnp
    from deeplearning4j_tpu.telemetry import memprof
    memprof.clear_tags()
    pool = jnp.zeros((7, 13, 5), jnp.float32)
    memprof.tag(pool, "test.pool")
    snap = memprof.snapshot(top_k=50)
    assert snap["total_live_bytes"] > 0 and snap["live_arrays"] > 0
    rows = {(tuple(r["shape"]), r["dtype"]): r for r in snap["top"]}
    r = rows[((7, 13, 5), "float32")]
    assert r["owner"] == "test.pool"
    assert r["total_bytes"] >= pool.nbytes
    assert snap["live_bytes_by_device"]          # CPU devices present
    gauges = memprof.publish_gauges(fresh_registry)
    assert gauges["memprof.live_bytes"] > 0
    del pool


def test_device_memory_gauges_cpu_fallback(fresh_registry):
    """Satellite regression: on backends without memory_stats (the CPU
    test platform) device_memory_gauges used to contribute NOTHING —
    now it falls back to live-array accounting, so tier-1 actually
    exercises the memory path."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.telemetry import device_memory_gauges
    keep = jnp.ones((64, 64), jnp.float32)
    out = device_memory_gauges(fresh_registry)
    assert out, "CPU fallback produced no gauges"
    assert any(k.endswith(".bytes_in_use") for k in out)
    g = fresh_registry.gauge_if_exists("device0.bytes_in_use")
    assert g is not None and g.value > 0
    assert fresh_registry.gauge_if_exists(
        "device0.live_arrays_fallback").value == 1.0
    del keep


def test_memprof_http_route(fresh_registry, fresh_index):
    import http.client
    from deeplearning4j_tpu.serving import InferenceEngine, ServingHTTPServer
    net = _tiny_net(n_in=8)
    eng = InferenceEngine(net, feature_shape=(8,), buckets=(2,),
                          batch_window_ms=0.2)
    srv = ServingHTTPServer(engine=eng)
    port = srv.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/debug/memprof",
                     body=json.dumps({"top_k": 5}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200
        assert body["live_arrays"] > 0 and len(body["top"]) <= 5
        # /metrics carries the perf block (cost table + memory)
        conn.request("GET", "/metrics")
        m = json.loads(conn.getresponse().read())
        assert "perf" in m and "programs" in m["perf"]
        assert any(r["path"].startswith("serving.default.bucket")
                   for r in m["perf"]["programs"])
        conn.close()
    finally:
        srv.stop()
        eng.stop(drain=False)


def test_flightrec_dump_includes_perf_and_memory(fresh_registry,
                                                 fresh_index, recorder):
    fresh_index.register("prog", flops_per_step=1e9, timing_metric="t_ms")
    fresh_registry.histogram("t_ms").observe(2.0)
    path = recorder.dump("perf_test")
    with open(path) as f:
        dump = json.load(f)
    assert dump["perf"]["programs"][0]["path"] == "prog"
    assert dump["perf"]["memory"]["live_arrays"] >= 0
    assert "step_decomposition" in dump["perf"]


# -------------------------------------------------------- PerfBaseline
def test_perf_baseline_loads_checked_in_trajectory():
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    b = PerfBaseline.load_trajectory(root)
    assert b.per_file, "no BENCH_r*.json parsed from the repo root"
    # r03 carries a full headline; scalar rows must be recoverable
    assert b.best("lstm_train_tokens_per_sec") > 0
    best, src = b.best_with_file("lstm_train_tokens_per_sec")
    assert src.startswith("BENCH_r")


def test_perf_baseline_tolerates_truncated_tail(tmp_path):
    full = {"metric": "m", "value": 1.0,
            "extras": {"transformer_lm_tokens_per_sec": 1000.0,
                       "serving_throughput": {"bucketed_req_per_sec": 50.0,
                                              "bucketed_p99_ms": 9.0}}}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"tail": json.dumps(full) + "\n", "parsed": None}))
    # tail truncated mid-value: the cut row is skipped, never guessed
    text = json.dumps(full)
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"tail": text[:text.find("1000.0") + 3], "parsed": None}))
    (tmp_path / "BENCH_r03.json").write_text("not json at all")
    b = PerfBaseline.load_trajectory(str(tmp_path))
    assert b.best("transformer_lm_tokens_per_sec") == 1000.0
    assert b.best("serving_throughput") == 50.0
    assert "BENCH_r02.json" not in b.per_file or \
        "transformer_lm_tokens_per_sec" not in \
        b.per_file.get("BENCH_r02.json", {})


# -------------------------------------------------------- ThroughputSLO
def test_throughput_slo_breach_and_recovery(fresh_registry, recorder):
    reg = fresh_registry
    slo = ThroughputSLO("train_tput", "train.windowed_steps_per_sec",
                        baseline=100.0, ratio_floor=0.5, target=0.5,
                        best_of=2)
    wd = SLOWatchdog([slo], windows=(60.0,), burn_limits=(1.0,),
                     min_coverage=0.0)
    # healthy: live best-of >= 50% of baseline
    reg.gauge("train.windowed_steps_per_sec").set(80.0)
    now = 1000.0
    for i in range(4):
        out = wd.check(now=now + i)
    assert not out["breached"]
    assert reg.gauge_if_exists(
        "slo.train_tput.throughput_ratio").value == pytest.approx(0.8)
    # regression: sustained 30% of baseline -> best-of window sinks, the
    # bad stream burns the budget, breach fires the flight recorder
    reg.gauge("train.windowed_steps_per_sec").set(30.0)
    dumps_before = len(recorder.dumps)
    for i in range(12):
        out = wd.check(now=now + 10 + i)
    assert "train_tput" in out["breached"]
    assert len(recorder.dumps) > dumps_before
    assert reg.counter("slo.breaches").value >= 1


def test_throughput_slo_cold_start_and_unknown_baseline(fresh_registry):
    reg = fresh_registry
    wd = SLOWatchdog([
        ThroughputSLO("cold", "never.set.gauge", baseline=100.0),
        ThroughputSLO("nobase", "some.gauge", baseline=0.0)],
        windows=(60.0,), min_coverage=0.0)
    reg.gauge("some.gauge").set(5.0)
    for i in range(6):
        out = wd.check(now=100.0 + i)
    # unset gauge contributes no samples; unknown baseline never breaches
    assert out["breached"] == []
    assert out["objectives"]["cold"]["good"] == 0
    assert out["objectives"]["nobase"]["good"] > 0


# ------------------------------------------------------- offline report
def _fit_and_dump(tmp_path, fresh_registry, fresh_index, k=4, epochs=2):
    x, y = _toy(n=32)
    net = _tiny_net()
    net.fit(iterator=_it(x, y), epochs=epochs, steps_per_dispatch=k,
            async_prefetch=False)
    path = str(tmp_path / "perf_dump.json")
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    write_perf_dump(path, registry=fresh_registry, index=fresh_index,
                    baseline_root=root)
    return net, path


def test_perf_report_renders_dump(fresh_registry, fresh_index, tmp_path,
                                  capsys):
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.perf_report import load_dump, main, roofline_rows
    _, path = _fit_and_dump(tmp_path, fresh_registry, fresh_index)
    assert main([path]) == 0
    out = capsys.readouterr().out
    assert "Roofline" in out and "fit/epoch/window" in out
    assert "Step-time decomposition" in out and "compute_ms" in out
    assert "Memory: live arrays" in out and "params" in out
    assert "Baseline deltas" in out and "BENCH_r" in out
    rows = roofline_rows(load_dump(path))
    r = [x for x in rows if x["path"] == "fit/epoch/window"][0]
    assert r["mfu"] is not None and not r["gauge_disagrees"]
    # --json mode round-trips
    assert main([path, "--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["roofline"] and data["memory"]


def test_perf_report_reads_flightrec_dump(fresh_registry, fresh_index,
                                          recorder, tmp_path, capsys):
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.perf_report import main
    x, y = _toy(n=16)
    net = _tiny_net()
    net.fit(iterator=_it(x, y), epochs=1, steps_per_dispatch=2,
            async_prefetch=False)
    path = recorder.dump("report_test")
    assert main([path]) == 0
    out = capsys.readouterr().out
    assert "flight-recorder dump" in out and "trigger=report_test" in out
    assert "fit/epoch/window" in out


def test_report_mfu_agrees_with_bench(fresh_registry, fresh_index,
                                      tmp_path):
    """ISSUE 15 acceptance: the report's per-program MFU for an
    instrumented fit agrees with bench.py's independently computed MFU
    for the SAME program (bench AOT-compiles the window step itself and
    runs its own _cost_analysis + _implied_mfu over the same step time).
    The live capture went through Lowered.cost_analysis(), bench goes
    through Compiled.cost_analysis() — agreement pins that the two
    paths (and the shared formula) cannot drift apart."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax
    import jax.numpy as jnp
    import bench
    from tools.perf_report import load_dump, roofline_rows
    net, path = _fit_and_dump(tmp_path, fresh_registry, fresh_index, k=4)
    row = [r for r in roofline_rows(load_dump(path))
           if r["path"] == "fit/epoch/window"][0]
    assert row["mfu"] is not None
    # bench's independent pass: AOT-compile the same K=4 window program
    # (fresh identical net -> same shapes/graph), pull flops through
    # bench._cost_analysis, apply bench._implied_mfu to the same step
    # time the report used
    net2 = _tiny_net()
    from deeplearning4j_tpu.optimize.solver import Solver
    s = Solver(net2)
    jitted = s._get_window_step(False, False, False)
    x, y = _toy(n=32)
    xs = jnp.asarray(x[:16]).reshape(4, 4, 8)
    ys = jnp.asarray(y[:16]).reshape(4, 4, 3)
    compiled = jitted.lower(net2.params, net2.state, net2.opt_state,
                            jnp.asarray(0, jnp.int32),
                            jax.random.PRNGKey(net2.conf.seed + 7919),
                            xs, ys).compile()
    flops = bench._cost_analysis(compiled).get("flops")
    assert flops and flops > 0
    bench_mfu = bench._implied_mfu(float(flops), row["step_ms"] / 1e3)
    assert row["mfu"] == pytest.approx(bench_mfu, rel=0.05), \
        f"report {row['mfu']} vs bench {bench_mfu} (flops {flops} vs " \
        f"captured {row['flops_per_step']})"


# ---------------------------------------------------------- dashboard
def test_dashboard_performance_card_i18n(fresh_registry, fresh_index):
    from deeplearning4j_tpu.ui import InMemoryStatsStorage
    from deeplearning4j_tpu.ui.dashboard import render_dashboard_html
    x, y = _toy(n=16)
    net = _tiny_net()
    net.fit(iterator=_it(x, y), epochs=1, steps_per_dispatch=2,
            async_prefetch=False)
    store = InMemoryStatsStorage()
    store.put_static_info("s", "w", {"a": 1})
    store.put_update("s", "w", {"iteration": 0, "score": 1.0})
    page = render_dashboard_html(store)
    assert "Performance (MFU / roofline / memory)" in page
    assert "fit/epoch/window" in page
    assert "compute_ms" in page
    # i18n'd heading in all six languages, like the existing cards
    from deeplearning4j_tpu.ui import i18n
    assert sorted(i18n.languages()) == ["de", "en", "ja", "ko", "ru", "zh"]
    for lang in i18n.languages():
        heading = i18n.get_message("train.performance", lang)
        assert heading and heading != "train.performance"
        assert heading in render_dashboard_html(store, lang=lang)
    # disabled telemetry: card omitted (old pages unchanged)
    fresh_registry.enabled = False
    try:
        assert "Performance (MFU" not in render_dashboard_html(store)
    finally:
        fresh_registry.enabled = True


# --------------------------------------------------------- bench guard
@pytest.mark.bench_smoke
def test_perf_accounting_overhead_bench_smoke():
    """Tier-1 guard for the perf_accounting_overhead_pct bench variant:
    full perf accounting (cost capture + decomposition + epoch fold)
    must cost <5% on the K=8 fused fit. Paired best-of ratio (adjacent
    on/off epochs share any co-tenant load burst); fails only if three
    consecutive measurements all exceed the bound."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    last = None
    for _ in range(3):
        row = bench.bench_telemetry_overhead(steps=128, repeats=4,
                                             variants=("perf",))
        assert row["perf_steps_per_sec"] > 0
        last = row
        if row["perf_accounting_overhead_pct"] < 5.0:
            return
    pytest.fail(
        f"perf accounting overhead >=5% in 3 consecutive runs: {last}")
