"""Elastic fault-tolerant training (parallel/elastic.py + util/async_checkpoint
+ parallel/faults.py).

The acceptance contract: training with an injected worker kill AND a
truncated newest checkpoint resumes from the last valid checkpoint on the
re-formed mesh and reaches the same result as an uninterrupted run —
bit-identical when the mesh shape is unchanged, within float tolerance
when the mesh shrank (the psum is the same reduction in a different
association order). Plus: async checkpointing adds zero blocking
device->host readbacks to the steady-state step loop (HostSyncDetector
tripwire, same harness as test_telemetry)."""
import os
import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.parallel import (CoordinationFlake, CorruptCheckpoint,
                                         ElasticTrainer, FaultInjector,
                                         FaultPlan, KillWorker,
                                         ParallelWrapper, PreemptAt,
                                         RecoveryFailedError, SlowCollective)
from deeplearning4j_tpu.parallel.faults import (corrupt_newest_sharded,
                                                truncate_newest_sharded)
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.telemetry import HostSyncDetector, get_registry
from deeplearning4j_tpu.util import async_checkpoint as ac
from deeplearning4j_tpu.util.distributed_checkpoint import (
    is_valid, latest_sharded_step, read_manifest,
    restore_latest_sharded_checkpoint, save_sharded_checkpoint)
from deeplearning4j_tpu.util.retry import RetryPolicy

R = np.random.default_rng(41)


def _net(seed=7):
    conf = (NeuralNetConfiguration(seed=seed, updater=Adam(1e-2),
                                   dtype="float32")
            .list(DenseLayer(n_in=6, n_out=16, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


_X = R.normal(size=(64, 6)).astype(np.float32)
_Y = np.eye(3, dtype=np.float32)[R.integers(0, 3, 64)]


def _it(bs=8):
    return ListDataSetIterator(features=_X, labels=_Y, batch_size=bs)


def _flat(net):
    return np.concatenate([np.asarray(l).ravel()
                           for l in jax.tree.leaves(net.params)])


def _devs(n=4):
    return jax.devices()[:n]


def _baseline(tmp_path, num_steps=20, **kw):
    a = _net()
    tr = ElasticTrainer(a, checkpoint_dir=str(tmp_path / "base"),
                        devices=_devs(), checkpoint_every_n_steps=4,
                        keep_last=4, **kw)
    tr.fit(_it(), num_steps=num_steps)
    return a, tr


# ------------------------------------------------------- async writer unit
def test_async_writer_writes_valid_checkpoints(tmp_path):
    mesh = make_mesh((4,), ("data",), _devs())
    rep = NamedSharding(mesh, P())
    tree = {"a": jax.device_put(jnp.arange(8.0), rep)}
    w = ac.AsyncCheckpointWriter(str(tmp_path), keep_last=2)
    try:
        w.submit(5, tree, extra={"step_in_epoch": 3})
        assert w.flush(timeout=30.0)
    finally:
        w.close()
    assert w.last_completed_step == 5
    assert latest_sharded_step(str(tmp_path)) == 5
    assert read_manifest(str(tmp_path), 5)["extra"] == {"step_in_epoch": 3}
    like = {"a": jax.device_put(jnp.zeros(8), rep)}
    step, got, extra = restore_latest_sharded_checkpoint(str(tmp_path), like)
    assert step == 5 and extra == {"step_in_epoch": 3}
    np.testing.assert_array_equal(np.asarray(got["a"]), np.arange(8.0))


def test_async_writer_latest_wins_coalescing(tmp_path, monkeypatch):
    """A slow write coalesces queued submits: only the newest pending
    snapshot is kept, drops are counted, step time never waits."""
    gate = threading.Event()
    written = []
    orig = ac.save_sharded_checkpoint

    def slow_save(directory, step, tree, extra=None):
        gate.wait(10.0)
        written.append(step)
        return orig(directory, step, tree, extra=extra)

    monkeypatch.setattr(ac, "save_sharded_checkpoint", slow_save)
    mesh = make_mesh((4,), ("data",), _devs())
    rep = NamedSharding(mesh, P())
    tree = {"a": jax.device_put(jnp.ones(4), rep)}
    reg = get_registry()
    before = reg.snapshot()["counters"].get("elastic.checkpoint.dropped", 0)
    w = ac.AsyncCheckpointWriter(str(tmp_path), keep_last=4)
    try:
        assert w.submit(1, tree)          # picked up by the (gated) writer
        time.sleep(0.05)
        assert w.submit(2, tree)          # pending slot
        assert not w.submit(3, tree)      # replaces pending 2
        gate.set()
        assert w.flush(timeout=30.0)
    finally:
        w.close()
    assert written == [1, 3]              # 2 was coalesced away
    after = reg.snapshot()["counters"].get("elastic.checkpoint.dropped", 0)
    assert after - before == 1


def test_async_writer_survives_write_errors(tmp_path, monkeypatch):
    calls = {"n": 0}
    orig = ac.save_sharded_checkpoint

    def flaky(directory, step, tree, extra=None):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("disk went away")
        return orig(directory, step, tree, extra=extra)

    monkeypatch.setattr(ac, "save_sharded_checkpoint", flaky)
    mesh = make_mesh((4,), ("data",), _devs())
    tree = {"a": jax.device_put(jnp.ones(4), NamedSharding(mesh, P()))}
    w = ac.AsyncCheckpointWriter(str(tmp_path))
    try:
        w.submit(1, tree)
        w.flush(timeout=30.0)
        assert isinstance(w.last_error, OSError)
        assert w.last_completed_step is None
        w.submit(2, tree)                  # the writer thread survived
        w.flush(timeout=30.0)
        assert w.last_completed_step == 2
    finally:
        w.close()


# ------------------------------------------------- sharded restore fallback
def _save_two(tmp_path):
    mesh = make_mesh((4,), ("data",), _devs())
    rep = NamedSharding(mesh, P())
    t1 = {"a": jax.device_put(jnp.full(6, 1.0), rep)}
    t2 = {"a": jax.device_put(jnp.full(6, 2.0), rep)}
    save_sharded_checkpoint(str(tmp_path), 1, t1)
    save_sharded_checkpoint(str(tmp_path), 2, t2)
    like = {"a": jax.device_put(jnp.zeros(6), rep)}
    return like


def test_restore_falls_back_past_truncated_newest(tmp_path):
    like = _save_two(tmp_path)
    assert truncate_newest_sharded(str(tmp_path)) == 2
    assert not is_valid(str(tmp_path), 2)
    assert is_valid(str(tmp_path), 1)
    assert latest_sharded_step(str(tmp_path)) == 1
    step, got, _ = restore_latest_sharded_checkpoint(str(tmp_path), like)
    assert step == 1
    np.testing.assert_array_equal(np.asarray(got["a"]), np.full(6, 1.0))


def test_restore_falls_back_past_corrupt_member(tmp_path):
    """Mid-file bit flips keep the zip directory intact (is_zipfile
    passes) — the CRC failure during the actual read must fall back."""
    like = _save_two(tmp_path)
    assert corrupt_newest_sharded(str(tmp_path)) == 2
    step, got, _ = restore_latest_sharded_checkpoint(str(tmp_path), like)
    assert step == 1
    np.testing.assert_array_equal(np.asarray(got["a"]), np.full(6, 1.0))


def test_restore_with_nothing_valid_returns_like(tmp_path):
    mesh = make_mesh((4,), ("data",), _devs())
    like = {"a": jax.device_put(jnp.zeros(3),
                                NamedSharding(mesh, P()))}
    step, got, extra = restore_latest_sharded_checkpoint(str(tmp_path), like)
    assert step is None and extra == {}
    assert got is like


# ------------------------------------------------------------ elastic loop
def test_elastic_no_fault_matches_parallel_wrapper(tmp_path):
    """Supervision (step callback + async checkpointing) must add
    NOTHING to the math: an unfaulted elastic run is bit-identical to a
    plain ParallelWrapper fit over the same steps."""
    a = _net()
    ParallelWrapper(a, mesh=make_mesh((4,), ("data",), _devs()),
                    prefetch_buffer=0).fit(_it(), epochs=3)   # 24 steps
    b = _net()
    tr = ElasticTrainer(b, checkpoint_dir=str(tmp_path),
                        devices=_devs(), checkpoint_every_n_steps=4)
    tr.fit(_it(), num_steps=24)
    assert tr.steps_done == 24 and tr.recoveries == 0
    np.testing.assert_array_equal(_flat(a), _flat(b))


def test_kill_plus_truncated_checkpoint_recovers_bit_identical(tmp_path):
    """THE acceptance scenario: worker kill at step 13 with the newest
    checkpoint truncated on disk. Recovery must skip the damaged save,
    restore the older valid one, re-form the mesh (rejoin -> same
    shape), replay, and land bit-identical to an uninterrupted run."""
    a, _ = _baseline(tmp_path)
    b = _net()
    inj = FaultInjector(FaultPlan(
        CorruptCheckpoint(step=13, mode="truncate"),
        KillWorker(step=13, worker=1, rejoin=True)))
    tr = ElasticTrainer(b, checkpoint_dir=str(tmp_path / "faulted"),
                        devices=_devs(), checkpoint_every_n_steps=4,
                        keep_last=4, fault_injector=inj)
    tr.fit(_it(), num_steps=20)
    assert tr.recoveries == 1
    assert tr.steps_done == 20
    assert get_registry().snapshot()["counters"].get(
        "elastic.recoveries", 0) >= 1
    np.testing.assert_array_equal(_flat(a), _flat(b))


def test_kill_without_rejoin_shrinks_mesh_and_converges(tmp_path):
    """A permanently lost worker re-forms a smaller mesh; the resumed run
    reaches the same result within float tolerance (different psum
    association order)."""
    a, _ = _baseline(tmp_path)
    b = _net()
    inj = FaultInjector(FaultPlan(KillWorker(step=11, worker=2,
                                             rejoin=False)))
    tr = ElasticTrainer(b, checkpoint_dir=str(tmp_path / "faulted"),
                        devices=_devs(), checkpoint_every_n_steps=4,
                        fault_injector=inj)
    tr.fit(_it(), num_steps=20)
    assert tr.recoveries == 1 and len(tr._devices) == 3
    assert tr.steps_done == 20
    np.testing.assert_allclose(_flat(a), _flat(b), rtol=1e-4, atol=1e-5)


def test_recovery_through_fused_windows_bit_identical(tmp_path):
    """steps_per_dispatch=2: the supervised loop runs K-fused windows;
    kill + recovery resumes mid-grid and must still be bit-identical to
    the unfaulted K=1 elastic run (the scan-window contract composes
    with recovery)."""
    a, _ = _baseline(tmp_path)
    b = _net()
    inj = FaultInjector(FaultPlan(KillWorker(step=14, worker=0,
                                             rejoin=True)))
    tr = ElasticTrainer(b, checkpoint_dir=str(tmp_path / "w"),
                        devices=_devs(), checkpoint_every_n_steps=4,
                        steps_per_dispatch=2, fault_injector=inj)
    tr.fit(_it(), num_steps=20)
    assert tr.recoveries == 1
    np.testing.assert_array_equal(_flat(a), _flat(b))


def test_no_checkpoint_yet_restarts_from_scratch(tmp_path):
    """Worker loss before the first checkpoint lands: recovery re-inits
    deterministically at step 0 and the full run still matches the
    baseline bit-for-bit."""
    a, _ = _baseline(tmp_path, num_steps=16)
    b = _net()
    inj = FaultInjector(FaultPlan(KillWorker(step=3, worker=1,
                                             rejoin=True)))
    tr = ElasticTrainer(b, checkpoint_dir=str(tmp_path / "scratch"),
                        devices=_devs(), checkpoint_every_n_steps=100,
                        fault_injector=inj)
    tr.fit(_it(), num_steps=16)
    assert tr.recoveries == 1
    np.testing.assert_array_equal(_flat(a), _flat(b))


def test_cross_process_resume_from_directory(tmp_path):
    """A FRESH trainer pointed at an existing checkpoint dir continues
    where the previous 'process' stopped — and matches the single-run
    baseline bit-for-bit (mid-epoch position from the manifest)."""
    a, _ = _baseline(tmp_path, num_steps=20)
    d = str(tmp_path / "resume")
    b = _net()
    ElasticTrainer(b, checkpoint_dir=d, devices=_devs(),
                   checkpoint_every_n_steps=4).fit(_it(), num_steps=10)
    c = _net()
    tr = ElasticTrainer(c, checkpoint_dir=d, devices=_devs(),
                        checkpoint_every_n_steps=4)
    tr.fit(_it(), num_steps=20)
    assert tr.steps_done == 20
    np.testing.assert_array_equal(_flat(a), _flat(c))


# ------------------------------------------------------------- coordination
def test_coordination_flakes_are_retried(tmp_path):
    a, _ = _baseline(tmp_path)
    b = _net()
    inj = FaultInjector(FaultPlan(
        KillWorker(step=13, worker=1, rejoin=True),
        CoordinationFlake(step=13, failures=2)))
    tr = ElasticTrainer(b, checkpoint_dir=str(tmp_path / "flaky"),
                        devices=_devs(), checkpoint_every_n_steps=4,
                        fault_injector=inj,
                        retry_policy=RetryPolicy(max_attempts=4,
                                                 base_delay_s=0.001,
                                                 sleep=lambda s: None))
    tr.fit(_it(), num_steps=20)
    assert tr.recoveries == 1
    assert inj.coordination_attempts == 3      # 2 flakes + 1 success
    np.testing.assert_array_equal(_flat(a), _flat(b))


def test_coordination_give_up_raises_recovery_failed(tmp_path):
    b = _net()
    inj = FaultInjector(FaultPlan(
        KillWorker(step=6, worker=1, rejoin=True),
        CoordinationFlake(step=6, failures=10)))
    tr = ElasticTrainer(b, checkpoint_dir=str(tmp_path),
                        devices=_devs(), checkpoint_every_n_steps=4,
                        fault_injector=inj,
                        retry_policy=RetryPolicy(max_attempts=3,
                                                 base_delay_s=0.001,
                                                 sleep=lambda s: None))
    with pytest.raises(RecoveryFailedError, match="gave up"):
        tr.fit(_it(), num_steps=20)


def test_max_recoveries_cap(tmp_path):
    b = _net()
    plan = FaultPlan(*[KillWorker(step=s, worker=0, rejoin=True)
                       for s in (3, 6, 9)])
    tr = ElasticTrainer(b, checkpoint_dir=str(tmp_path),
                        devices=_devs(), checkpoint_every_n_steps=2,
                        max_recoveries=2, fault_injector=FaultInjector(plan))
    with pytest.raises(RecoveryFailedError, match="max_recoveries"):
        tr.fit(_it(), num_steps=20)


# ------------------------------------------------------------ degraded mode
def test_degraded_mode_enters_and_exits(tmp_path):
    """Slow-collective latency above the budget flips the loop into
    SparkNet-style averaging windows (one collective per K steps) and
    flips back once the interconnect recovers."""
    b = _net()
    inj = FaultInjector(FaultPlan(
        SlowCollective(step=4, until_step=16, delay_ms=400.0)))
    tr = ElasticTrainer(b, checkpoint_dir=str(tmp_path),
                        devices=_devs(), checkpoint_every_n_steps=100,
                        sync_latency_budget_ms=50.0, latency_window=2,
                        degraded_averaging_window=4,
                        degraded_exit_patience=2, fault_injector=inj)
    tr.fit(_it(), num_steps=32)
    assert tr.steps_done >= 32
    assert tr.degraded_transitions == 2
    modes = [m for _, m in tr.mode_history]
    assert modes == ["averaging", "sync"]
    enter_step, exit_step = (s for s, _ in tr.mode_history)
    assert enter_step < 16 <= exit_step
    assert tr.mode == "sync"
    snap = get_registry().snapshot()
    assert snap["counters"].get("elastic.degraded_transitions", 0) >= 2
    assert np.isfinite(_flat(b)).all()


# -------------------------------------------------------------- preemption
def test_preemption_flushes_final_checkpoint_and_resumes(tmp_path):
    a, _ = _baseline(tmp_path, num_steps=20)
    d = str(tmp_path / "preempt")
    b = _net()
    inj = FaultInjector(FaultPlan(PreemptAt(step=9)))
    tr = ElasticTrainer(b, checkpoint_dir=d, devices=_devs(),
                        checkpoint_every_n_steps=4, fault_injector=inj)
    tr.fit(_it(), num_steps=20)
    assert tr.preempted
    assert tr.steps_done == 9
    # the final flush landed a checkpoint at EXACTLY the preempt step
    assert latest_sharded_step(d) == 9
    assert read_manifest(d, 9)["extra"]["step_in_epoch"] == 1
    # a fresh "process" resumes and matches the uninterrupted baseline
    c = _net()
    tr2 = ElasticTrainer(c, checkpoint_dir=d, devices=_devs(),
                         checkpoint_every_n_steps=4)
    tr2.fit(_it(), num_steps=20)
    assert not tr2.preempted
    np.testing.assert_array_equal(_flat(a), _flat(c))


def test_sigterm_guard_triggers_clean_preemption(tmp_path):
    """A real SIGTERM through PreemptionGuard takes the same clean path:
    flag set by the handler, final checkpoint flushed, fit returns."""

    class _SignalAt(FaultInjector):
        def __init__(self, at):
            super().__init__()
            self.at = at
            self.sent = False

        def on_step(self, step, trainer=None):
            if not self.sent and step >= self.at:
                self.sent = True
                signal.raise_signal(signal.SIGTERM)

    b = _net()
    d = str(tmp_path)
    inj = _SignalAt(at=6)
    tr = ElasticTrainer(b, checkpoint_dir=d, devices=_devs(),
                        checkpoint_every_n_steps=4, fault_injector=inj)
    with tr.preemption_guard() as guard:
        tr.fit(_it(), num_steps=20)
    assert guard.triggered and tr.preempted
    assert tr.steps_done == 6
    assert latest_sharded_step(d) == 6


# ------------------------------------------------- sync-freedom (acceptance)
def test_elastic_steady_state_adds_zero_host_syncs(tmp_path):
    """The tier-1 sync-freedom pin, extended to the elastic path: a
    steady-state supervised loop WITH async checkpointing active —
    including the initial restore and periodic submits — performs zero
    blocking device->host readbacks on the step-loop thread (the writer
    thread's materialization is the designed exception)."""
    b = _net()
    d = str(tmp_path)
    tr = ElasticTrainer(b, checkpoint_dir=d, devices=_devs(),
                        checkpoint_every_n_steps=4, final_checkpoint=False)
    # warm-up: compiles + first-touch caches may legitimately sync
    tr.fit(_it(), num_steps=8)
    with HostSyncDetector(action="count") as det:
        tr.fit(_it(), num_steps=24)       # restore -> steady loop -> submits
    assert tr.steps_done == 24
    assert det.count == 0, \
        f"syncs at {[e['span_path'] for e in det.events]}"
    # the async writer did run (checkpoints landed during the guarded fit)
    assert latest_sharded_step(d) >= 20


def test_same_process_continuation_before_first_full_pass(tmp_path):
    """Regression: a fit() stopping mid-epoch BEFORE any clean pass
    (epoch length still unknown) must record its position so a
    continuation fit() on the same trainer resumes there instead of
    replaying the epoch prefix."""
    a, _ = _baseline(tmp_path, num_steps=16)
    b = _net()
    tr = ElasticTrainer(b, checkpoint_dir=str(tmp_path / "cont"),
                        devices=_devs(), checkpoint_every_n_steps=4)
    tr.fit(_it(), num_steps=5)           # stops mid-epoch, L unknown
    assert tr.steps_done == 5
    tr.fit(_it(), num_steps=16)          # continuation, same trainer
    assert tr.steps_done == 16
    np.testing.assert_array_equal(_flat(a), _flat(b))


def test_non_resettable_exhausted_iterator_raises(tmp_path):
    """A generator that exhausts and can't reset must raise instead of
    spinning the supervised loop forever at zero progress."""
    b = _net()
    one_epoch = iter([d for d in _it()])     # no reset(): one pass only
    tr = ElasticTrainer(b, checkpoint_dir=str(tmp_path), devices=_devs(),
                        checkpoint_every_n_steps=4)
    with pytest.raises(ValueError, match="resettable"):
        tr.fit(one_epoch, num_steps=20)      # epoch has only 8 batches
    assert b.iteration_count == 8            # the one pass did train


def test_averaging_path_remainder_batch_fallback():
    """Regression (found by the chaos soak): the K-step averaging path
    used to die on the shard_map divisibility error when the batch size
    stopped tiling the mesh (exactly what happens when degraded mode
    runs on a recovery-shrunk mesh). Remainder batches now dispatch the
    replicated-feed averaging program."""
    net = _net()
    pw = ParallelWrapper(net, mesh=make_mesh((3,), ("data",), _devs(3)),
                         training_mode="averaging", averaging_frequency=4,
                         average_updaters=True, prefetch_buffer=0)
    pw.fit(_it(bs=8), epochs=1)           # 8 % 3 != 0 on every batch
    assert net.iteration_count == 8
    assert np.isfinite(_flat(net)).all()


# ------------------------------------------------------------- chaos (slow)
@pytest.mark.slow
def test_chaos_soak_random_fault_plan(tmp_path):
    """Seeded random weather: kills (mixed rejoin), checkpoint damage,
    slow-collective windows. N recoveries later the run completes every
    step with finite params."""
    rng = np.random.default_rng(1234)
    kills = sorted(rng.choice(np.arange(6, 120, 3), size=4, replace=False))
    faults = []
    for i, s in enumerate(kills):
        faults.append(KillWorker(step=int(s), worker=int(rng.integers(0, 4)),
                                 rejoin=bool(i % 2)))
        if i % 2:
            faults.append(CorruptCheckpoint(
                step=int(s), mode="truncate" if i % 4 else "flip"))
    faults.append(SlowCollective(step=40, until_step=70, delay_ms=300.0))
    inj = FaultInjector(FaultPlan(*faults))
    b = _net()
    tr = ElasticTrainer(b, checkpoint_dir=str(tmp_path), devices=_devs(),
                        checkpoint_every_n_steps=5, keep_last=4,
                        sync_latency_budget_ms=60.0, latency_window=2,
                        degraded_averaging_window=4, max_recoveries=16,
                        fault_injector=inj)
    tr.fit(_it(), num_steps=130)
    assert tr.steps_done >= 130
    assert tr.recoveries == 4
    assert np.isfinite(_flat(b)).all()
    out = b.output(_X[:8])
    assert np.isfinite(np.asarray(out)).all()


# ------------------------------------------------------------- bench smoke
@pytest.mark.bench_smoke
def test_elastic_recovery_bench_smoke():
    import bench
    row = bench.bench_elastic_recovery(steps=24, ckpt_every=4)
    assert row["value"] is not None and row["value"] > 0
    assert row["recoveries"] == 1
    assert row["steady_steps_per_sec_ckpt"] > 0
    assert row["steady_steps_per_sec_none"] > 0
    assert isinstance(row["ckpt_overhead_pct"], float)
