"""ComputationGraph recurrent capability: tBPTT training, rnn_time_step
streaming, seq2seq graphs, recurrent CG gradient checks with masking
(reference ComputationGraph.java rnnTimeStep :2301, tBPTT branch :908;
GradientCheckTestsComputationGraph + GradientCheckTestsMasking)."""
import numpy as np
import pytest

from deeplearning4j_tpu import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph.graph import ComputationGraph
from deeplearning4j_tpu.nn.graph.vertices import (DuplicateToTimeSeriesVertex,
                                                  LastTimeStepVertex)
from deeplearning4j_tpu.nn.layers import (DenseLayer, GravesLSTM, LSTM,
                                          OutputLayer, RnnOutputLayer)
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd
from deeplearning4j_tpu.util.gradcheck import check_gradients

R = np.random.default_rng(31)


def _seq2seq(tbptt=None, dtype="float32", updater=None, seed=5):
    """Encoder LSTM -> LastTimeStep -> DuplicateToTimeSeries -> decoder LSTM
    -> RnnOutput (the reference's canonical seq2seq CG shape)."""
    g = (NeuralNetConfiguration(seed=seed, updater=updater or Adam(5e-3),
                                dtype=dtype)
         .graph_builder()
         .add_inputs("in")
         .add_layer("enc", LSTM(n_out=8, activation="tanh"), "in")
         .add_vertex("last", LastTimeStepVertex(mask_input="in"), "enc")
         .add_vertex("dup", DuplicateToTimeSeriesVertex(reference_input="in"),
                     "last")
         .add_layer("dec", LSTM(n_out=8, activation="tanh"), "dup")
         .add_layer("out", RnnOutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "dec")
         .set_outputs("out")
         .set_input_types(InputType.recurrent(4, 6)))
    if tbptt:
        g = g.tbptt_length(tbptt)
    return ComputationGraph(g.build()).init()


def _seq_data(n=16, t=6, f=4, c=3):
    x = R.normal(size=(n, t, f)).astype(np.float32)
    yi = (np.cumsum(x.sum(-1), axis=1) > 0).astype(int)
    y = np.eye(c, dtype=np.float32)[np.clip(yi, 0, c - 1)]
    return x, y


def test_seq2seq_trains_with_tbptt():
    net = _seq2seq(tbptt=3)
    x, y = _seq_data()
    s0 = net.score(x, y)
    net.fit(x, y, epochs=20, batch_size=16)
    assert net.score(x, y) < s0
    assert net.iteration_count == 20 * 2  # 2 chunks of 3 per batch of T=6


def test_cg_tbptt_single_chunk_matches_standard_step():
    """With chunk length >= T one tBPTT step must equal one standard step."""
    x, y = _seq_data(n=8)
    a = _seq2seq(tbptt=None, updater=Sgd(0.1), seed=11)
    b = _seq2seq(tbptt=10, updater=Sgd(0.1), seed=11)
    b.set_params_flat(a.params_flat())
    a.fit(x, y, epochs=1, batch_size=8)
    b.fit(x, y, epochs=1, batch_size=8)
    np.testing.assert_allclose(np.asarray(a.params_flat()),
                               np.asarray(b.params_flat()), atol=2e-6)


def test_cg_rnn_time_step_matches_full_sequence():
    g = (NeuralNetConfiguration(seed=3, updater=Adam(1e-2), dtype="float32")
         .graph_builder()
         .add_inputs("in")
         .add_layer("l1", GravesLSTM(n_out=7, activation="tanh"), "in")
         .add_layer("out", RnnOutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"), "l1")
         .set_outputs("out")
         .set_input_types(InputType.recurrent(3, 5)))
    net = ComputationGraph(g.build()).init()
    x = R.normal(size=(4, 5, 3)).astype(np.float32)
    full = np.asarray(net.output(x))
    net.rnn_clear_previous_state()
    steps = [np.asarray(net.rnn_time_step(x[:, t])) for t in range(5)]
    for t in range(5):
        np.testing.assert_allclose(steps[t], full[:, t], atol=1e-5)
    # state persists: re-feeding step 0 now differs from the fresh-state output
    again = np.asarray(net.rnn_time_step(x[:, 0]))
    assert not np.allclose(again, steps[0], atol=1e-5)
    net.rnn_clear_previous_state()
    fresh = np.asarray(net.rnn_time_step(x[:, 0]))
    np.testing.assert_allclose(fresh, steps[0], atol=1e-5)


@pytest.mark.slow
def test_cg_seq2seq_gradients_with_masking():
    # Slow lane (ISSUE 19 tier-1 budget reclaim): ~9s masked-gradcheck
    # variant — test_cg_recurrent_gradients_plain keeps the CG recurrent
    # gradcheck tier-1 and the masked gradient contract stays tier-1 in
    # test_gradient_checks.py / test_recurrent.py's mask cases.
    net = _seq2seq(dtype="float64", updater=Sgd(0.1))
    x, y = _seq_data(n=4)
    x, y = x.astype(np.float64), y.astype(np.float64)
    fmask = np.ones((4, 6))
    fmask[2, 4:] = 0.0
    fmask[3, 2:] = 0.0
    lmask = fmask.copy()
    assert check_gradients(net, x, y, features_mask=fmask, labels_mask=lmask,
                           subset=150, print_results=True)


def test_cg_recurrent_gradients_plain():
    g = (NeuralNetConfiguration(seed=9, updater=Sgd(0.1), dtype="float64")
         .graph_builder()
         .add_inputs("in")
         .add_layer("l1", LSTM(n_out=6, activation="tanh"), "in")
         .add_layer("out", RnnOutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"), "l1")
         .set_outputs("out")
         .set_input_types(InputType.recurrent(3, 4)))
    net = ComputationGraph(g.build()).init()
    x = R.normal(size=(3, 4, 3))
    yi = (x.sum(-1) > 0).astype(int)
    y = np.eye(2)[yi]
    assert check_gradients(net, x, y, subset=150, print_results=True)
