"""Fleet observability (ISSUE 19): merge math, collector, fleet SLOs.

The merge-correctness contract is pinned here against the one honest
baseline there is: a single registry that observed every sample. Fleet
p99 computed off elementwise-summed cumulative ``le`` buckets must EQUAL
the single-registry bucket computation (same nearest-rank convention,
same ladder) — an averaged-percentile shortcut would fail this test.
Also covered: bucket-ladder mismatch refusal, the collector's
cursor/attribution/spool-recovery mechanics over synthetic spools, the
registry-shaped aggregate view driving an unmodified SLOWatchdog (and
through it the autoscaler's ``slo_breached`` input), Prometheus
exposition with ``replica=`` labels + ``fleet_`` aggregates, an
in-process end-to-end pull through real HTTP replicas, and the
trace2timeline/fleet_report tool surfaces. True multi-PROCESS stitching
(separate registries per OS process, SIGKILL spool recovery) lives in
tests/test_fleet_process.py.
"""
import json
import os
import sys
import time

import pytest

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.telemetry import (HistogramLadderMismatch,
                                          LatencySLO, MetricsRegistry,
                                          TraceSpool, bucket_quantile,
                                          merge_cumulative_buckets)
from deeplearning4j_tpu.serving.fleet import (FleetCollector, FleetRouter,
                                              merge_raw_metrics)
from deeplearning4j_tpu.serving.fleet.collector import FRONT_DOOR
from deeplearning4j_tpu.util.httpjson import HTTPClient

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

TID = "deadbeef0123"            # valid wire-format trace id (hex, 8-64)


@pytest.fixture
def fresh_registry():
    reg = MetricsRegistry(enabled=True)
    prev = telemetry.set_registry(reg)
    try:
        yield reg
    finally:
        telemetry.set_registry(prev)


class StubRouter:
    """Just enough router for the collector: a membership table + a
    pooled client."""

    def __init__(self, rows=()):
        self.rows = [dict(r) for r in rows]
        self.client = HTTPClient(max_per_host=2, timeout=5.0)

    def replicas(self):
        return [dict(r) for r in self.rows]

    def metrics(self):
        return {"replicas": {
            r["id"]: dict(r, steering=r.get("steering", {}))
            for r in self.rows}}


def _observing(samples, extra_counters=()):
    reg = MetricsRegistry(enabled=True)
    h = reg.histogram("generation.lm.ttft_ms")
    for v in samples:
        h.observe(float(v))
    for name, n in extra_counters:
        reg.counter(name).inc(n)
    return reg


def _event(seq_hint, name, ts, trace_id=TID, **args):
    return {"name": name, "ph": "i", "ts": ts, "cat": "event",
            "args": {"trace_id": trace_id, **args}}


# ------------------------------------------------------------- merge math
def test_fleet_quantile_equals_single_registry_pin():
    """THE regression pin: p50/p95/p99 off merged cumulative buckets ==
    the same computation on one registry that saw every sample."""
    a = [1.0, 3.0, 9.0, 40.0] * 25             # 100 samples
    b = [220.0, 800.0, 4000.0] * 40             # 120 samples, other tail
    ra, rb = _observing(a), _observing(b)
    rall = _observing(a + b)
    merged = merge_raw_metrics(
        {"r0": ra.raw_metrics(), "r1": rb.raw_metrics()}
    )["histograms"]["generation.lm.ttft_ms"]
    single = rall.raw_metrics()["histograms"]["generation.lm.ttft_ms"]
    assert merged["bounds"] == single["bounds"]
    assert merged["cumulative"] == single["cumulative"]
    assert merged["count"] == single["count"] == 220
    assert merged["sum"] == pytest.approx(single["sum"])
    for q in (0.5, 0.95, 0.99):
        assert bucket_quantile(merged["bounds"], merged["cumulative"], q) \
            == bucket_quantile(single["bounds"], single["cumulative"], q)


def test_merge_sums_counters_and_keeps_gauges_out():
    raws = {"r0": _observing([1.0], [("fleet.ok", 3)]).raw_metrics(),
            "r1": _observing([2.0], [("fleet.ok", 4)]).raw_metrics()}
    agg = merge_raw_metrics(raws)
    assert agg["counters"]["fleet.ok"] == 7
    assert agg["replicas"] == ["r0", "r1"]
    assert "gauges" not in agg      # no honest fleet-wide gauge sum


def test_merge_refuses_ladder_mismatch_loudly():
    good = _observing([5.0]).raw_metrics()
    bad = _observing([5.0]).raw_metrics()
    h = bad["histograms"]["generation.lm.ttft_ms"]
    h["bounds"] = h["bounds"][:-1] + [99999.0]      # different ladder
    with pytest.raises(HistogramLadderMismatch) as ei:
        merge_raw_metrics({"r0": good, "r1": bad})
    assert "r1" in str(ei.value)                    # names the offender
    with pytest.raises(HistogramLadderMismatch):
        merge_cumulative_buckets([1.0, 2.0], [[1, 2, 3], [1, 2]])


# -------------------------------------------------- collector mechanics
def test_collector_ingests_spool_with_cursor_and_attribution(tmp_path):
    vic = MetricsRegistry(enabled=True)
    for i in range(3):
        vic.record_event(_event(i, f"gen.step{i}", 1000 + i))
    vic.histogram("generation.lm.ttft_ms").observe(7.0)
    vic.gauge("generation.lm.queue_depth").set(2.0)
    vic.gauge("generation.lm.prefix_hit_rate").set(0.75)
    path = str(tmp_path / "replica-r0.spool.json")
    TraceSpool(path, replica_id="r0", registry=vic).flush(force=True)

    router = StubRouter([{"id": "r0", "state": "dead", "url": None,
                          "spool_path": path}])
    local = MetricsRegistry(enabled=True)
    col = FleetCollector(router, registry=local)
    try:
        assert col.pull_once() == 3
        assert col.spools_recovered == 1
        # exactly-once by seq watermark: the same spill adds nothing
        assert col.pull_once() == 0
        assert col.spools_recovered == 1
        events = col.events_for_trace(TID)
        assert [e["name"] for e in events] == ["gen.step0", "gen.step1",
                                               "gen.step2"]
        assert all(e["args"]["replica"] == "r0" for e in events)
        # the victim's metrics joined the aggregate
        agg = col.aggregate()
        assert agg["histograms"]["generation.lm.ttft_ms"]["count"] == 1
        # per-replica steering gauges published into the LOCAL registry
        assert local.gauge_if_exists(
            "fleet.replica.r0.prefix_hit_rate").value == 0.75
        assert local.gauge_if_exists(
            "fleet.replica.r0.queue_depth").value == 2.0
        snap = col.snapshot()
        assert snap["spools_recovered"] == 1
        assert snap["per_replica"]["r0"]["events"] == 3
        assert snap["traces"] == 1
    finally:
        col.stop()
        router.client.close()


def test_stitching_merges_local_front_door_events(tmp_path):
    vic = MetricsRegistry(enabled=True)
    vic.record_event(_event(0, "generation.admit", 2000))
    path = str(tmp_path / "replica-r1.spool.json")
    TraceSpool(path, replica_id="r1", registry=vic).flush(force=True)
    router = StubRouter([{"id": "r1", "state": "dead", "url": None,
                          "spool_path": path}])
    local = MetricsRegistry(enabled=True)
    local.record_event(_event(0, "fleet.request", 1000))   # earlier ts
    col = FleetCollector(router, registry=local)
    try:
        col.pull_once()
        events = col.events_for_trace(TID)
        assert [e["name"] for e in events] == ["fleet.request",
                                               "generation.admit"]
        assert events[0]["args"]["replica"] == FRONT_DOOR
        assert events[1]["args"]["replica"] == "r1"
        # the local ring itself was NOT mutated by the stamping
        assert "replica" not in local.trace_events()[0]["args"]
    finally:
        col.stop()
        router.client.close()


# -------------------------------------------- aggregate registry + SLOs
def test_fleet_watchdog_and_autoscaler_wiring(tmp_path):
    """An unmodified SLOWatchdog over the aggregate view breaches on
    fleet-wide bad latency, writes its gauges into the LOCAL registry,
    and feeds the autoscaler's ``slo_breached`` observation."""
    from deeplearning4j_tpu.serving.fleet import Autoscaler

    rows = []
    for rid, lat in (("r0", 900.0), ("r1", 950.0)):
        reg = MetricsRegistry(enabled=True)
        for _ in range(50):
            reg.histogram("generation.lm.ttft_ms").observe(lat)
        path = str(tmp_path / f"replica-{rid}.spool.json")
        TraceSpool(path, replica_id=rid, registry=reg).flush(force=True)
        rows.append({"id": rid, "state": "dead", "url": None,
                     "spool_path": path})
    router = StubRouter(rows)
    local = MetricsRegistry(enabled=True)
    col = FleetCollector(router, registry=local)
    try:
        col.pull_once()
        areg = col.aggregate_registry()
        h = areg.histogram("generation.lm.ttft_ms")
        good, total = h.count_le_and_total(50.0)
        assert (good, total) == (0, 100)        # every sample is bad
        wd = col.make_watchdog(
            [LatencySLO("fleet_ttft", "generation.lm.ttft_ms",
                        threshold_ms=50.0, target=0.99)],
            dump_on_breach=False)
        # anchor sample times to the monotonic clock: Autoscaler.observe()
        # re-runs check() at real time.monotonic(), so synthetic epochs
        # would fall outside the burn windows
        t0 = time.monotonic()
        wd.check(now=t0 - 45.0)                 # seed the baseline
        for _ in range(100):
            col.local_registry.histogram("generation.lm.ttft_ms") \
               .observe(900.0)                  # front door sees it too
        out = wd.check(now=t0)                  # 60s window 75% covered
        assert "fleet_ttft" in out["breached"]
        # watchdog side effects landed in the local registry
        assert local.gauge_if_exists("slo.fleet_ttft.breached").value == 1
        assert local.counter("slo.breaches").value >= 1
        scaler = Autoscaler(router, spec_factory=lambda i: None,
                            watchdog=wd)
        obs = scaler.observe()
        assert obs["slo_breached"] is True
        assert "fleet_ttft" in obs["breached"]
    finally:
        col.stop()
        router.client.close()


def test_prometheus_text_labels_and_fleet_aggregates(tmp_path):
    regs = {"r0": _observing([1.0, 40.0], [("requests", 2)]),
            "r1": _observing([800.0], [("requests", 1)])}
    rows = []
    for rid, reg in regs.items():
        path = str(tmp_path / f"replica-{rid}.spool.json")
        TraceSpool(path, replica_id=rid, registry=reg).flush(force=True)
        rows.append({"id": rid, "state": "dead", "url": None,
                     "spool_path": path})
    router = StubRouter(rows)
    local = MetricsRegistry(enabled=True)
    col = FleetCollector(router, registry=local)
    try:
        col.pull_once()
        text = col.to_prometheus_text()
        # per-replica samples carry replica= labels
        assert 'dl4j_tpu_requests{replica="r0"} 2' in text
        assert 'dl4j_tpu_requests{replica="r1"} 1' in text
        assert 'dl4j_tpu_generation_lm_ttft_ms_bucket{replica="r0",' \
            in text
        # fleet aggregates: summed counter + merged bucket series
        assert "dl4j_tpu_fleet_requests 3" in text
        assert "# TYPE dl4j_tpu_fleet_generation_lm_ttft_ms histogram" \
            in text
        assert 'dl4j_tpu_fleet_generation_lm_ttft_ms_bucket{le="+Inf"} 3' \
            in text
        assert "dl4j_tpu_fleet_generation_lm_ttft_ms_count 3" in text
        # the merged bucket series reproduces the honest fleet quantile
        merged = col.merged_histogram("generation.lm.ttft_ms")
        single = _observing([1.0, 40.0, 800.0]).histogram(
            "generation.lm.ttft_ms").raw()
        assert merged["cumulative"] == single["cumulative"]
    finally:
        col.stop()
        router.client.close()


# --------------------------------------------------- in-process end to end
@pytest.fixture(scope="module")
def live_replica():
    """One real single-process replica (GenerationEngine behind
    ServingHTTPServer) — the /debug/trace + /debug/metrics surface under
    a real HTTP client."""
    from deeplearning4j_tpu.models.zoo_extra import transformer_lm
    from deeplearning4j_tpu.serving import (GenerationEngine,
                                            ServingHTTPServer)
    net = transformer_lm(vocab_size=29, d_model=16, n_heads=2, n_blocks=1,
                         max_length=32, seed=7, dtype="float32",
                         token_input=True).init()
    eng = GenerationEngine(net, model_name="lm", block_len=8,
                           max_seq_len=32, decode_slots=2,
                           prefill_batches=(1,), prompt_rungs=(32,))
    srv = ServingHTTPServer(generation=eng)
    url = f"http://127.0.0.1:{srv.start()}"
    yield url
    srv.stop()
    eng.stop(drain=False, timeout=5.0)


def test_debug_trace_route_serves_ndjson_deltas(live_replica,
                                                fresh_registry):
    client = HTTPClient(max_per_host=1, timeout=10.0)
    try:
        status, body = client.request_json(
            "POST", live_replica + "/generate",
            payload={"prompt": [1, 2, 3], "max_tokens": 3,
                     "stream": False},
            headers={"X-Trace-Id": TID})
        assert status == 200
        status, headers, events = client.request_ndjson(
            "GET", live_replica + "/debug/trace?since_seq=0")
        assert status == 200
        assert headers.get("Content-Type") == "application/x-ndjson"
        watermark = int(headers["X-Trace-Seq"])
        assert watermark == fresh_registry.last_seq > 0
        assert any(e.get("args", {}).get("trace_id") == TID
                   for e in events)
        seqs = [e["seq"] for e in events]
        assert seqs == sorted(seqs)
        # cursoring: pulling past the watermark returns nothing
        status, _, rest = client.request_ndjson(
            "GET",
            f"{live_replica}/debug/trace?since_seq={watermark}")
        assert status == 200 and rest == []
        status, _, _ = client.request_ndjson(
            "GET", live_replica + "/debug/trace?since_seq=bogus")
        assert status == 400
        status, raw = client.request_json(
            "GET", live_replica + "/debug/metrics")
        assert status == 200
        assert "generation.lm.ttft_ms" in raw["histograms"]
    finally:
        client.close()


def test_collector_pulls_live_replica_and_front_door_routes(
        live_replica, fresh_registry, tmp_path):
    """Real HTTP pull path + the fleet front door's collector routes
    (/debug/trace/<id> stitched JSON, /metrics/prometheus, /metrics slo
    + collector keys). The collector gets its OWN local registry so the
    shared-process registry does not double as both sides."""
    from deeplearning4j_tpu.serving.fleet.http import FleetHTTPServer
    router = FleetRouter(policy="round_robin", health_period_s=3600.0)
    local = MetricsRegistry(enabled=True)
    col = FleetCollector(router, registry=local)
    front = FleetHTTPServer(router, collector=col)
    port = front.start()
    client = HTTPClient(max_per_host=2, timeout=10.0)
    try:
        router.add_url(live_replica, "f0")
        status, body = client.request_json(
            "POST", f"http://127.0.0.1:{port}/generate",
            payload={"prompt": [2, 3, 4], "max_tokens": 3,
                     "stream": False},
            headers={"X-Trace-Id": TID})
        assert status == 200 and body["replica"] == "f0"
        got = col.pull_once()
        assert got > 0 and col.pull_errors == 0
        cursor = col.snapshot()["per_replica"]["f0"]["cursor"]
        assert col.pull_once() == 0     # cursor: no re-pull of old spans
        assert col.snapshot()["per_replica"]["f0"]["cursor"] >= cursor
        # stitched download through the front door
        status, stitched = client.request_json(
            "GET", f"http://127.0.0.1:{port}/debug/trace/{TID}")
        assert status == 200 and stitched["trace_id"] == TID
        names = [e["name"] for e in stitched["events"]]
        assert any(n.startswith("generation.") for n in names)
        assert all(e["args"]["replica"] == "f0"
                   for e in stitched["events"])
        status, listing = client.request_json(
            "GET", f"http://127.0.0.1:{port}/debug/trace")
        assert status == 200 and TID in listing["traces"]
        status, _, data = client.request(
            "GET", f"http://127.0.0.1:{port}/metrics/prometheus")
        text = data.decode()
        assert status == 200
        assert 'replica="f0"' in text and "dl4j_tpu_fleet_" in text
        col.make_watchdog([LatencySLO(
            "fleet_ttft", "generation.lm.ttft_ms",
            threshold_ms=60000.0, target=0.5)], dump_on_breach=False)
        status, m = client.request_json(
            "GET", f"http://127.0.0.1:{port}/metrics")
        assert status == 200
        assert m["collector"]["pulls"] >= 2
        assert "fleet_ttft" in m["slo"]["objectives"]
        # 404 for an unknown trace id
        status, _ = client.request_json(
            "GET", f"http://127.0.0.1:{port}/debug/trace/{'ab' * 8}")
        assert status == 404
    finally:
        client.close()
        front.stop()
        col.stop()
        router.stop()
        # replicas are externally managed here: close only the client
        router.client.close()


# ------------------------------------------------------------ tool surface
def test_trace2timeline_merges_spools_with_replica_column(tmp_path,
                                                          capsys):
    from tools.trace2timeline import (format_timeline, list_traces,
                                      load_merged, main, timeline)
    front = {"replica": "", "events": [
        _event(0, "fleet.request", 1000),
        _event(0, "fleet.route", 1500, target="f0")]}
    spool = {"spool": 1, "replica": "f0", "seq": 2, "events": [
        _event(0, "generation.admit", 2000),
        _event(0, "generation.prefill", 3000)]}
    fp = tmp_path / "front.json"
    sp = tmp_path / "replica-f0.spool.json"
    fp.write_text(json.dumps(front))
    sp.write_text(json.dumps(spool))

    events = load_merged([str(fp), str(sp)])
    rows = timeline(events, TID)
    assert [r["name"] for r in rows] == ["fleet.request", "fleet.route",
                                         "generation.admit",
                                         "generation.prefill"]
    assert [r["replica"] for r in rows] == ["", "", "f0", "f0"]
    text = format_timeline(rows)
    assert "replica" in text.splitlines()[0]
    listing = list_traces(events)
    assert listing[0]["replicas"] == ["f0"]
    # CLI accepts multiple files
    assert main([str(fp), str(sp), "--trace-id", TID]) == 0
    out = capsys.readouterr().out
    assert "generation.prefill" in out and "f0" in out


def test_fleet_report_renders_slo_and_collector_sections():
    from tools.fleet_report import fold, render
    snap = {
        "policy": "affinity", "block_len": 8,
        "replicas": {"f0": {"state": "ready", "steering": {}}},
        "replica_metrics": {},
        "slo": {"objectives": {
                    "fleet_ttft": {"target": 0.99,
                                   "burn_rates": {"60s": 7.5,
                                                  "300s": 2.0}}},
                "breached": ["fleet_ttft"]},
        "collector": {"pulls": 12, "events_pulled": 340, "traces": 4,
                      "spools_recovered": 1, "pull_errors": 0},
    }
    report = fold(snap)
    assert report["slo"]["breached"] == ["fleet_ttft"]
    text = render(report)
    assert "fleet SLOs:" in text
    assert "fleet_ttft: target=0.99" in text
    assert "burn[60s]=7.50" in text and "BREACHED" in text
    assert "collector: pulls=12" in text
    assert "spools_recovered=1" in text
    # a snapshot without the new keys renders the old report unchanged
    plain = render(fold({"policy": "affinity", "block_len": 8,
                         "replicas": {}}))
    assert "fleet SLOs" not in plain and "collector:" not in plain


# ------------------------------------------------------------- bench guard
@pytest.mark.bench_smoke
def test_fleet_collector_overhead_bench_smoke():
    """Tier-1 guard for the ISSUE 19 bench variant: collector pulls +
    spool spills riding the serving process must stay <5% on the paired
    best-of ratio. Same retry discipline as the other telemetry guards —
    wall clock on a shared rig swings, so fail only on three consecutive
    breaches."""
    import bench
    last = None
    for _ in range(3):
        row = bench.bench_telemetry_overhead(steps=32, repeats=4,
                                             serving_requests=80,
                                             variants=("fleet",))
        assert row["fleet_collected_req_per_sec"] > 0
        last = row
        if row["fleet_collector_overhead_pct"] < 5.0:
            return
    pytest.fail(f"fleet collector overhead >=5% in 3 consecutive runs: "
                f"{last}")
