"""VAE + RBM tests (mirrors reference VaeGradientCheckTests + TestVAE +
RBMTests): pretrain ELBO gradient checks across reconstruction distributions,
supervised-path gradient checks, generative APIs, RBM CD-k pretraining."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (BernoulliReconstructionDistribution,
                                          CompositeReconstructionDistribution,
                                          DenseLayer,
                                          ExponentialReconstructionDistribution,
                                          GaussianReconstructionDistribution,
                                          LossFunctionWrapper, OutputLayer,
                                          RBM, VariationalAutoencoder)
from deeplearning4j_tpu.nn.conf.serde import from_json, to_json
from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.optimize.updaters import Sgd
from deeplearning4j_tpu.util.gradcheck import (check_gradients,
                                               check_pretrain_gradients)

R = np.random.default_rng(7)


def _vae_net(dist, n_in=6, latent=3, act="tanh", num_samples=1):
    conf = NeuralNetConfiguration(seed=12345, updater=Sgd(0.05), dtype="float64") \
        .list(VariationalAutoencoder(
            n_in=n_in, n_out=latent, encoder_layer_sizes=(7,),
            decoder_layer_sizes=(7,), activation=act,
            reconstruction_distribution=dist, num_samples=num_samples),
        ).build()
    return MultiLayerNetwork(conf).init()


def _data(dist, n=8, d=6):
    if isinstance(dist, BernoulliReconstructionDistribution):
        return (R.random((n, d)) > 0.5).astype(float)
    if isinstance(dist, ExponentialReconstructionDistribution):
        return R.exponential(1.0, size=(n, d))
    return R.normal(size=(n, d))


@pytest.mark.parametrize("dist", [
    # plain-gaussian variant in the slow lane (tier-1 budget): the gaussian
    # gradcheck stays pinned via gaussian-tanh here and the gaussian half
    # of test_vae_pretrain_gradients_composite
    pytest.param(GaussianReconstructionDistribution(),
                 marks=pytest.mark.slow),
    GaussianReconstructionDistribution(activation="tanh"),
    BernoulliReconstructionDistribution(),
    ExponentialReconstructionDistribution(),
    LossFunctionWrapper(loss="mse"),
], ids=["gaussian", "gaussian-tanh", "bernoulli", "exponential", "losswrapper"])
def test_vae_pretrain_gradients(dist):
    net = _vae_net(dist)
    x = _data(dist)
    assert check_pretrain_gradients(net, 0, x, print_results=True)


def test_vae_pretrain_gradients_multisample():
    net = _vae_net(GaussianReconstructionDistribution(), num_samples=3)
    x = _data(GaussianReconstructionDistribution())
    assert check_pretrain_gradients(net, 0, x, print_results=True)


def test_vae_pretrain_gradients_composite():
    # columns 0-2 gaussian, 3-5 bernoulli (reference
    # CompositeReconstructionDistribution usage in VaeGradientCheckTests)
    dist = CompositeReconstructionDistribution(parts=[
        [3, GaussianReconstructionDistribution()],
        [3, BernoulliReconstructionDistribution()]])
    net = _vae_net(dist)
    x = np.concatenate([R.normal(size=(8, 3)),
                        (R.random((8, 3)) > 0.5).astype(float)], axis=1)
    assert check_pretrain_gradients(net, 0, x, print_results=True)


def test_vae_supervised_gradients():
    """VAE as a hidden layer of a classifier (reference VaeGradientCheckTests
    testVaeAsMLP): forward = mean(q(z|x)); decoder params get zero gradient."""
    conf = NeuralNetConfiguration(seed=12345, updater=Sgd(0.05), dtype="float64") \
        .list(VariationalAutoencoder(n_in=4, n_out=3, encoder_layer_sizes=(6,),
                                     decoder_layer_sizes=(6,), activation="tanh"),
              OutputLayer(n_out=3, activation="softmax", loss="mcxent")).build()
    net = MultiLayerNetwork(conf).init()
    x = R.normal(size=(10, 4))
    y = np.eye(3)[R.integers(0, 3, 10)]
    assert check_gradients(net, x, y, print_results=True)


def test_vae_pretrain_improves_elbo_and_generates():
    dist = BernoulliReconstructionDistribution()
    net = _vae_net(dist, n_in=8, latent=2)
    x = (R.random((64, 8)) > 0.6).astype(float)
    layer = net.layers[0]
    rng = jax.random.PRNGKey(0)
    before = float(layer.pretrain_loss(net.params[0], jnp.asarray(x), rng))
    it = ListDataSetIterator(features=x, labels=x, batch_size=16)
    net.pretrain(it, epochs=30)
    after = float(layer.pretrain_loss(net.params[0], jnp.asarray(x), rng))
    assert after < before
    # generative APIs
    z = jnp.asarray(R.normal(size=(5, 2)))
    mean_x = layer.generate_at_mean_given_z(net.params[0], z)
    assert mean_x.shape == (5, 8)
    assert np.all(np.asarray(mean_x) >= 0) and np.all(np.asarray(mean_x) <= 1)
    rand_x = layer.generate_random_given_z(net.params[0], z, jax.random.PRNGKey(1))
    assert set(np.unique(np.asarray(rand_x))) <= {0.0, 1.0}
    logp = layer.reconstruction_log_probability(net.params[0], jnp.asarray(x[:4]),
                                                num_samples=10)
    assert logp.shape == (4,)
    assert np.all(np.isfinite(np.asarray(logp)))


def test_vae_config_roundtrip():
    dist = CompositeReconstructionDistribution(parts=[
        [2, GaussianReconstructionDistribution(activation="tanh")],
        [3, BernoulliReconstructionDistribution()]])
    layer = VariationalAutoencoder(n_in=5, n_out=2, encoder_layer_sizes=(4, 3),
                                   decoder_layer_sizes=(3, 4),
                                   reconstruction_distribution=dist,
                                   pzx_activation="tanh", num_samples=2)
    back = from_json(to_json(layer))
    assert back == layer
    assert back.param_order == layer.param_order


def test_rbm_supervised_gradients():
    """RBM as feed-forward layer: propUp is just act(xW+b) (reference
    RBM.activate)."""
    conf = NeuralNetConfiguration(seed=12345, updater=Sgd(0.05), dtype="float64") \
        .list(RBM(n_in=4, n_out=5),
              OutputLayer(n_out=3, activation="softmax", loss="mcxent")).build()
    net = MultiLayerNetwork(conf).init()
    x = R.normal(size=(10, 4))
    y = np.eye(3)[R.integers(0, 3, 10)]
    assert check_gradients(net, x, y, print_results=True)


@pytest.mark.parametrize("visible,hidden", [("binary", "binary"),
                                            ("gaussian", "rectified")])
def test_rbm_cd_pretrain_reduces_reconstruction_error(visible, hidden):
    conf = NeuralNetConfiguration(seed=12345, updater=Sgd(0.05), dtype="float64") \
        .list(RBM(n_in=6, n_out=12, visible_unit=visible, hidden_unit=hidden, k=1),
        ).build()
    net = MultiLayerNetwork(conf).init()
    # two prototype patterns + noise
    protos = np.array([[1, 1, 1, 0, 0, 0], [0, 0, 0, 1, 1, 1]], dtype=float)
    x = protos[R.integers(0, 2, 128)]
    if visible == "binary":
        flip = R.random(x.shape) < 0.05
        x = np.where(flip, 1 - x, x)
    else:
        x = x + 0.1 * R.normal(size=x.shape)
    layer = net.layers[0]

    def recon_err(params):
        r = layer.reconstruct(params, jnp.asarray(x))
        return float(jnp.mean((r - x) ** 2))

    before = recon_err(net.params[0])
    it = ListDataSetIterator(features=x, labels=x, batch_size=32)
    net.pretrain(it, epochs=20)
    after = recon_err(net.params[0])
    assert after < before


def test_rbm_free_energy_surrogate_matches_cd_update():
    """grad of the surrogate loss w.r.t. vb must be exactly -(mean v_data -
    mean v_model) — the textbook CD visible-bias update."""
    layer = RBM(n_in=4, n_out=3, k=1)
    rng = jax.random.PRNGKey(3)
    params = {"W": jnp.asarray(R.normal(size=(4, 3)) * 0.1),
              "b": jnp.zeros(3), "vb": jnp.zeros(4)}
    v0 = jnp.asarray((R.random((16, 4)) > 0.5).astype(float))
    grads = jax.grad(lambda p: layer.pretrain_loss(p, v0, rng))(params)
    v_model = layer.gibbs_chain(params, v0, rng)
    expected_vb = -(jnp.mean(v0, axis=0) - jnp.mean(v_model, axis=0))
    np.testing.assert_allclose(np.asarray(grads["vb"]),
                               np.asarray(expected_vb), atol=1e-10)


def test_vae_pretrain_on_computation_graph():
    """ComputationGraph.pretrain (reference ComputationGraph.pretrain):
    a VAE vertex trains its ELBO against its input vertex's activations."""
    from deeplearning4j_tpu import InputType
    from deeplearning4j_tpu.nn.graph.graph import ComputationGraph

    g = (NeuralNetConfiguration(seed=4, updater=Sgd(0.05), dtype="float64")
         .graph_builder()
         .add_inputs("in")
         .add_layer("vae", VariationalAutoencoder(
             n_in=8, n_out=2, encoder_layer_sizes=(10,),
             decoder_layer_sizes=(10,), activation="tanh",
             reconstruction_distribution=BernoulliReconstructionDistribution()),
             "in")
         .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"), "vae")
         .set_outputs("out")
         .set_input_types(InputType.feed_forward(8)))
    net = ComputationGraph(g.build()).init()
    x = (R.random((64, 8)) > 0.6).astype(float)
    vae_idx = net.vertex_names.index("vae")
    layer = net.layers[vae_idx]
    rng = jax.random.PRNGKey(0)
    before = float(layer.pretrain_loss(net.params[vae_idx], jnp.asarray(x), rng))
    it = ListDataSetIterator(features=x, labels=x, batch_size=16)
    net.pretrain(it, epochs=25)
    after = float(layer.pretrain_loss(net.params[vae_idx], jnp.asarray(x), rng))
    assert after < before
    # supervised fine-tuning on top still works
    y = np.eye(2)[R.integers(0, 2, 64)]
    net.fit(x, y, epochs=2, batch_size=64)
