"""Gradient checks: central-difference vs analytic, float64 (SURVEY.md §4 —
the correctness backbone; mirrors reference GradientCheckTests,
CNNGradientCheckTest, BNGradientCheckTest, LossFunctionGradientCheck)."""
import numpy as np
import pytest

from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import (ActivationLayer, BatchNormalization,
                                          ConvolutionLayer, DenseLayer,
                                          GlobalPoolingLayer,
                                          LocalResponseNormalization,
                                          LossLayer, OutputLayer,
                                          SubsamplingLayer, ZeroPaddingLayer)
from deeplearning4j_tpu.optimize.updaters import Sgd
from deeplearning4j_tpu.util.gradcheck import check_gradients

R = np.random.default_rng(42)


def _net(layers, input_type=None, l1=0.0, l2=0.0):
    b = NeuralNetConfiguration(seed=12345, updater=Sgd(0.1), dtype="float64",
                               l1=l1, l2=l2).list(*layers)
    if input_type is not None:
        b = b.set_input_type(input_type)
    return MultiLayerNetwork(b.build()).init()


def _onehot(idx, n):
    return np.eye(n)[idx]


@pytest.mark.parametrize("act", ["tanh", "sigmoid", "relu", "elu", "softplus",
                                 "cube", "rationaltanh"])
def test_dense_activations(act):
    net = _net([DenseLayer(n_in=4, n_out=6, activation=act),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")])
    x = R.normal(size=(10, 4))
    y = _onehot(R.integers(0, 3, 10), 3)
    assert check_gradients(net, x, y, print_results=True)


@pytest.mark.parametrize("loss,act", [
    ("mcxent", "softmax"), ("mse", "identity"), ("mse", "tanh"),
    ("xent", "sigmoid"), ("l1", "identity"), ("l2", "tanh"),
    ("hinge", "identity"), ("squared_hinge", "identity"),
    ("poisson", "softplus"), ("mean_absolute_error", "identity"),
    ("kl_divergence", "sigmoid"), ("cosine_proximity", "identity"),
])
def test_loss_functions(loss, act):
    n_out = 3
    net = _net([DenseLayer(n_in=4, n_out=5, activation="tanh"),
                OutputLayer(n_out=n_out, activation=act, loss=loss)])
    x = R.normal(size=(8, 4))
    if loss in ("hinge", "squared_hinge"):
        y = 2.0 * _onehot(R.integers(0, n_out, 8), n_out) - 1.0
    elif loss in ("mcxent", "xent", "kl_divergence"):
        y = _onehot(R.integers(0, n_out, 8), n_out)
        if loss == "kl_divergence":
            y = np.clip(y, 0.05, 0.9)
            y /= y.sum(-1, keepdims=True)
    elif loss == "poisson":
        y = R.poisson(3.0, size=(8, n_out)).astype(float)
    else:
        y = R.normal(size=(8, n_out))
    assert check_gradients(net, x, y, print_results=True)


def test_l1_l2_regularization():
    net = _net([DenseLayer(n_in=4, n_out=6, activation="tanh"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
               l1=0.01, l2=0.02)
    # keep params away from 0 so |w| is differentiable
    flat = np.asarray(net.params_flat())
    flat = np.where(np.abs(flat) < 0.05, 0.1, flat)
    net.set_params_flat(flat)
    x = R.normal(size=(10, 4))
    y = _onehot(R.integers(0, 3, 10), 3)
    assert check_gradients(net, x, y, print_results=True)


def test_cnn_conv_subsampling():
    net = _net([ConvolutionLayer(n_out=3, kernel_size=(2, 2), stride=(1, 1),
                                 activation="tanh"),
                SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                 stride=(2, 2)),
                OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               input_type=InputType.convolutional(6, 6, 2))
    x = R.normal(size=(6, 6, 6, 2))
    y = _onehot(R.integers(0, 2, 6), 2)
    assert check_gradients(net, x, y, print_results=True)


@pytest.mark.parametrize("pool", ["avg", "pnorm"])
def test_cnn_pooling_types(pool):
    net = _net([ConvolutionLayer(n_out=2, kernel_size=(2, 2), activation="sigmoid"),
                SubsamplingLayer(pooling_type=pool, kernel_size=(2, 2), stride=(1, 1)),
                OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               input_type=InputType.convolutional(5, 5, 1))
    x = R.normal(size=(4, 5, 5, 1))
    y = _onehot(R.integers(0, 2, 4), 2)
    assert check_gradients(net, x, y, print_results=True)


def test_cnn_same_mode_zeropad_globalpool():
    net = _net([ZeroPaddingLayer(padding=(1, 1)),
                ConvolutionLayer(n_out=3, kernel_size=(3, 3), stride=(2, 2),
                                 convolution_mode="same", activation="tanh"),
                GlobalPoolingLayer(pooling_type="avg"),
                OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               input_type=InputType.convolutional(6, 6, 2))
    x = R.normal(size=(5, 6, 6, 2))
    y = _onehot(R.integers(0, 2, 5), 2)
    assert check_gradients(net, x, y, print_results=True)


def test_batchnorm_dense():
    net = _net([DenseLayer(n_in=4, n_out=6, activation="identity"),
                BatchNormalization(),
                ActivationLayer(activation="tanh"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")])
    x = R.normal(size=(12, 4))
    y = _onehot(R.integers(0, 3, 12), 3)
    # BN in eval mode uses running stats (fixed) — gradients flow through
    # gamma/beta and the normalization; matches reference BNGradientCheckTest
    # which checks through the BN transform.
    assert check_gradients(net, x, y, print_results=True)


def test_batchnorm_cnn_and_lrn():
    net = _net([ConvolutionLayer(n_out=3, kernel_size=(2, 2), activation="identity"),
                BatchNormalization(),
                LocalResponseNormalization(),
                ActivationLayer(activation="relu"),
                GlobalPoolingLayer(pooling_type="max"),
                OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               input_type=InputType.convolutional(5, 5, 2))
    x = R.normal(size=(4, 5, 5, 2))
    y = _onehot(R.integers(0, 2, 4), 2)
    assert check_gradients(net, x, y, print_results=True)


def test_loss_layer_and_masking():
    net = _net([DenseLayer(n_in=4, n_out=3, activation="tanh"),
                LossLayer(loss="mcxent", activation="softmax")])
    x = R.normal(size=(9, 4))
    y = _onehot(R.integers(0, 3, 9), 3)
    mask = np.ones(9)
    mask[5:] = 0.0
    assert check_gradients(net, x, y, labels_mask=mask, print_results=True)


def test_conv1d_subsampling1d():
    """Temporal conv family (reference CNN1DGradientCheckTest)."""
    from deeplearning4j_tpu.nn.layers import (Convolution1DLayer,
                                              RnnOutputLayer,
                                              Subsampling1DLayer)
    from deeplearning4j_tpu import InputType
    net = _net([Convolution1DLayer(n_out=5, kernel_size=3,
                                   convolution_mode="same", activation="tanh"),
                Subsampling1DLayer(pooling_type="max", kernel_size=2, stride=1,
                                   convolution_mode="same"),
                RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               input_type=InputType.recurrent(3, 6))
    x = R.normal(size=(3, 6, 3))
    y = _onehot(R.integers(0, 2, (3, 6)).ravel(), 2).reshape(3, 6, 2)
    assert check_gradients(net, x, y, print_results=True)


def test_embedding_layer_gradients():
    """Embedding gather (scatter-add backward; reference GradientCheckTests
    embedding coverage)."""
    from deeplearning4j_tpu.nn.layers import EmbeddingLayer
    net = _net([EmbeddingLayer(n_in=7, n_out=5, activation="tanh"),
                DenseLayer(n_out=6, activation="tanh"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")])
    x = R.integers(0, 7, (10, 1))
    y = _onehot(R.integers(0, 3, 10), 3)
    assert check_gradients(net, x, y, print_results=True)


def test_center_loss_output_gradients_and_dynamics():
    """CenterLossOutputLayer: the center terms deliberately stop-gradient one
    side each (SGD on the alpha term IS the reference's EMA center update),
    so the full objective is not central-difference checkable — the
    classifier path is gradchecked with the center terms off, and the center
    DYNAMICS are asserted directly: centers move toward class feature means."""
    from deeplearning4j_tpu.nn.layers import CenterLossOutputLayer

    # 1) classifier path exact (center terms disabled)
    net = _net([DenseLayer(n_in=4, n_out=6, activation="tanh"),
                CenterLossOutputLayer(n_out=3, activation="softmax",
                                      loss="mcxent", alpha=0.0, lambda_=0.0)])
    x = R.normal(size=(8, 4))
    y = _onehot(R.integers(0, 3, 8), 3)
    assert check_gradients(net, x, y, print_results=True)

    # 2) center dynamics: with alpha on, training pulls each class's center
    # toward that class's mean feature vector
    import numpy as _np
    net2 = _net([DenseLayer(n_in=4, n_out=6, activation="tanh"),
                 CenterLossOutputLayer(n_out=3, activation="softmax",
                                       loss="mcxent", alpha=0.5, lambda_=0.01)])
    x2 = R.normal(size=(60, 4))
    yi = R.integers(0, 3, 60)
    y2 = _onehot(yi, 3)
    net2.fit(x2, y2, epochs=20, batch_size=60)
    feats = _np.asarray(net2.feed_forward(x2)[1])       # dense activations
    centers = _np.asarray(net2.params[1]["centers"])
    for c in range(3):
        mean_c = feats[yi == c].mean(0)
        d_own = _np.linalg.norm(centers[c] - mean_c)
        d_other = min(_np.linalg.norm(centers[o] - mean_c)
                      for o in range(3) if o != c)
        assert d_own < d_other, (c, d_own, d_other)
