"""Genuinely-pretrained zoo weights (models.digits_cnn): the committed
artifact carries weights TRAINED on real handwritten-digit scans
(tools/train_pretrained_digits.py — UCI optical digits via scikit-learn,
1,397 train / 400 held out). These tests restore WITHOUT any training and
verify real generalization, the reference ZooModel.initPretrained
contract (zoo/ZooModel.java:40-81) with real learned weights behind it."""
import numpy as np
import pytest

from deeplearning4j_tpu.models import digits_cnn
from deeplearning4j_tpu.models.lenet import (DIGITS_CNN_ARTIFACT,
                                             DIGITS_CNN_CHECKSUM)
from deeplearning4j_tpu.models.pretrained import adler32_of


def _held_out():
    """The exact held-out split the training tool never touched."""
    from sklearn.datasets import load_digits
    digits = load_digits()
    x = (digits.images / 16.0).astype(np.float32)[..., None]
    y = digits.target
    order = np.random.default_rng(0).permutation(len(x))
    return x[order][:400], y[order][:400]


def test_artifact_checksum_pinned():
    assert adler32_of(DIGITS_CNN_ARTIFACT) == DIGITS_CNN_CHECKSUM


def test_pretrained_restores_and_generalizes():
    """No fit() anywhere: restored weights alone must classify real
    held-out scans far above the 10% chance floor."""
    net = digits_cnn(pretrained=True)
    x_te, y_te = _held_out()
    pred = np.argmax(np.asarray(net.output(x_te)), axis=1)
    acc = float(np.mean(pred == y_te))
    assert acc >= 0.95, f"pretrained held-out accuracy {acc:.4f}"


def test_pretrained_checksum_mismatch_raises(tmp_path):
    with pytest.raises(IOError, match="Checksum mismatch"):
        from deeplearning4j_tpu.models.pretrained import init_pretrained
        net = digits_cnn().init()
        init_pretrained(net, DIGITS_CNN_ARTIFACT, checksum=12345,
                        cache_dir=str(tmp_path))


def test_fresh_net_is_at_chance():
    """Control: an untrained digits_cnn scores near chance on the same
    batch — the accuracy above really comes from the restored weights."""
    net = digits_cnn(seed=123).init()
    x_te, y_te = _held_out()
    pred = np.argmax(np.asarray(net.output(x_te)), axis=1)
    acc = float(np.mean(pred == y_te))
    assert acc < 0.5
